"""Standing batched-inference engine with SLO telemetry.

The engine is the trainer's step loop turned inside out: instead of an
infeed pipeline pushing fixed-shape batches at a jitted step, requests of
arbitrary row count and (for MLM) arbitrary sequence length arrive at a
queue, and a batcher thread decides when a batch is worth launching:

  * admission — launch when ``serve.max_batch_size`` rows are waiting OR
    the oldest request has waited ``serve.max_wait_ms``, whichever comes
    first. Latency-throughput knob, same trade as infeed prefetch depth.
  * padding buckets — variable shapes would make XLA recompile per
    request. Sequences pad up to the next entry of ``serve.seq_buckets``
    and row counts to a power-of-two ladder over multiples of the dp
    size, so the compile budget is exactly |seq_buckets| x |row ladder|;
    each bucket's first execution is telemetered (KIND_SERVE_RECOMPILE)
    because past the warmup an unexpected recompile IS the bug.
  * placement — params are placed once via parallel/sharding.py specs
    (replicated on the dp-only serving mesh) and batches via
    core/mesh.batch_spec, the same rules the trainer compiles under.

Everything observable rides core/telemetry.py: per-request queue-wait and
latency, per-batch fill and compute time, periodic queue depth, and
p50/p90/p99 rollups from a bounded reservoir (core/metrics.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding

from distributed_tensorflow_framework_tpu.core import (
    memstats,
    telemetry,
    tracing,
)
from distributed_tensorflow_framework_tpu.core.config import ServeConfig
from distributed_tensorflow_framework_tpu.core.mesh import (
    MeshConfig,
    MeshSizeError,
    batch_spec,
    create_mesh,
)
from distributed_tensorflow_framework_tpu.core.metrics import (
    PercentileReservoir,
)
from distributed_tensorflow_framework_tpu.models import get_model
from distributed_tensorflow_framework_tpu.parallel import sharding as shd
from distributed_tensorflow_framework_tpu.serve.export import (
    Artifact,
    load_artifact,
)
from distributed_tensorflow_framework_tpu.train.step import model_inputs

log = logging.getLogger(__name__)


class ServeError(RuntimeError):
    """Base for serving-path request errors (server.py maps subclasses to
    HTTP statuses; everything else is a 500)."""


class ServeReporterError(RuntimeError):
    """The telemetry reporter thread died. Stored by the reporter and
    re-raised on :meth:`InferenceEngine.drain` — a silent telemetry
    outage must not read as a healthy engine (the async-saver contract:
    background failures surface on the owning thread)."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"serve reporter thread failed: {type(cause).__name__}: {cause}")
        self.__cause__ = cause


class ReloadError(ServeError):
    """Live weight reload rejected — tampered/unverified artifact, an
    incompatible model config, or a reload already in flight. The engine
    keeps serving the OLD weights; rejection is never an outage
    (server.py maps this to HTTP 409)."""


class OversizeRequestError(ServeError):
    """Request has more rows than ``serve.max_batch_size`` — it could
    never be admitted whole. Split it client-side or raise the knob."""


class SequenceTooLongError(ServeError):
    """Sequence exceeds the largest padding bucket (or the artifact's
    fixed length when no buckets are configured)."""


class QueueFullError(ServeError):
    """Backpressure: ``serve.queue_capacity`` requests already queued.
    The caller should retry with backoff (server.py returns 503)."""


class EngineClosedError(ServeError):
    """Submitted after drain began, or the request was still queued when
    the drain timeout expired."""


def serving_mesh(data: int = 1):
    """The dp-only serving mesh over the first ``data`` devices (-1 = all
    visible). Serving never shards params — fsdp/pipe/model stay 1 and
    parallel/sharding falls back to replication — so "mesh" here is just
    data-parallel replica count for batch throughput."""
    devices = jax.devices()
    n = len(devices) if data in (0, -1) else int(data)
    if n > len(devices):
        raise MeshSizeError({"data": n}, n, len(devices))
    return create_mesh(MeshConfig(data=n), devices=devices[:n])


def make_forward(model, mesh):
    """The jitted serve forward: apply under the serving mesh, logits
    out. Module-level (not an engine method) so graftcheck's compiled-HLO
    audits can lower/compile the REAL serving path without standing up an
    engine — the same callable the batcher thread executes."""

    def _forward(variables, inputs):
        with mesh:
            logits = model.apply(variables, *inputs, train=False)
        if isinstance(logits, dict):
            logits = logits["logits"]
        return logits

    return jax.jit(_forward)


def pick_bucket(value: int, buckets: list[int]) -> int:
    """Smallest bucket >= value (buckets ascending). ValueError past the
    last bucket — the caller owns the typed error. An empty ladder is a
    configuration error, not an IndexError."""
    if not buckets:
        raise ValueError(
            f"empty bucket ladder — no bucket can hold {value}")
    for b in buckets:
        if value <= b:
            return int(b)
    raise ValueError(f"{value} exceeds largest bucket {buckets[-1]}")


def batch_buckets(max_batch_size: int, dp: int) -> list[int]:
    """Row-count padding ladder: dp, 2*dp, 4*dp, ... capped at
    max_batch_size rounded up to a dp multiple. Every entry is divisible
    by ``dp`` so the padded batch always shards over the data axis."""
    cap = -(-int(max_batch_size) // dp) * dp
    out, b = [], dp
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


@dataclass
class _Request:
    inputs: dict[str, np.ndarray]
    rows: int
    seq_len: int  # 0 for classification
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.monotonic)
    # Incoming trace context (tracing.SpanContext) — queue-wait, batch
    # membership and compute become spans in the request's trace tree.
    trace: Any = None


class InferenceEngine:
    """Standing engine over a loaded :class:`~serve.export.Artifact`.

    Thread layout: callers (server worker threads) block in
    :meth:`predict`; one batcher thread forms and launches batches; one
    reporter thread emits periodic queue-depth/latency telemetry. The
    jitted forward itself runs on the batcher thread, so device order is
    trivially serial — no interleaved-launch hazards.
    """

    def __init__(self, artifact: Artifact, serve_cfg: ServeConfig, *,
                 mesh=None, telemetry_writer=None, trace_enabled=True):
        self.artifact = artifact
        self.cfg = serve_cfg
        self.mesh = mesh if mesh is not None else serving_mesh(serve_cfg.data)
        self._tw = telemetry_writer
        self.task = artifact.task
        self.dp = int(np.prod(
            [self.mesh.shape[a] for a in ("data", "fsdp", "expert")]))
        self.row_buckets = batch_buckets(serve_cfg.max_batch_size, self.dp)
        self.max_rows = self.row_buckets[-1]
        if self.task == "mlm":
            fixed = int(artifact.input_spec["input_ids"]["shape"][0])
            self.seq_buckets = ([int(b) for b in serve_cfg.seq_buckets]
                                or [fixed])
        else:
            self.seq_buckets = []
        self.model = get_model(
            artifact.model_config, bn_axis_name=None, mesh=self.mesh)
        # One placement at startup: replicated under the dp-only specs.
        self._variables = self._place_variables(artifact)
        self._batch_sharding = NamedSharding(self.mesh, batch_spec(self.mesh))
        self._fn = make_forward(self.model, self.mesh)
        self._compiled: set[tuple] = set()

        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._stop_reporting = threading.Event()
        self._state = "running"  # running | draining | closed
        # Staged live reload: (artifact, placed variables, future,
        # t_requested), applied by the batcher BETWEEN batches. Identity
        # label rides fleet telemetry (cli/fleet.py sets DTF_REPLICA_ID).
        self._pending_reload: tuple | None = None
        self._reloads = 0
        self._replica_label = os.environ.get("DTF_REPLICA_ID", "engine")
        # One tracer per replica process (server.py shares it): queue
        # wait, batch membership and compute become KIND_SPAN events in
        # each request's trace tree (trace.enabled gates emission).
        self.tracer = tracing.Tracer(
            telemetry_writer if trace_enabled else None,
            service=self._replica_label)
        self._t_start = time.monotonic()
        self._latency = PercentileReservoir()
        self._requests = 0
        self._rows = 0
        self._batches = 0
        self._batch_rows = 0
        self._padded_rows = 0
        self._queue_wait_ms = 0.0
        self._compute_ms = 0.0
        # HBM pressure on the serving mesh (core/memstats.py): sampled by
        # the reporter thread at report_interval_s, snapshot on /healthz.
        self._mem = memstats.MemoryMonitor(
            telemetry_writer, interval_s=serve_cfg.report_interval_s,
            source="serve", devices=list(self.mesh.devices.flat))
        self._reporter_error: ServeReporterError | None = None
        self._batcher = threading.Thread(
            target=self._batch_loop, name="dtf-serve-batcher", daemon=True)
        self._batcher.start()
        self._reporter = threading.Thread(
            target=self._report_loop, name="dtf-serve-reporter", daemon=True)
        self._reporter.start()
        log.info(
            "engine up: task=%s step=%d dp=%d row_buckets=%s seq_buckets=%s",
            self.task, artifact.step, self.dp, self.row_buckets,
            self.seq_buckets)

    def _place_variables(self, artifact: Artifact) -> dict[str, Any]:
        """Host trees -> device, replicated under the dp-only specs (the
        same placement for cold start and live reload — parity by
        construction)."""
        specs = shd.infer_param_specs(artifact.params, self.mesh)
        variables = {
            "params": shd.shard_pytree(artifact.params, specs, self.mesh)}
        if jax.tree.leaves(artifact.batch_stats):
            stat_specs = shd.infer_param_specs(
                artifact.batch_stats, self.mesh)
            variables["batch_stats"] = shd.shard_pytree(
                artifact.batch_stats, stat_specs, self.mesh)
        return variables

    # ------------------------------------------------------- validation

    def _validate(self, inputs: dict[str, Any]) -> _Request:
        spec = self.artifact.input_spec
        unknown = set(inputs) - set(spec) - {"segment_ids"}
        if unknown:
            raise ServeError(
                f"unknown input key(s) {sorted(unknown)} — this artifact "
                f"takes {sorted(spec)}")
        arrays: dict[str, np.ndarray] = {}
        for key, info in spec.items():
            row_ndim = len(info["shape"])
            if key not in inputs:
                if key == "attention_mask":
                    continue  # synthesized below
                raise ServeError(f"missing required input {key!r}")
            arr = np.asarray(inputs[key], dtype=np.dtype(info["dtype"]))
            if arr.ndim == row_ndim:  # single row without the batch dim
                arr = arr[None]
            if arr.ndim != row_ndim + 1:
                raise ServeError(
                    f"input {key!r} has shape {arr.shape}, expected "
                    f"(rows, {', '.join(map(str, info['shape']))})")
            arrays[key] = arr
        if self.task == "mlm":
            ids = arrays["input_ids"]
            rows, seq = ids.shape
            if "attention_mask" not in arrays:
                arrays["attention_mask"] = np.ones_like(ids)
            if arrays["attention_mask"].shape != ids.shape:
                raise ServeError(
                    f"attention_mask shape {arrays['attention_mask'].shape} "
                    f"!= input_ids shape {ids.shape}")
            if seq > self.seq_buckets[-1]:
                raise SequenceTooLongError(
                    f"sequence length {seq} exceeds the largest padding "
                    f"bucket {self.seq_buckets[-1]} (serve.seq_buckets="
                    f"{self.seq_buckets}) — truncate or add a bucket")
        else:
            rows = arrays["image"].shape[0]
            want = tuple(spec["image"]["shape"])
            if arrays["image"].shape[1:] != want:
                raise ServeError(
                    f"image rows have shape {arrays['image'].shape[1:]}, "
                    f"artifact expects {want}")
            seq = 0
        if rows < 1:
            raise ServeError("request has zero rows")
        if rows > self.max_rows:
            raise OversizeRequestError(
                f"request has {rows} rows but serve.max_batch_size="
                f"{self.cfg.max_batch_size} (padded cap {self.max_rows}) — "
                f"split the request or raise the knob")
        return _Request(inputs=arrays, rows=rows, seq_len=seq)

    # ------------------------------------------------------- public API

    def submit(self, inputs: dict[str, Any],
               trace: "tracing.SpanContext | None" = None) -> Future:
        """Validate + enqueue; returns a Future resolving to the per-row
        logits (np.ndarray, request rows only — padding stripped)."""
        req = self._validate(inputs)
        req.trace = trace
        with self._cond:
            if self._state != "running":
                raise EngineClosedError(
                    f"engine is {self._state} — not accepting requests")
            if len(self._queue) >= self.cfg.queue_capacity:
                raise QueueFullError(
                    f"queue at capacity ({self.cfg.queue_capacity}) — "
                    f"retry with backoff")
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    def predict(self, inputs: dict[str, Any],
                timeout: float | None = None,
                trace: "tracing.SpanContext | None" = None) -> np.ndarray:
        return self.submit(inputs, trace=trace).result(timeout)

    def request_reload(self, artifact_dir: str) -> Future:
        """Stage a live weight swap; the batcher applies it BETWEEN
        batches, so in-flight requests finish on the old weights and the
        next batch runs the new ones — zero downtime.

        Manifest verification (serve/export.load_artifact) and host->
        device placement happen HERE, on the calling thread: a tampered
        or incompatible artifact raises :class:`ReloadError` without the
        batcher ever seeing it, and the old weights keep serving. The
        jitted forward is reused unchanged (same model config is
        enforced), so reloaded responses are bitwise what a cold engine
        on the new artifact would produce.
        """
        try:
            art = load_artifact(artifact_dir)
        except (ValueError, OSError) as e:
            raise ReloadError(
                f"reload rejected, still serving step "
                f"{self.artifact.step}: {e}") from e
        if art.task != self.task:
            raise ReloadError(
                f"reload rejected: artifact task {art.task!r} != serving "
                f"task {self.task!r}")
        if art.model_config != self.artifact.model_config:
            raise ReloadError(
                "reload rejected: model config differs from the serving "
                "artifact — a fleet swaps weights, not architectures")
        if art.input_spec != self.artifact.input_spec:
            raise ReloadError(
                "reload rejected: input spec differs from the serving "
                "artifact")
        t0 = time.monotonic()
        variables = self._place_variables(art)
        fut: Future = Future()
        with self._cond:
            if self._state != "running":
                raise EngineClosedError(
                    f"engine is {self._state} — not accepting reloads")
            if self._pending_reload is not None:
                raise ReloadError(
                    "reload rejected: another reload is already staged")
            self._pending_reload = (art, variables, fut, t0)
            self._cond.notify_all()
        return fut

    def reload(self, artifact_dir: str,
               timeout: float | None = 60.0) -> dict[str, Any]:
        """Synchronous :meth:`request_reload` (server.py's POST /reload)."""
        return self.request_reload(artifact_dir).result(timeout)

    def artifact_info(self) -> dict[str, Any]:
        """Digest self-report for /healthz: mid-roll, mixed-version
        replicas each answer with the artifact they are ACTUALLY
        serving."""
        with self._cond:
            art = self.artifact
            reloads = self._reloads
        return {
            "step": art.step,
            "param_spec_digest": art.param_spec_digest,
            "content_digest": art.version_digest,
            "reloads": reloads,
        }

    def stats(self) -> dict[str, Any]:
        """Point-in-time counters for healthz (no locking beyond the
        queue peek — monotonic counters can be a batch stale)."""
        with self._cond:
            depth = len(self._queue)
        return {
            "state": self._state,
            "uptime_s": time.monotonic() - self._t_start,
            "requests": self._requests,
            "rows": self._rows,
            "batches": self._batches,
            "batch_rows": self._batch_rows,
            "padded_rows": self._padded_rows,
            "queue_depth": depth,
            "queue_wait_ms_total": self._queue_wait_ms,
            "compute_ms_total": self._compute_ms,
            "latency": self._latency.summary(),
            "row_buckets": self.row_buckets,
            "seq_buckets": self.seq_buckets,
            "compiled_buckets": sorted(str(k) for k in self._compiled),
        }

    def goodput_snapshot(self) -> dict[str, Any]:
        """Serve-side goodput: the fraction of engine uptime the batcher
        spent computing vs the request-seconds lost to queueing — the
        healthz counters load_gen diffs around a bench window."""
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        return {
            "uptime_s": elapsed,
            "compute_ms_total": self._compute_ms,
            "queue_wait_ms_total": self._queue_wait_ms,
            "compute_frac": (self._compute_ms / 1e3) / elapsed,
        }

    def memory_snapshot(self) -> dict[str, Any]:
        """Live device-memory view (no telemetry emission) for /healthz."""
        return self._mem.snapshot()

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admission, serve everything already queued, stop threads.

        Mirrors the supervisor's preemption contract: in-flight work is
        completed, not dropped. Returns True when the queue fully drained
        within ``timeout``; leftover requests (timeout expiry) fail with
        EngineClosedError rather than hanging their clients.
        """
        with self._cond:
            if self._state == "closed":
                return True
            self._state = "draining"
            self._cond.notify_all()
        self._batcher.join(timeout)
        drained = not self._batcher.is_alive()
        leftovers: list[_Request] = []
        with self._cond:
            self._state = "closed"
            leftovers, self._queue = list(self._queue), deque()
            pending, self._pending_reload = self._pending_reload, None
            self._cond.notify_all()
        for req in leftovers:
            req.future.set_exception(EngineClosedError(
                "engine drain timed out before this request was served"))
        if pending is not None:
            pending[2].set_exception(EngineClosedError(
                "engine drained before the staged reload applied"))
        self._stop_reporting.set()
        self._reporter.join(max(1.0, self.cfg.report_interval_s))
        self._emit_latency()  # final cumulative rollup — last one wins
        if self._tw:
            self._mem.sample(final=True)
        log.info("engine drained: %d requests in %d batches, %d undrained",
                 self._requests, self._batches, len(leftovers))
        with self._cond:
            reporter_error, self._reporter_error = self._reporter_error, None
        if reporter_error is not None:
            raise reporter_error
        return drained and not leftovers

    # ---------------------------------------------------------- batcher

    def _take_batch(self) -> list[_Request] | None:
        """Block until a batch is worth launching (admission rule) or the
        engine is told to finish; None means exit the loop."""
        with self._cond:
            while not self._queue:
                if self._state != "running":
                    return None
                if self._pending_reload is not None:
                    return []  # wake the loop so the swap applies now
                self._cond.wait(0.1)
            deadline = self._queue[0].t_enqueue + self.cfg.max_wait_ms / 1e3
            while (self._state == "running"
                   and sum(r.rows for r in self._queue) < self.max_rows):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch, rows = [], 0
            while self._queue and rows + self._queue[0].rows <= self.max_rows:
                req = self._queue.popleft()
                batch.append(req)
                rows += req.rows
            return batch

    def _assemble(self, batch: list[_Request]) -> tuple[dict, tuple, int]:
        """Pad requests into one fixed (row_bucket, seq_bucket) batch.

        Filler rows replicate row 0 rather than zeros — an all-zero row
        is a degenerate input some models normalize over, and replicated
        real rows keep the padded batch numerically unremarkable. Their
        outputs are sliced off before any future resolves.
        """
        rows = sum(r.rows for r in batch)
        row_bucket = pick_bucket(rows, self.row_buckets)
        if self.task == "mlm":
            seq_bucket = max(
                pick_bucket(r.seq_len, self.seq_buckets) for r in batch)
            keys = ["input_ids", "attention_mask"]
            if any("segment_ids" in r.inputs for r in batch):
                keys.append("segment_ids")
            cols = {k: [] for k in keys}
            for r in batch:
                for k in keys:
                    arr = r.inputs.get(k)
                    if arr is None:  # segment_ids absent for this request
                        arr = np.zeros((r.rows, r.seq_len), np.int32)
                    pad = seq_bucket - arr.shape[1]
                    if pad:
                        arr = np.pad(arr, ((0, 0), (0, pad)))
                    cols[k].append(arr)
            host = {k: np.concatenate(v) for k, v in cols.items()}
        else:
            seq_bucket = 0
            host = {"image": np.concatenate([r.inputs["image"]
                                             for r in batch])}
        fill = row_bucket - rows
        if fill:
            host = {k: np.concatenate([v, np.repeat(v[:1], fill, axis=0)])
                    for k, v in host.items()}
        placed = {k: jax.device_put(v, self._batch_sharding)
                  for k, v in host.items()}
        return placed, (seq_bucket, row_bucket), rows

    def _run_batch(self, batch: list[_Request]) -> None:
        with self._cond:
            depth = len(self._queue)
        t_form = time.monotonic()
        placed, key, rows = self._assemble(batch)
        inputs = model_inputs(self.task, placed)
        first_use = key not in self._compiled
        t0 = time.monotonic()
        logits = self._fn(self._variables, inputs)
        logits = np.asarray(jax.block_until_ready(logits))
        t_done = time.monotonic()
        compute_ms = (t_done - t0) * 1e3
        if first_use:
            self._compiled.add(key)
            label = (f"rows{key[1]}" if self.task != "mlm"
                     else f"seq{key[0]}xrows{key[1]}")
            if self._tw:
                self._tw.emit(
                    telemetry.KIND_SERVE_RECOMPILE,
                    metrics={"compile_ms": compute_ms}, bucket=label)
            log.info("compiled bucket %s in %.0f ms (%d/%d buckets warm)",
                     label, compute_ms, len(self._compiled),
                     len(self.row_buckets) * max(1, len(self.seq_buckets)))
        row_bucket = key[1]
        self._batches += 1
        self._batch_rows += rows
        self._padded_rows += row_bucket
        self._compute_ms += compute_ms
        if self._tw:
            self._tw.emit(
                telemetry.KIND_SERVE_BATCH,
                metrics={"rows": rows, "padded_rows": row_bucket,
                         "compute_ms": compute_ms, "queue_depth": depth})
        offset = 0
        for req in batch:
            out = logits[offset:offset + req.rows]
            if self.task == "mlm":  # strip the seq padding too
                out = out[:, :req.seq_len]
            offset += req.rows
            wait_ms = (t_form - req.t_enqueue) * 1e3
            latency_ms = (time.monotonic() - req.t_enqueue) * 1e3
            self._requests += 1
            self._rows += req.rows
            self._queue_wait_ms += wait_ms
            self._latency.add(latency_ms)
            if self._tw:
                self._tw.emit(
                    telemetry.KIND_SERVE_REQUEST,
                    metrics={"rows": req.rows, "queue_wait_ms": wait_ms,
                             "latency_ms": latency_ms})
            if req.trace is not None:
                # Backfilled from the timestamps already measured above:
                # queue wait, this request's membership in the padded
                # batch, and the batch's device compute.
                self.tracer.emit_span(
                    "engine.queue", req.trace,
                    start_mono=req.t_enqueue, end_mono=t_form,
                    rows=req.rows)
                bev = self.tracer.emit_span(
                    "engine.batch", req.trace,
                    start_mono=t_form, end_mono=t_done,
                    batch=self._batches, rows=rows,
                    padded_rows=row_bucket, queue_depth=depth)
                bspan = (bev.get("extra") or {}).get("span")
                self.tracer.emit_span(
                    "engine.compute",
                    tracing.SpanContext(req.trace.trace_id, bspan or "")
                    if req.trace.trace_id else req.trace,
                    start_mono=t0, end_mono=t_done,
                    first_use=first_use)
            req.future.set_result(out)

    def _apply_pending_reload(self) -> None:
        """Batcher-thread half of the reload: swap the verified, already
        placed trees in one locked assignment between batches."""
        with self._cond:
            pending, self._pending_reload = self._pending_reload, None
        if pending is None:
            return
        art, variables, fut, t0 = pending
        old = self.artifact
        with self._cond:
            self.artifact = art
            self._variables = variables
            self._reloads += 1
        reload_ms = (time.monotonic() - t0) * 1e3
        result = {
            "from_step": old.step, "to_step": art.step,
            "from_digest": old.version_digest,
            "to_digest": art.version_digest,
            "reload_ms": reload_ms,
        }
        if self._tw:
            self._tw.emit(
                telemetry.KIND_SERVE_RELOAD,
                metrics={"reload_ms": reload_ms},
                replica=self._replica_label, ok=True,
                from_digest=old.version_digest,
                to_digest=art.version_digest,
                from_step=old.step, to_step=art.step)
        log.info("live reload: step %d -> %d, digest %s -> %s (%.0f ms)",
                 old.step, art.step, old.version_digest[:8],
                 art.version_digest[:8], reload_ms)
        fut.set_result(result)

    def _batch_loop(self) -> None:
        while True:
            self._apply_pending_reload()
            batch = self._take_batch()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — engine must outlive a bad batch
                log.exception("batch of %d request(s) failed", len(batch))
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    # --------------------------------------------------------- reporter

    def _emit_latency(self) -> None:
        if not self._tw or not self._latency.count:
            return
        s = self._latency.summary()
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        self._tw.emit(
            telemetry.KIND_SERVE_LATENCY,
            metrics={"p50_ms": s["p50"], "p90_ms": s["p90"],
                     "p99_ms": s["p99"], "count": s["count"]},
            throughput={"requests_per_sec": self._requests / elapsed,
                        "rows_per_sec": self._rows / elapsed})

    def _report_loop(self) -> None:
        try:
            while not self._stop_reporting.wait(self.cfg.report_interval_s):
                with self._cond:
                    depth = len(self._queue)
                if self._tw:
                    self._tw.emit(telemetry.KIND_SERVE_QUEUE,
                                  metrics={"queue_depth": depth})
                    self._mem.sample()
                self._emit_latency()
        except BaseException as e:  # surface on drain(), never just stderr
            log.error("serve reporter thread failed", exc_info=True)
            with self._cond:
                if self._reporter_error is None:
                    self._reporter_error = ServeReporterError(e)
