"""Freeze a trained checkpoint into a serving artifact.

An artifact is a directory holding exactly what inference needs and
nothing the trainer needs back:

    <artifact>/
      artifact.json   — schema, model config, task, source step, input
                        spec, sha256 digest of the param tree
      params/         — orbax StandardSave of {"params", "batch_stats"}
      manifest.json   — ckpt/manifest.py integrity commit record over the
                        whole directory (an artifact without one is
                        uncommitted, same contract as training steps)

Export goes THROUGH the existing restore path (ckpt/checkpoint.py): the
checkpoint's integrity manifest is verified, quarantine/fallback apply,
and the mesh-topology gate (ckpt/reshard.py) runs — a multi-host training
mesh restores onto the 1-device/dp-only serving mesh only when
``serve.allow_reshard`` is set, otherwise the typed MeshTopologyError
names that knob. EMA params are frozen when present (``serve.use_ema``),
matching what the trainer's eval would have scored.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import json
import logging
import os
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.ckpt import reshard
from distributed_tensorflow_framework_tpu.ckpt.checkpoint import (
    CheckpointManager,
)
from distributed_tensorflow_framework_tpu.core.config import (
    ExperimentConfig,
    ModelConfig,
    _build,
)

log = logging.getLogger(__name__)

ARTIFACT_SCHEMA = "dtf-serve-artifact/1"
ARTIFACT_JSON = "artifact.json"
_PARAMS_DIR = "params"

# Hint appended to MeshTopologyError on the export path: the operator is
# holding the serve config block, not the training checkpoint block.
RESHARD_HINT = (
    "Serving export: set serve.allow_reshard=true (cli/export.py --set "
    "serve.allow_reshard=true) to restore this training-mesh checkpoint "
    "onto the dp-only serving mesh."
)


def param_tree_digest(tree: Any) -> str:
    """sha256 over every leaf's (tree path, shape, dtype) — the artifact's
    recorded param spec digest. Checked again at load so a tree that
    deserialized into a different structure/shape fails by name, not as a
    shape error deep inside the first forward pass."""
    h = hashlib.sha256()
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        shape = tuple(np.shape(leaf))
        dtype = np.asarray(leaf).dtype if np.ndim(leaf) == 0 else leaf.dtype
        h.update(
            f"{jax.tree_util.keystr(path)}={shape}:{dtype}\n".encode())
    return h.hexdigest()


def artifact_content_digest(manifest: dict) -> str:
    """sha256 over the manifest's (file, sha256) records — changes
    whenever any payload byte changes, unlike param_tree_digest which
    hashes only the spec (path/shape/dtype)."""
    h = hashlib.sha256()
    for rel in sorted(manifest.get("files") or {}):
        h.update(f"{rel}:{manifest['files'][rel].get('sha256')}\n".encode())
    return h.hexdigest()


def input_spec_for(config: ExperimentConfig, task: str) -> dict[str, Any]:
    """Per-ROW request spec recorded in the artifact: what a client must
    send per example. The server's healthz exposes it so the load
    generator can synthesize valid traffic without sharing config."""
    if task == "mlm":
        seq = int(config.data.seq_len or config.model.max_seq_len)
        return {
            "input_ids": {"shape": [seq], "dtype": "int32"},
            "attention_mask": {"shape": [seq], "dtype": "int32"},
        }
    return {
        "image": {
            "shape": [int(config.data.image_size),
                      int(config.data.image_size),
                      int(config.data.channels)],
            "dtype": "float32",
        },
    }


def _sample_batch(config: ExperimentConfig, task: str, rows: int) -> dict:
    """Shape-only host batch for building the restore template (the init
    only traces shapes; no dataset construction needed for export)."""
    if task == "mlm":
        seq = int(config.data.seq_len or config.model.max_seq_len)
        return {
            "input_ids": np.zeros((rows, seq), np.int32),
            "targets": np.full((rows, seq), -1, np.int32),
            "attention_mask": np.ones((rows, seq), np.int32),
        }
    size, ch = int(config.data.image_size), int(config.data.channels)
    return {
        "image": np.zeros((rows, size, size, ch), np.float32),
        "label": np.zeros((rows,), np.int32),
    }


@dataclasses.dataclass
class Artifact:
    """A loaded serving artifact: host param trees + the recorded meta."""

    model_config: ModelConfig
    task: str
    params: Any
    batch_stats: Any
    step: int
    param_spec_digest: str
    input_spec: dict[str, Any]
    meta: dict[str, Any]
    # WEIGHT-bearing identity from the integrity manifest (see
    # artifact_content_digest) — "" for artifacts loaded without one.
    content_digest: str = ""

    @property
    def vocab_size(self) -> int:
        return int(self.meta.get("vocab_size") or
                   self.model_config.vocab_size)

    @property
    def version_digest(self) -> str:
        """The digest that identifies THIS artifact's weights: the
        content digest when available, else the spec digest. The fleet's
        rolling reload keys mixed-version /healthz visibility on it —
        two same-architecture exports share a param_spec_digest, so the
        spec digest alone cannot tell old weights from new."""
        return self.content_digest or self.param_spec_digest


def save_artifact(
    output_dir: str,
    *,
    model_config: ModelConfig,
    task: str,
    params: Any,
    batch_stats: Any,
    step: int,
    input_spec: dict[str, Any],
    source: dict[str, Any] | None = None,
    vocab_size: int | None = None,
) -> str:
    """Low-level artifact writer (export_checkpoint's back half; tests use
    it directly to build artifacts from initialized params). Refuses a
    non-empty target — an artifact is immutable once committed."""
    out = os.path.abspath(output_dir)
    if os.path.isdir(out) and os.listdir(out):
        raise ValueError(
            f"artifact directory {out} already exists and is not empty — "
            f"artifacts are immutable; export to a fresh directory"
        )
    os.makedirs(out, exist_ok=True)
    host_params = jax.device_get(params)
    host_stats = jax.device_get(batch_stats)
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    try:
        ckptr.save(
            os.path.join(out, _PARAMS_DIR),
            args=ocp.args.StandardSave(
                {"params": host_params, "batch_stats": host_stats}),
        )
    finally:
        ckptr.close()
    meta = {
        "schema": ARTIFACT_SCHEMA,
        "task": task,
        "step": int(step),
        "model": dataclasses.asdict(model_config),
        "param_spec_digest": param_tree_digest(host_params),
        "input_spec": input_spec,
        "vocab_size": int(vocab_size or model_config.vocab_size),
        "exported_t": time.time(),
        "source": source or {},
    }
    path = os.path.join(out, ARTIFACT_JSON)
    with open(path, "w") as fh:
        json.dump(meta, fh, indent=2, sort_keys=True)
        fh.write("\n")
    # The integrity commit record: hash every payload file (ckpt/manifest
    # discipline — an artifact without a manifest is uncommitted).
    mf.write_manifest(out, step)
    log.info("exported serving artifact to %s (step %d, %s)",
             out, step, task)
    return out


def export_checkpoint(
    config: ExperimentConfig,
    output_dir: str,
    *,
    step: int | None = None,
) -> str:
    """Export ``config.checkpoint.directory``'s checkpoint into a frozen
    serving artifact at ``output_dir``.

    Restores onto the serving mesh (``serve.data`` devices, dp-only) via
    the full integrity + topology-gated restore path; a training-mesh
    checkpoint requires ``serve.allow_reshard`` or raises the typed
    MeshTopologyError with the serve-side knob named.
    """
    from distributed_tensorflow_framework_tpu.serve.engine import (
        serving_mesh,
    )
    from distributed_tensorflow_framework_tpu.train.step import (
        StepBuilder,
        task_for_model,
    )

    if config.model.pipeline_stages > 1:
        raise ValueError(
            "export of pipelined models (model.pipeline_stages>1) is not "
            "supported yet — multi-stage serving is the 1F1B slot-table "
            "follow-up (ROADMAP item 3); export from a stage-merged "
            "checkpoint instead"
        )
    if not config.checkpoint.directory:
        raise ValueError("checkpoint.directory must name the trained run "
                         "to export")
    # The template builder runs on the SERVING mesh with serving-only
    # semantics: jit mode, no quantized-collective residual (a stored
    # residual is dropped by the restore reconciliation — serving never
    # steps the optimizer).
    cfg = copy.deepcopy(config)
    cfg.train.spmd_mode = "jit"
    cfg.train.grad_allreduce_dtype = ""
    cfg.parallel.collective_dtype = ""
    cfg.optimizer.shard_opt_state = False
    mesh = serving_mesh(cfg.serve.data)
    task = task_for_model(cfg.model.name)
    builder = StepBuilder(cfg, mesh)
    rows = int(mesh.shape["data"])
    template = builder.init_state(0, _sample_batch(cfg, task, rows))
    ckpt_cfg = dataclasses.replace(
        cfg.checkpoint,
        async_save=False,
        allow_reshard=cfg.serve.allow_reshard,
    )
    manager = CheckpointManager(ckpt_cfg, mesh=mesh, process_count=1)
    try:
        try:
            state = manager.restore(template, step=step)
        except reshard.MeshTopologyError as e:
            raise reshard.MeshTopologyError(
                e.saved_axes, e.requested_axes, directory=e.directory,
                step=e.step, hint=RESHARD_HINT,
            ) from None
    finally:
        manager.close()
    if state is None:
        raise ValueError(
            f"no committed checkpoint to export in "
            f"{config.checkpoint.directory}"
        )
    use_ema = bool(cfg.serve.use_ema and jax.tree.leaves(state.ema_params))
    params = state.ema_params if use_ema else state.params
    restored_step = int(jax.device_get(state.step))
    return save_artifact(
        output_dir,
        model_config=cfg.model,
        task=task,
        params=params,
        batch_stats=state.batch_stats,
        step=restored_step,
        input_spec=input_spec_for(cfg, task),
        vocab_size=(cfg.data.vocab_size if task == "mlm" else None),
        source={
            "checkpoint_dir": os.path.abspath(config.checkpoint.directory),
            "experiment": config.name,
            "used_ema": use_ema,
            "serve_mesh": {a: int(s) for a, s in mesh.shape.items()},
            "sharding_spec_digest": reshard.spec_digest(state),
        },
    )


def load_artifact(artifact_dir: str, *, verify: bool = True) -> Artifact:
    """Load + integrity-check a committed artifact into host trees."""
    out = os.path.abspath(artifact_dir)
    meta_path = os.path.join(out, ARTIFACT_JSON)
    if not os.path.isfile(meta_path):
        raise ValueError(
            f"{out} is not a serving artifact (no {ARTIFACT_JSON}) — "
            f"export one with cli/export.py"
        )
    with open(meta_path) as fh:
        meta = json.load(fh)
    if meta.get("schema") != ARTIFACT_SCHEMA:
        raise ValueError(
            f"artifact schema {meta.get('schema')!r} != {ARTIFACT_SCHEMA!r}"
            f" — re-export with this version"
        )
    manifest = mf.read_manifest(out)
    if manifest is None:
        raise ValueError(
            f"artifact {out} has no integrity manifest (export did not "
            f"complete) — re-export it"
        )
    if verify:
        errors = mf.verify_step_dir(out, manifest)
        if errors:
            raise ValueError(
                f"artifact {out} failed integrity verification: "
                + "; ".join(errors[:5])
            )
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    try:
        tree = ckptr.restore(os.path.join(out, _PARAMS_DIR))
    finally:
        ckptr.close()
    params = tree["params"]
    digest = param_tree_digest(params)
    if digest != meta["param_spec_digest"]:
        raise ValueError(
            f"artifact {out} param tree digest mismatch: recorded "
            f"{meta['param_spec_digest'][:12]}…, loaded {digest[:12]}… — "
            f"the stored tree does not match what was exported"
        )
    return Artifact(
        model_config=_build(ModelConfig, meta["model"]),
        task=meta["task"],
        params=params,
        batch_stats=tree.get("batch_stats", {}),
        step=int(meta["step"]),
        param_spec_digest=digest,
        input_spec=meta["input_spec"],
        meta=meta,
        content_digest=artifact_content_digest(manifest),
    )
