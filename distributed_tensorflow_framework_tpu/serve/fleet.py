"""Health-aware fleet router: N replica engines behind one front door.

ROADMAP item 3's stance shift: PR 8's serve/ stack is one standing
engine, so one process death drops every in-flight request. The fleet
treats replica failure as the NORMAL case (the TensorFlow system paper's
worker-failure posture, PAPERS.md): a stdlib ThreadingHTTPServer proxies
``POST /predict`` across N ``cli/serve.py`` subprocesses and keeps the
client whole while replicas die, stall, or swap weights underneath it.

The moving parts, each mirroring an existing training-side contract:

  * routing — least-loaded admitted replica, scored by the live
    ``queue_depth`` each replica already publishes on ``/healthz`` plus
    the router's own in-flight count (the same queue-pressure signal
    the engine's admission bound uses).
  * hedged retries — every proxied request carries an end-to-end
    deadline (``serve.fleet_deadline_s``) and a per-attempt cap
    (``serve.fleet_attempt_timeout_s``); an attempt that has not
    answered inside the cap is abandoned and re-issued on a DIFFERENT
    replica with doubling backoff, at most ``serve.fleet_retries``
    times. Only idempotent ``POST /predict`` is retried; 4xx answers
    are deterministic and returned as-is.
  * circuit breaker — ``serve.fleet_eject_failures`` consecutive
    failures or a ``/healthz`` older than ``serve.fleet_healthz_stale_s``
    ejects a replica from routing; the background prober keeps probing
    it and readmits on the first healthy answer.
  * supervision — a dead subprocess is restarted through the training
    supervisor's machinery (core/supervision.py): capped-exponential
    ``backoff_seconds`` between attempts and a ``CrashLoopBreaker``
    keyed on (rc, requests served, artifact step) so a replica that
    dies identically twice without serving anything is declared a
    deterministic crash and left down instead of burning restarts.
  * shedding — when every admitted replica is saturated the router
    answers 503 + ``Retry-After`` (``serve.fleet_shed_retry_after_s``)
    instead of queueing unboundedly; backpressure is the client's
    signal, not a hidden queue.
  * rolling reload — ``POST /reload {"artifact_dir"}`` walks the fleet
    one replica at a time: drain (stop routing, wait out in-flight),
    reload (the engine's between-batches swap, manifest-verified),
    probe (healthz must report the NEW digest), readmit. A rejected
    reload aborts the roll with every replica still serving weights
    that passed verification. Optional ``count`` / ``digest`` body
    fields scope the roll to a subset, which is how one model version
    rolls while another keeps serving (cross-model multiplexing: the
    traffic split between artifacts IS the replica allocation, and
    ``X-DTF-Model: <digest prefix>`` pins a request to one of them).
  * autoscaling — with ``serve.fleet_autoscale`` the prober tick feeds
    a fleet pressure snapshot to serve/autoscale.py's hysteresis policy
    and actuates its verdicts: scale-up spawns ONE replica through the
    same supervised launch path restarts use (so the crash-loop breaker
    gates both and a broken artifact can't trigger infinite spawn),
    scale-down retires the newest admitted replica through the same
    drain path rolling reloads use, bounded by ``fleet_min_replicas``/
    ``fleet_max_replicas`` and rate-limited by
    ``fleet_scale_cooldown_s``.
  * multi-tenant QoS — ``X-DTF-Tenant`` names a tenant whose class
    (``high``/``default``/``batch``) decides how much per-replica queue
    headroom it must leave free (``serve.tenant_priority_reserve``), so
    under saturation batch sheds strictly before default before high;
    per-tenant token buckets (``serve.tenant_quota_rps``) answer 429 +
    Retry-After BEFORE a replica slot is claimed.

Chaos drills ride core/faults.py: ``kill_replica`` / ``stall_replica``
/ ``spike`` / ``tenant_stampede`` fire at the prober's ``fleet_chaos``
point, ``corrupt_reload`` at ``fleet_reload``. Everything observable
rides core/telemetry.py (KIND_SERVE_ROUTE / KIND_SERVE_EJECT /
KIND_SERVE_RELOAD / KIND_SCALE / KIND_ADMISSION).

Stdlib-only by design — the router imports no jax and can front any
HTTP replica, which is also what keeps its tests in tier 1.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from distributed_tensorflow_framework_tpu.core import (
    faults,
    supervision,
    telemetry,
    tracing,
)
from distributed_tensorflow_framework_tpu.core.config import ServeConfig
from distributed_tensorflow_framework_tpu.serve import autoscale

log = logging.getLogger(__name__)

_MAX_BODY = 64 * 1024 * 1024  # refuse absurd request bodies outright

# Client-facing QoS / multiplexing headers (docs/SERVING.md).
TENANT_HEADER = "X-DTF-Tenant"
MODEL_HEADER = "X-DTF-Model"
# Decode-session affinity: a generation session's KV pages live on ONE
# replica, so every /generate carrying the same X-DTF-Session value must
# land there (docs/SERVING.md "Autoregressive decode").
SESSION_HEADER = "X-DTF-Session"


class FleetError(RuntimeError):
    """Base for fleet-router failures (typed so the CLI can map them to
    exit codes and the handler to HTTP statuses)."""


class ReplicaLaunchError(FleetError):
    """The replica launcher failed to produce a live subprocess."""


class FleetProberError(FleetError):
    """The background prober thread died. Stored by the prober and
    re-raised when the router exits — a silent prober outage would stop
    ejection/readmission/restart while routing blindly continues."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"fleet prober thread failed: {type(cause).__name__}: {cause}")
        self.__cause__ = cause


class FleetDrainError(FleetError):
    """The signal-triggered drain thread failed. Stored and re-raised
    from :meth:`FleetRouter.serve_forever` so the failure surfaces on
    the owning thread instead of a daemon thread's stderr."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"fleet drain failed: {type(cause).__name__}: {cause}")
        self.__cause__ = cause


@dataclass
class Replica:
    """One fronted engine and its circuit-breaker bookkeeping. All
    mutable fields are written under the router lock (or by the prober
    before the router starts)."""

    index: int
    url: str = ""
    proc: Any = None  # subprocess.Popen when launcher-managed
    endpoint_path: str = ""  # resolved lazily after (re)launch
    state: str = "ejected"  # admitted | ejected | draining | dead | retired
    give_up: bool = False  # crash-loop verdict or restart budget spent
    # Scale-down lifecycle: retiring = drain in progress (claim skips
    # it, supervision must NOT restart it); retired = drained + gone.
    retiring: bool = False
    retire_deadline: float = 0.0
    inflight: int = 0
    routed: int = 0
    consecutive_failures: int = 0
    restarts: int = 0
    next_restart_t: float = 0.0
    stalled_until: float = 0.0
    last_health: dict = field(default_factory=dict)
    last_health_t: float = 0.0
    breaker: supervision.CrashLoopBreaker = field(
        default_factory=lambda: supervision.CrashLoopBreaker(threshold=2))

    @property
    def label(self) -> str:
        return f"r{self.index}"

    def artifact_info(self) -> dict:
        return dict(self.last_health.get("artifact") or {})


def _http_json(url: str, *, data: bytes | None = None,
               timeout: float = 5.0,
               headers: dict[str, str] | None = None) -> tuple[int, dict]:
    """One HTTP exchange; transport failures (refused, reset, timed out)
    come back as status 0 so callers treat them like any 5xx. ``headers``
    adds to the defaults (trace propagation rides X-DTF-Trace here)."""
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except (ValueError, OSError):
            return e.code, {}
    except (urllib.error.URLError, OSError, ValueError) as e:
        return 0, {"error": f"{type(e).__name__}: {e}"}


def read_endpoint(path: str) -> str:
    """The replica URL from a cli/serve.py endpoint.json, or '' while
    the file is absent/torn (the replica is still booting)."""
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return ""
    url = record.get("url") if isinstance(record, dict) else None
    return url if isinstance(url, str) else ""


class FleetRouter:
    """The health-aware router over registered replicas.

    Thread layout: ThreadingHTTPServer worker threads block in
    :meth:`_proxy_predict`; one prober thread owns the replica
    lifecycle (health polls, eject/readmit, chaos faults, restarts);
    the rolling reload runs on the POST /reload handler thread. Shared
    counters and every Replica field are guarded by ``self._lock``.
    """

    def __init__(self, serve_cfg: ServeConfig, *, telemetry_writer=None,
                 launcher: Callable[[int], tuple[Any, str]] | None = None,
                 trace_enabled: bool = True,
                 flight_recorder: "tracing.FlightRecorder | None" = None):
        self.cfg = serve_cfg
        self._tw = telemetry_writer
        # Router-side tracing: a client's X-DTF-Trace becomes a
        # router.request span with one fleet.attempt child per hedged
        # try; each attempt's context rides the header to the replica.
        self.tracer = tracing.Tracer(
            telemetry_writer if trace_enabled else None, service="router")
        # Flight recorder (cli/fleet.py attaches it to the writer): the
        # prober dumps it when it observes a replica die, so the fault's
        # causal neighborhood survives even a torn replica JSONL.
        self.flightrec = flight_recorder
        if flight_recorder is not None and flight_recorder.tracer is None:
            flight_recorder.tracer = self.tracer
        # launcher(index) -> (Popen, endpoint_json_path). It must spawn
        # WITHOUT blocking on readiness — the prober resolves the
        # endpoint and readmits once /healthz answers, so one booting
        # replica never starves the health checks of the others.
        self._launcher = launcher
        self._lock = threading.Lock()
        self._replicas: list[Replica] = []
        self._draining = threading.Event()
        self._done = threading.Event()
        self._stop = threading.Event()
        self._serving = threading.Event()
        self._drain_error: FleetDrainError | None = None
        self._prober_error: FleetProberError | None = None
        self._rolling = False
        self._tick_count = 0
        self._chaos_armed = False
        self._chaos_tick = 0
        self._requests = 0
        self._retries_total = 0
        self._shed = 0
        self._deadline_exceeded = 0
        self._reload_rolls = 0
        # Decode-session affinity map (session id → replica index),
        # written under the router lock. Entries are dropped when the
        # pinned replica leaves the routable set for good (dead /
        # retired / ejected) so a later request repins cleanly.
        self._sessions: dict[str, int] = {}
        self._generate_streams = 0
        self._affinity_misses = 0
        # Multi-tenant QoS: per-tenant router ledger (routed / shed /
        # quota_rejected, exposed on /healthz) + the token buckets.
        self._tenants: dict[str, dict] = {}
        self._quotas = autoscale.TenantQuotas(
            serve_cfg.tenant_quota_rps, serve_cfg.tenant_quota_burst)
        # Chaos windows (core/faults.py spike / tenant_stampede): while
        # open they inject synthetic per-replica load — spike into the
        # autoscaler's pressure signal only, stampede into the claim
        # path too (saturating every unreserved queue slot).
        self._spike_until = 0.0
        self._spike_load = 0.0
        self._stampede_until = 0.0
        # Autoscaler (serve/autoscale.py): policy object + action ledger.
        self._autoscaler = (
            autoscale.Autoscaler(
                min_replicas=serve_cfg.fleet_min_replicas,
                max_replicas=serve_cfg.fleet_max_replicas,
                up_threshold=serve_cfg.fleet_scale_up_threshold,
                down_threshold=serve_cfg.fleet_scale_down_threshold,
                cooldown_s=serve_cfg.fleet_scale_cooldown_s,
            ) if serve_cfg.fleet_autoscale else None)
        self._scale_ups = 0
        self._scale_downs = 0
        self._shed_seen = 0  # shed counter at the last autoscale look
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through logging
                log.debug("%s %s", self.address_string(), fmt % args)

            def _reply(self, status: int, payload: dict,
                       headers: dict | None = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for key, value in (headers or {}).items():
                    self.send_header(key, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/healthz":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                outer.handle_healthz(self)

            def do_POST(self):
                if self.path == "/predict":
                    outer.handle_predict(self)
                elif self.path == "/generate":
                    outer.handle_generate(self)
                elif self.path == "/reload":
                    outer.handle_reload(self)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

        class Server(ThreadingHTTPServer):
            # Same accept-backlog sizing rationale as serve/server.py.
            request_queue_size = max(128, serve_cfg.queue_capacity)

        self.httpd = Server((serve_cfg.host, serve_cfg.port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._prober = threading.Thread(
            target=self._probe_loop, name="dtf-fleet-prober", daemon=True)

    # ------------------------------------------------------ registration

    def add_replica(self, *, url: str = "", proc: Any = None,
                    endpoint_path: str = "",
                    admitted: bool = False) -> Replica:
        """Register one replica. With ``admitted`` (externally managed,
        already known healthy — tests) it routes immediately; otherwise
        it starts ejected and earns admission from the prober."""
        with self._lock:
            rep = Replica(index=len(self._replicas), url=url, proc=proc,
                          endpoint_path=endpoint_path)
            if admitted:
                rep.state = "admitted"
                rep.last_health_t = time.monotonic()
            self._replicas.append(rep)
        return rep

    def spawn_replicas(self, count: int | None = None) -> None:
        """Launch ``count`` (default ``serve.fleet_replicas``) replicas
        through the launcher; they join the routable set as the prober
        sees them answer /healthz."""
        if self._launcher is None:
            raise ReplicaLaunchError(
                "no launcher configured — register replicas via "
                "add_replica(url=...) instead")
        n = int(count if count is not None else self.cfg.fleet_replicas)
        for _ in range(n):
            with self._lock:
                index = len(self._replicas)
            try:
                proc, endpoint_path = self._launcher(index)
            except Exception as e:
                raise ReplicaLaunchError(
                    f"replica r{index} failed to launch: {e}") from e
            self.add_replica(proc=proc, endpoint_path=endpoint_path)

    def start(self) -> None:
        """Start the background prober (idempotent-unsafe: call once)."""
        self._prober.start()

    def wait_ready(self, *, min_replicas: int | None = None,
                   timeout: float = 180.0) -> bool:
        """Block until ``min_replicas`` (default: all registered) are
        admitted, or the timeout passes. False = not ready (callers
        decide whether a partial fleet is acceptable)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                want = (len(self._replicas) if min_replicas is None
                        else int(min_replicas))
                up = sum(1 for r in self._replicas if r.state == "admitted")
            if up >= want:
                return True
            time.sleep(0.1)
        return False

    # ----------------------------------------------------------- routing

    def _stampede_load(self, now: float) -> int:
        """Synthetic per-replica load while a tenant_stampede window is
        open: batch-class traffic has filled every queue slot except the
        priority reserve, so only classes with reserved headroom route.
        Caller holds the lock."""
        if now >= self._stampede_until:
            return 0
        return max(0, self.cfg.queue_capacity
                   - max(1, self.cfg.tenant_priority_reserve))

    def _claim_replica(self, exclude: set[int], *, priority: int = 0,
                       digest: str | None = None) -> Replica | None:
        """Pick the least-loaded admitted replica (live healthz queue
        depth + router in-flight) and claim an in-flight slot on it.
        None = nothing routable (all ejected, excluded, stalled, or
        saturated for this priority class).

        QoS: a class ``priority`` steps below high may only claim a
        replica whose load leaves ``priority * tenant_priority_reserve``
        queue slots free — under exact-capacity load that sheds batch
        strictly before default before high. ``digest`` pins the claim
        to replicas serving a matching artifact (cross-model
        multiplexing via the X-DTF-Model header)."""
        now = time.monotonic()
        allowed = (self.cfg.queue_capacity
                   - priority * self.cfg.tenant_priority_reserve)
        with self._lock:
            synthetic = self._stampede_load(now)
            best: Replica | None = None
            best_key: tuple | None = None
            for rep in self._replicas:
                if rep.state != "admitted" or rep.index in exclude:
                    continue
                if rep.stalled_until > now:
                    continue  # known-wedged: don't feed it requests
                if digest:
                    rep_digest = str((rep.last_health.get("artifact") or {})
                                     .get("content_digest") or "")
                    if not rep_digest.startswith(digest):
                        continue  # serving a different model
                engine = rep.last_health.get("engine") or {}
                try:
                    depth = int(engine.get("queue_depth") or 0)
                except (TypeError, ValueError):
                    depth = 0
                load = depth + rep.inflight + synthetic
                if load >= allowed:
                    continue  # saturated for this class: shed, not queue
                # Tie-break equal load by total routed so sequential
                # traffic still round-robins instead of pinning r0.
                key = (load, rep.routed)
                if best is None or key < best_key:
                    best, best_key = rep, key
            if best is not None:
                best.inflight += 1
            return best

    def _release_replica(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    def _record_success(self, rep: Replica) -> None:
        with self._lock:
            rep.consecutive_failures = 0
            rep.routed += 1

    def _record_failure(self, rep: Replica, reason: str) -> None:
        with self._lock:
            rep.consecutive_failures += 1
            eject = (rep.state == "admitted" and rep.consecutive_failures
                     >= self.cfg.fleet_eject_failures)
            if eject:
                rep.state = "ejected"
        if eject:
            self._emit_eject(rep, action="eject", reason=reason)

    def _emit_eject(self, rep: Replica, *, action: str, reason: str,
                    **extra: Any) -> None:
        log.warning("fleet: %s %s (%s)", action, rep.label, reason)
        if self._tw:
            self._tw.emit(telemetry.KIND_SERVE_EJECT, replica=rep.label,
                          action=action, reason=reason, **extra)

    def _proxy_predict(
            self, body: bytes,
            client_ctx: "tracing.SpanContext | None" = None,
            *, priority: int = 0, tenant: str | None = None,
            model_digest: str | None = None,
    ) -> tuple[int, dict, Replica | None, dict]:
        """Deadline-bounded, hedged proxying of one idempotent /predict.

        Each attempt gets ``min(remaining deadline, attempt timeout)``;
        a failed or abandoned attempt retries on a DIFFERENT replica
        after a doubling backoff. When every admitted replica has been
        tried, reuse beats refusal (one survivor still serves a
        3-replica fleet with two down).

        With a client trace context, the whole exchange becomes one
        ``router.request`` span with a ``fleet.attempt`` child per try
        (and ``fleet.backoff`` children for the sleeps between); each
        attempt's own context rides ``X-DTF-Trace`` to the replica, so a
        hedged retry yields ONE tree: failed attempt and winning attempt
        side by side under the same root."""
        cfg = self.cfg
        tr = self.tracer
        root = None
        if client_ctx is not None:
            tr.adopt(client_ctx)
            root = tr.start("router.request", client_ctx)
        t0 = time.monotonic()
        deadline = t0 + cfg.fleet_deadline_s
        backoff = cfg.fleet_retry_backoff_ms / 1e3
        tried: set[int] = set()
        attempts = 0
        shed = deadline_exceeded = False
        status, payload = 0, {"error": "no admitted replica"}
        served_by: Replica | None = None
        while attempts <= cfg.fleet_retries:
            rep = self._claim_replica(
                tried, priority=priority, digest=model_digest)
            if rep is None and tried:
                rep = self._claim_replica(
                    set(), priority=priority, digest=model_digest)
            if rep is None:
                shed = True
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._release_replica(rep)
                deadline_exceeded = True
                break
            attempts += 1
            aspan = (tr.start("fleet.attempt", root, replica=rep.label,
                              attempt=attempts)
                     if root is not None else None)
            headers = ({tracing.TRACE_HEADER: aspan.context().encode()}
                       if aspan is not None else None)
            try:
                status, payload = _http_json(
                    rep.url + "/predict", data=body,
                    timeout=min(remaining, cfg.fleet_attempt_timeout_s),
                    headers=headers)
            finally:
                self._release_replica(rep)
            if status == 200:
                if aspan is not None:
                    aspan.end(status="ok", http_status=status)
                served_by = rep
                self._record_success(rep)
                break
            if 400 <= status < 500:
                # Deterministic request error — the replica is fine and
                # another replica would answer identically.
                if aspan is not None:
                    aspan.end(status=f"http_{status}", http_status=status)
                served_by = rep
                break
            if aspan is not None:
                aspan.end(status="error", http_status=status,
                          error=str(payload.get("error") or "")[:200])
            self._record_failure(rep, f"predict failed (status {status})")
            tried.add(rep.index)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                deadline_exceeded = True
                break
            if attempts <= cfg.fleet_retries:
                sleep_s = min(backoff, remaining, 1.0)
                bspan = (tr.start("fleet.backoff", root,
                                  after_attempt=attempts, backoff_s=sleep_s)
                         if root is not None else None)
                time.sleep(sleep_s)
                if bspan is not None:
                    bspan.end()
                backoff *= 2
        retries = max(0, attempts - 1)
        latency_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self._requests += 1
            self._retries_total += retries
            if shed:
                self._shed += 1
            if deadline_exceeded:
                self._deadline_exceeded += 1
            if tenant is not None and not shed:
                led = self._tenants.setdefault(
                    tenant,
                    {"routed": 0, "shed": 0, "quota_rejected": 0})
                led["routed"] += 1
        if root is not None:
            root.end(
                status="ok" if status == 200 else (
                    "shed" if shed else
                    "deadline" if deadline_exceeded and status != 200
                    else f"status_{status}"),
                retries=retries, shed=shed,
                deadline_exceeded=deadline_exceeded,
                replica=served_by.label if served_by else None)
        if self._tw:
            # Sheds carry no tenant here: the KIND_ADMISSION event
            # handle_predict emits owns the per-tenant shed ledger, so
            # the run summary never double-counts one rejection.
            self._tw.emit(
                telemetry.KIND_SERVE_ROUTE,
                metrics={"latency_ms": latency_ms, "retries": retries,
                         "status": status},
                replica=served_by.label if served_by else None,
                shed=shed, deadline_exceeded=deadline_exceeded,
                tenant=None if shed else tenant,
                trace=client_ctx.trace_id if client_ctx else None)
        info = {"shed": shed, "deadline_exceeded": deadline_exceeded,
                "retries": retries}
        return status, payload, served_by, info

    # ------------------------------------------------------------ routes

    def _emit_admission(self, tenant: str, priority: int, verdict: str,
                        retry_after_s: float) -> None:
        """Record one router-level rejection (quota 429 or shed 503) in
        the per-tenant ledger and as a KIND_ADMISSION event."""
        with self._lock:
            led = self._tenants.setdefault(
                tenant, {"routed": 0, "shed": 0, "quota_rejected": 0})
            led["quota_rejected" if verdict == "quota" else "shed"] += 1
        if self._tw:
            self._tw.emit(
                telemetry.KIND_ADMISSION,
                tenant=tenant, priority=priority, verdict=verdict,
                retry_after_s=retry_after_s)

    def handle_predict(self, handler) -> None:
        if self._draining.is_set():
            handler._reply(503, {"error": "draining", "retryable": True})
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                handler._reply(400, {"error": f"bad Content-Length {length}"})
                return
            body = handler.rfile.read(length)
            client_ctx = tracing.safe_parse(
                handler.headers.get(tracing.TRACE_HEADER))
            tenant = (handler.headers.get(TENANT_HEADER)
                      or self.cfg.tenant_default_class)
            priority = autoscale.priority_of(
                tenant, default_class=self.cfg.tenant_default_class)
            model_digest = handler.headers.get(MODEL_HEADER) or None
            # Admission control BEFORE any replica slot is claimed: a
            # tenant over its token bucket gets 429 + an honest
            # Retry-After (seconds until the next token refills).
            verdict = self._quotas.admit(tenant)
            if not verdict.ok:
                retry_after = max(0.05, verdict.retry_after_s)
                self._emit_admission(tenant, priority, "quota", retry_after)
                handler._reply(
                    429,
                    {"error": f"tenant {tenant!r} over quota "
                              f"({self.cfg.tenant_quota_rps:g} rps)",
                     "retryable": True, "tenant": tenant},
                    headers={"Retry-After": f"{retry_after:.3f}"})
                return
            status, payload, served_by, info = self._proxy_predict(
                body, client_ctx, priority=priority, tenant=tenant,
                model_digest=model_digest)
            if info["shed"]:
                self._emit_admission(
                    tenant, priority, "shed",
                    self.cfg.fleet_shed_retry_after_s)
                handler._reply(
                    503,
                    {"error": "fleet saturated or no replica admitted — "
                              "retry after backoff",
                     "retryable": True, "shed": True, "tenant": tenant},
                    headers={"Retry-After":
                             f"{self.cfg.fleet_shed_retry_after_s:g}"})
                return
            if status == 0 and not info["deadline_exceeded"]:
                handler._reply(
                    503,
                    {"error": f"every attempt failed after "
                              f"{info['retries']} retries",
                     "retryable": True},
                    headers={"Retry-After":
                             f"{self.cfg.fleet_shed_retry_after_s:g}"})
                return
            if info["deadline_exceeded"] and status != 200:
                handler._reply(
                    503,
                    {"error": f"deadline {self.cfg.fleet_deadline_s:g}s "
                              f"exceeded after {info['retries']} retries",
                     "retryable": True},
                    headers={"Retry-After":
                             f"{self.cfg.fleet_shed_retry_after_s:g}"})
                return
            headers = ({"X-DTF-Replica": served_by.label}
                       if served_by is not None else None)
            handler._reply(status, payload, headers=headers)
        except Exception as e:  # noqa: BLE001 — router must outlive a bad request
            log.exception("proxy predict failed")
            handler._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def _claim_for_session(
            self, session: str | None) -> tuple[Replica | None, float | None]:
        """Claim the replica a decode session is pinned to.

        Returns ``(replica, None)`` on success, ``(None, retry_after_s)``
        when the pinned replica is mid-drain (rolling reload: its KV
        pages survive the drain, so the honest answer is "come back in a
        moment", not a silent repin that loses the session's cache), and
        ``(None, None)`` when nothing is routable. A pinned replica that
        is dead/ejected/retired has already lost the session's pages —
        repin silently to a fresh claim."""
        now = time.monotonic()
        if session:
            with self._lock:
                pinned = self._sessions.get(session)
                if pinned is not None and pinned < len(self._replicas):
                    rep = self._replicas[pinned]
                    if rep.state == "draining":
                        self._affinity_misses += 1
                        return None, self.cfg.fleet_shed_retry_after_s
                    if (rep.state == "admitted" and not rep.give_up
                            and rep.stalled_until <= now):
                        rep.inflight += 1
                        return rep, None
                    self._sessions.pop(session, None)
        rep = self._claim_replica(set())
        if rep is not None and session:
            with self._lock:
                self._sessions[session] = rep.index
        return rep, None

    def handle_generate(self, handler) -> None:
        """Proxy one streamed ``/generate`` to a session-pinned replica.

        Unlike /predict this is NOT hedged or retried: a generation
        stream is stateful (KV pages on one replica) and not idempotent
        once tokens start flowing, so a mid-stream transport failure
        surfaces to the client instead of silently restarting the
        stream elsewhere. 409 + Retry-After = the session's replica is
        draining for a rolling reload; retry the same session after the
        pause and it lands back on the reloaded replica."""
        if self._draining.is_set():
            handler._reply(503, {"error": "draining", "retryable": True})
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                handler._reply(400, {"error": f"bad Content-Length {length}"})
                return
            body = handler.rfile.read(length)
            session = handler.headers.get(SESSION_HEADER) or None
            rep, retry_after = self._claim_for_session(session)
            if retry_after is not None:
                handler._reply(
                    409,
                    {"error": f"session {session!r} is pinned to a "
                              f"replica that is draining for a reload — "
                              f"retry unchanged",
                     "retryable": True, "session": session},
                    headers={"Retry-After": f"{retry_after:g}"})
                return
            if rep is None:
                handler._reply(
                    503,
                    {"error": "no admitted replica for generate",
                     "retryable": True, "shed": True},
                    headers={"Retry-After":
                             f"{self.cfg.fleet_shed_retry_after_s:g}"})
                return
        except Exception as e:  # noqa: BLE001 — router must outlive a bad request
            log.exception("generate claim failed")
            handler._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        t0 = time.monotonic()
        status = 0
        try:
            req = urllib.request.Request(
                rep.url + "/generate", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.cfg.fleet_deadline_s)
            except urllib.error.HTTPError as e:
                # Submit-time rejection (400/503/...) — relay verbatim;
                # a 4xx is the request's fault, not the replica's.
                status = e.code
                try:
                    payload = json.loads(e.read() or b"{}")
                except (ValueError, OSError):
                    payload = {"error": f"replica status {e.code}"}
                if status >= 500:
                    self._record_failure(
                        rep, f"generate failed (status {status})")
                handler._reply(status, payload,
                               headers={"X-DTF-Replica": rep.label})
                return
            with resp:
                status = resp.status
                handler.send_response(status)
                handler.send_header(
                    "Content-Type",
                    resp.headers.get("Content-Type",
                                     "application/x-ndjson"))
                handler.send_header("Transfer-Encoding", "chunked")
                handler.send_header("X-DTF-Replica", rep.label)
                handler.end_headers()
                # http.client undoes the replica's chunking; readline
                # re-streams each NDJSON event the moment it arrives.
                for line in resp:
                    handler.wfile.write(
                        f"{len(line):X}\r\n".encode() + line + b"\r\n")
                    handler.wfile.flush()
                handler.wfile.write(b"0\r\n\r\n")
                handler.wfile.flush()
            self._record_success(rep)
        except (urllib.error.URLError, OSError, ValueError) as e:
            self._record_failure(rep, f"generate failed ({type(e).__name__})")
            if status == 0:
                # Nothing on the wire yet — a clean retryable error.
                try:
                    handler._reply(
                        503, {"error": f"{type(e).__name__}: {e}",
                              "retryable": True},
                        headers={"Retry-After":
                                 f"{self.cfg.fleet_shed_retry_after_s:g}"})
                except OSError:
                    pass
            else:
                log.warning("generate stream to %s aborted mid-flight: "
                            "%s: %s", rep.label, type(e).__name__, e)
        finally:
            self._release_replica(rep)
            with self._lock:
                self._requests += 1
                self._generate_streams += 1
            if self._tw:
                self._tw.emit(
                    telemetry.KIND_SERVE_ROUTE,
                    metrics={"latency_ms": (time.monotonic() - t0) * 1e3,
                             "retries": 0, "status": status},
                    replica=rep.label, shed=False,
                    deadline_exceeded=False, tenant=None, trace=None)

    def handle_reload(self, handler) -> None:
        if self._draining.is_set():
            handler._reply(503, {"error": "draining", "retryable": True})
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                handler._reply(400, {"error": f"bad Content-Length {length}"})
                return
            payload = json.loads(handler.rfile.read(length))
            artifact_dir = payload.get("artifact_dir")
            if not isinstance(artifact_dir, str) or not artifact_dir:
                handler._reply(
                    400, {"error": "body must be {\"artifact_dir\": ...}"})
                return
            count = payload.get("count")
            if count is not None and (
                    not isinstance(count, int) or count < 1):
                handler._reply(
                    400, {"error": f"count must be a positive int, "
                                   f"got {count!r}"})
                return
            digest = payload.get("digest")
            if digest is not None and (
                    not isinstance(digest, str) or not digest):
                handler._reply(
                    400, {"error": "digest must be a non-empty string "
                                   "(content_digest prefix)"})
                return
            results, ok = self.rolling_reload(
                artifact_dir, count=count, only_digest=digest)
            handler._reply(200 if ok else 409,
                           {"reloaded": ok, "replicas": results})
        except FleetError as e:
            handler._reply(409, {"error": str(e), "reloaded": False})
        except json.JSONDecodeError as e:
            handler._reply(400, {"error": f"invalid JSON: {e}"})
        except Exception as e:  # noqa: BLE001 — router must outlive a bad request
            log.exception("rolling reload failed")
            handler._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def handle_healthz(self, handler) -> None:
        status = 503 if self._draining.is_set() else 200
        handler._reply(status, self.fleet_healthz())

    def fleet_healthz(self) -> dict:
        """The router's /healthz payload: per-replica lifecycle + router
        counters, plus the spec passthrough load_gen needs to synthesize
        traffic (task/input_spec from any replica that reported one) and
        an aggregate engine-counter view for healthz-delta accounting."""
        with self._lock:
            reps = []
            base: dict = {}
            engine_agg: dict[str, float] = {}
            for rep in self._replicas:
                health = rep.last_health
                if health.get("input_spec") and not base:
                    base = health
                engine = health.get("engine") or {}
                for key, value in engine.items():
                    if isinstance(value, (int, float)) and not isinstance(
                            value, bool):
                        engine_agg[key] = engine_agg.get(key, 0) + value
                reps.append({
                    "replica": rep.label,
                    "url": rep.url,
                    "state": rep.state,
                    "give_up": rep.give_up,
                    "inflight": rep.inflight,
                    "routed": rep.routed,
                    "consecutive_failures": rep.consecutive_failures,
                    "restarts": rep.restarts,
                    "queue_depth": (rep.last_health.get("engine") or {}
                                    ).get("queue_depth"),
                    "artifact": rep.artifact_info(),
                    "step": rep.last_health.get("step"),
                })
            router = {
                "requests": self._requests,
                "retries": self._retries_total,
                "generate_streams": self._generate_streams,
                "sessions": len(self._sessions),
                "affinity_misses": self._affinity_misses,
                "shed": self._shed,
                "deadline_exceeded": self._deadline_exceeded,
                "reload_rolls": self._reload_rolls,
                "ticks": self._tick_count,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
            }
            tenants = {t: dict(led) for t, led in self._tenants.items()}
            # Per-model rollup: the live traffic weights of a
            # multiplexed fleet (replica allocation per content_digest).
            models: dict[str, dict] = {}
            for rep in self._replicas:
                if rep.state == "retired":
                    continue
                dg = (rep.last_health.get("artifact") or {}).get(
                    "content_digest")
                if not dg:
                    continue
                m = models.setdefault(
                    str(dg), {"replicas": 0, "routed": 0})
                m["replicas"] += 1
                m["routed"] += rep.routed
            asc = self._autoscaler
            autoscale_view = ({
                "enabled": True,
                "min_replicas": asc.min_replicas,
                "max_replicas": asc.max_replicas,
                "pressure": round(asc.last_pressure, 4),
            } if asc is not None else {"enabled": False})
        admitted = sum(1 for r in reps if r["state"] == "admitted")
        return {
            "status": "draining" if self._draining.is_set() else "ok",
            "role": "fleet",
            "task": base.get("task"),
            "model": base.get("model"),
            "step": base.get("step"),
            "vocab_size": base.get("vocab_size"),
            "input_spec": base.get("input_spec"),
            "engine": {"state": "running", **engine_agg},
            "fleet": {"replicas": reps, "router": router,
                      "admitted": admitted,
                      "tenants": tenants, "models": models,
                      "autoscale": autoscale_view},
        }

    # ----------------------------------------------------------- reload

    def rolling_reload(self, artifact_dir: str, *, count: int | None = None,
                       only_digest: str | None = None
                       ) -> tuple[list[dict], bool]:
        """Zero-downtime deploy: drain → reload → probe → readmit, one
        replica at a time. The first rejected reload ABORTS the roll —
        a tampered/incompatible artifact must never spread past the
        replica that refused it (every replica keeps serving weights
        that passed verification either way).

        ``count`` caps how many replicas roll and ``only_digest`` scopes
        the roll to replicas currently serving a matching artifact —
        together they move part of the fleet to a new model while the
        rest keeps serving the old one (the multiplexing deploy: the
        per-model traffic weight IS the replica allocation, readable
        from the healthz ``models`` rollup)."""
        with self._lock:
            if self._rolling:
                raise FleetError("a rolling reload is already in progress")
            self._rolling = True
            self._reload_rolls += 1
        try:
            for fault in faults.fire("fleet_reload"):
                if fault.kind == "corrupt_reload":
                    faults.corrupt_checkpoint_dir(artifact_dir)
            with self._lock:
                targets = [r for r in self._replicas]
            results: list[dict] = []
            ok = True
            rolled = 0
            for rep in targets:
                if count is not None and rolled >= count:
                    break
                with self._lock:
                    skip = rep.state not in ("admitted", "ejected")
                    rep_digest = str((rep.last_health.get("artifact") or {})
                                     .get("content_digest") or "")
                if only_digest and not rep_digest.startswith(only_digest):
                    continue  # serving a different model: not in scope
                if skip:
                    results.append({"replica": rep.label, "ok": False,
                                    "skipped": True, "state": rep.state})
                    continue
                result = self._reload_replica(rep, artifact_dir)
                results.append(result)
                rolled += 1
                if not result["ok"]:
                    ok = False
                    break
            return results, ok
        finally:
            with self._lock:
                self._rolling = False

    def _reload_replica(self, rep: Replica, artifact_dir: str) -> dict:
        cfg = self.cfg
        t0 = time.monotonic()
        from_digest = rep.artifact_info().get("content_digest")
        with self._lock:
            prev_state, rep.state = rep.state, "draining"
        # Drain: the claim loop no longer picks this replica; wait out
        # the requests it already carries (bounded by the drain budget).
        drain_deadline = time.monotonic() + cfg.drain_timeout_s
        while time.monotonic() < drain_deadline:
            with self._lock:
                if rep.inflight == 0:
                    break
            time.sleep(0.05)
        status, payload = _http_json(
            rep.url + "/reload",
            data=json.dumps({"artifact_dir": artifact_dir}).encode(),
            timeout=cfg.drain_timeout_s + cfg.fleet_attempt_timeout_s)
        ok = status == 200 and bool(payload.get("reloaded"))
        to_digest = payload.get("to_digest")
        if ok:
            # Probe: trust /healthz, not the reload response — readmit
            # only once the replica self-reports the NEW digest.
            probe_deadline = time.monotonic() + cfg.drain_timeout_s
            confirmed = False
            while time.monotonic() < probe_deadline:
                hstatus, health = _http_json(
                    rep.url + "/healthz",
                    timeout=max(1.0, cfg.fleet_attempt_timeout_s / 2))
                if (hstatus == 200 and (health.get("artifact") or {}).get(
                        "content_digest") == to_digest):
                    with self._lock:
                        rep.last_health = health
                        rep.last_health_t = time.monotonic()
                    confirmed = True
                    break
                time.sleep(min(0.2, cfg.fleet_probe_interval_s))
            ok = confirmed
        with self._lock:
            # A rejected reload (409: tamper, mismatch) leaves a HEALTHY
            # replica on its old weights — readmit it. A transport-dead
            # one goes back to its previous state for the breaker to
            # handle.
            rep.state = ("admitted" if ok or status == 409 else prev_state)
        reload_ms = (time.monotonic() - t0) * 1e3
        result = {
            "replica": rep.label, "ok": ok, "status": status,
            "from_digest": from_digest, "to_digest": to_digest,
            "reload_ms": reload_ms,
            "error": None if ok else payload.get("error"),
        }
        log.info("rolling reload %s: ok=%s status=%d (%.0f ms)",
                 rep.label, ok, status, reload_ms)
        if self._tw:
            self._tw.emit(
                telemetry.KIND_SERVE_RELOAD,
                metrics={"reload_ms": reload_ms},
                replica=rep.label, ok=ok,
                from_digest=from_digest, to_digest=to_digest)
        return result

    # ----------------------------------------------------------- prober

    def _apply_chaos(self, fault) -> None:
        """Execute a fleet_chaos fault against its target replica (the
        drill harness: kill = SIGKILL the child, stall = SIGSTOP it for
        fault.seconds — alive, port open, answering nothing). The
        traffic-shaped kinds (spike / tenant_stampede) target the
        ROUTER itself: they open a synthetic-load window instead of
        touching a subprocess."""
        if fault.kind == "spike":
            log.warning("chaos: traffic spike +%.0f req/replica for %.0fs",
                        fault.factor or 0.0, fault.seconds or 0.0)
            with self._lock:
                self._spike_until = time.monotonic() + (fault.seconds or 0.0)
                self._spike_load = float(fault.factor or 0.0)
            return
        if fault.kind == "tenant_stampede":
            log.warning("chaos: tenant stampede for %.0fs (batch-class "
                        "load saturates unreserved queue slots)",
                        fault.seconds or 0.0)
            with self._lock:
                self._stampede_until = (time.monotonic()
                                        + (fault.seconds or 0.0))
            return
        with self._lock:
            target = (self._replicas[fault.replica]
                      if fault.replica is not None
                      and 0 <= fault.replica < len(self._replicas) else None)
        if target is None or target.proc is None:
            log.warning("chaos fault %s has no launcher-managed target — "
                        "skipped", fault.fault_id)
            return
        if fault.kind == "kill_replica":
            log.warning("chaos: SIGKILL %s (pid %d)",
                        target.label, target.proc.pid)
            target.proc.kill()
        elif fault.kind == "stall_replica":
            log.warning("chaos: SIGSTOP %s (pid %d) for %.0fs",
                        target.label, target.proc.pid, fault.seconds or 0)
            try:
                os.kill(target.proc.pid, signal.SIGSTOP)
            except ProcessLookupError:
                return
            with self._lock:
                target.stalled_until = (time.monotonic()
                                        + (fault.seconds or 0.0))

    def _resume_stalls(self, now: float) -> None:
        with self._lock:
            due = [r for r in self._replicas
                   if r.stalled_until and now >= r.stalled_until]
            for rep in due:
                rep.stalled_until = 0.0
        for rep in due:
            if rep.proc is not None:
                try:
                    os.kill(rep.proc.pid, signal.SIGCONT)
                except ProcessLookupError:
                    pass

    def _check_process(self, rep: Replica, now: float) -> None:
        """Dead-child detection + the supervision restart policy."""
        if rep.proc is None or rep.proc.poll() is None:
            return
        with self._lock:
            if rep.state == "dead":
                return
            if rep.retiring or rep.state == "retired":
                # A scale-down victim exiting is the PLAN, not a death:
                # supervision must not restart what autoscaling drained.
                return
            rep.state = "dead"
            rep.consecutive_failures = 0
            routed, artifact_step = rep.routed, rep.artifact_info().get("step")
        rc = rep.proc.returncode
        # Same verdict machinery as the training supervisor: identical
        # rc with no serving progress twice in a row = deterministic.
        stop = rep.breaker.record(
            rc=rc, last_step=routed, ckpt_step=artifact_step,
            transient=rc in (-signal.SIGKILL, -signal.SIGTERM))
        budget_spent = rep.restarts >= self.cfg.fleet_max_restarts
        with self._lock:
            rep.give_up = stop or budget_spent or self._launcher is None
            rep.next_restart_t = now + supervision.backoff_seconds(
                rep.restarts + 1, base=max(0.5, self.cfg.fleet_probe_interval_s),
                cap=30.0)
        self._emit_eject(
            rep, action="eject", reason=f"dead (rc={rc})",
            give_up=rep.give_up, crash_loop=bool(stop),
            restarts=rep.restarts)
        if self.flightrec is not None:
            # Forensics at the moment of observation: the ring holds the
            # route/attempt/eject events (spans included) leading up to
            # the death, plus every router span still open.
            self.flightrec.dump(f"replica {rep.label} dead (rc={rc})",
                                open_spans=self.tracer.open_spans())

    def _restart_due(self, now: float) -> None:
        with self._lock:
            due = [r for r in self._replicas
                   if r.state == "dead" and not r.give_up
                   and now >= r.next_restart_t]
        for rep in due:
            try:
                proc, endpoint_path = self._launcher(rep.index)
            except Exception as e:  # noqa: BLE001 — keep supervising the rest
                log.error("restart of %s failed: %s", rep.label, e)
                with self._lock:
                    rep.restarts += 1
                    rep.give_up = rep.restarts >= self.cfg.fleet_max_restarts
                    rep.next_restart_t = now + supervision.backoff_seconds(
                        rep.restarts + 1,
                        base=max(0.5, self.cfg.fleet_probe_interval_s),
                        cap=30.0)
                continue
            with self._lock:
                rep.proc = proc
                rep.endpoint_path = endpoint_path
                rep.url = ""
                rep.last_health = {}
                rep.restarts += 1
                rep.state = "ejected"  # earns admission via the prober
            self._emit_eject(rep, action="restart",
                             reason="supervised relaunch",
                             restarts=rep.restarts)

    def _probe_replica(self, rep: Replica, now: float) -> None:
        """Health poll + circuit-breaker transitions for one replica."""
        with self._lock:
            state = rep.state
            stalled = rep.stalled_until > now
        if state in ("dead", "draining", "retired") or stalled:
            return
        if not rep.url and rep.endpoint_path:
            url = read_endpoint(rep.endpoint_path)
            if not url:
                return  # still booting
            with self._lock:
                rep.url = url
        if not rep.url:
            return
        timeout = max(0.5, min(2.0, self.cfg.fleet_healthz_stale_s / 3))
        status, payload = _http_json(rep.url + "/healthz", timeout=timeout)
        if status == 200:
            with self._lock:
                rep.last_health = payload
                rep.last_health_t = now
                rep.consecutive_failures = 0
                readmit = state == "ejected"
                if readmit:
                    rep.state = "admitted"
            if readmit:
                self._emit_eject(rep, action="readmit",
                                 reason="healthz recovered")
            return
        self._record_failure(rep, f"healthz failed (status {status})")
        with self._lock:
            stale = (rep.state == "admitted" and rep.last_health_t
                     and now - rep.last_health_t
                     > self.cfg.fleet_healthz_stale_s)
            if stale:
                rep.state = "ejected"
        if stale:
            self._emit_eject(rep, action="eject", reason="stale healthz")

    def _tick(self) -> None:
        with self._lock:
            self._tick_count += 1
            # The chaos clock arms only once every registered replica has
            # come up (admitted, or given up) — `kill_replica:N:T` then
            # means "T ticks after the fleet was ready", deterministic
            # relative to the drill's load instead of racing replica boot.
            if not self._chaos_armed and self._replicas and all(
                    r.state in ("admitted", "retired") or r.give_up
                    for r in self._replicas):
                self._chaos_armed = True
            if self._chaos_armed:
                self._chaos_tick += 1
            chaos_tick = self._chaos_tick if self._chaos_armed else None
        if chaos_tick is not None:
            for fault in faults.fire("fleet_chaos", step=chaos_tick):
                self._apply_chaos(fault)
        now = time.monotonic()
        self._resume_stalls(now)
        with self._lock:
            replicas = list(self._replicas)
        for rep in replicas:
            self._check_process(rep, now)
            self._probe_replica(rep, time.monotonic())
        self._restart_due(time.monotonic())
        self._advance_retirements(time.monotonic())
        self._autoscale_tick(time.monotonic())

    # ------------------------------------------------------- autoscaling

    def _advance_retirements(self, now: float) -> None:
        """Finish scale-down drains: once a retiring replica's in-flight
        hits zero (or its drain budget expires), SIGTERM it — the
        replica's own graceful drain flushes telemetry — and mark it
        retired so neither routing nor supervision ever touches it
        again."""
        with self._lock:
            due = [r for r in self._replicas
                   if r.retiring and r.state == "draining"
                   and (r.inflight == 0 or now >= r.retire_deadline)]
            for rep in due:
                rep.state = "retired"
        for rep in due:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.terminate()
            log.info("fleet autoscale: %s retired (drained)", rep.label)

    def _emit_scale(self, decision: "autoscale.ScaleDecision",
                    replica_label: str | None) -> None:
        log.warning("fleet autoscale: scale %s -> %d replicas (%s)",
                    decision.action, decision.to_replicas, decision.reason)
        if self._tw:
            self._tw.emit(
                telemetry.KIND_SCALE,
                metrics={"pressure": decision.pressure},
                action=decision.action, reason=decision.reason,
                replica=replica_label,
                from_replicas=decision.from_replicas,
                to_replicas=decision.to_replicas)

    def _autoscale_tick(self, now: float) -> None:
        """One control-loop iteration: snapshot under the lock, let the
        pure policy decide, actuate at most one action. Scale-up goes
        through the SAME launcher path supervised restarts use;
        scale-down marks the newest admitted replica retiring and lets
        :meth:`_advance_retirements` finish the drain across ticks."""
        asc = self._autoscaler
        if asc is None:
            return
        with self._lock:
            synthetic = self._stampede_load(now) + (
                self._spike_load if now < self._spike_until else 0.0)
            admitted = booting = draining = give_up = alive = 0
            load = 0.0
            for rep in self._replicas:
                if rep.give_up:
                    give_up += 1
                    continue
                if rep.state == "retired":
                    continue
                if rep.retiring:
                    draining += 1
                    continue
                alive += 1
                if rep.state == "admitted":
                    admitted += 1
                    engine = rep.last_health.get("engine") or {}
                    try:
                        depth = int(engine.get("queue_depth") or 0)
                    except (TypeError, ValueError):
                        depth = 0
                    load += depth + rep.inflight + synthetic
                else:
                    # Spawned/restarting but not yet admitted: it fills
                    # a hole already — judging pressure now would
                    # double-spawn for the same gap.
                    booting += 1
            shed_delta = self._shed - self._shed_seen
            self._shed_seen = self._shed
            snap = autoscale.FleetSnapshot(
                admitted=admitted, alive=alive, booting=booting,
                draining=draining, give_up=give_up, load=load,
                capacity=self.cfg.queue_capacity, shed_delta=shed_delta)
        decision = asc.decide(snap, now)
        if decision is None:
            return
        if decision.action == "up":
            if self._launcher is None:
                log.warning("fleet autoscale: scale-up wanted (%s) but no "
                            "launcher is configured — skipped",
                            decision.reason)
                return
            with self._lock:
                index = len(self._replicas)
            try:
                proc, endpoint_path = self._launcher(index)
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                log.error("fleet autoscale: spawn of r%d failed: %s",
                          index, e)
                return
            rep = self.add_replica(proc=proc, endpoint_path=endpoint_path)
            with self._lock:
                self._scale_ups += 1
            self._emit_scale(decision, rep.label)
            return
        # decision.action == "down": retire the newest admitted replica
        # (LIFO keeps the original fixed fleet as the stable core).
        with self._lock:
            victims = [r for r in self._replicas
                       if r.state == "admitted" and not r.retiring]
            if not victims:
                return
            victim = max(victims, key=lambda r: r.index)
            victim.state = "draining"
            victim.retiring = True
            victim.retire_deadline = now + self.cfg.drain_timeout_s
            self._scale_downs += 1
        self._emit_scale(decision, victim.label)

    def _probe_loop(self) -> None:
        try:
            while not self._stop.wait(self.cfg.fleet_probe_interval_s):
                self._tick()
        except BaseException as e:  # surface on exit, never just stderr
            log.error("fleet prober thread failed", exc_info=True)
            with self._lock:
                if self._prober_error is None:
                    self._prober_error = FleetProberError(e)

    # ------------------------------------------------------------- drain

    def shutdown(self, reason: str = "shutdown") -> bool:
        """Stop admission → stop the prober → SIGTERM every replica
        (their own graceful drain finishes queued work) → stop the HTTP
        loop. Idempotent; safe from any thread."""
        if self._draining.is_set():
            self._done.wait(self.cfg.drain_timeout_s)
            return True
        self._draining.set()
        self._stop.set()
        if self._prober.is_alive():
            self._prober.join(max(2.0, 4 * self.cfg.fleet_probe_interval_s))
        log.info("fleet drain started (%s)", reason)
        with self._lock:
            procs = [(r.label, r.proc) for r in self._replicas
                     if r.proc is not None and r.proc.poll() is None]
        clean = True
        for _, proc in procs:
            proc.terminate()  # SIGTERM → the replica's graceful drain
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        for label, proc in procs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(remaining)
            except Exception:  # noqa: BLE001 — subprocess.TimeoutExpired et al.
                log.warning("replica %s did not drain in time — SIGKILL",
                            label)
                proc.kill()
                clean = False
        if self._tw:
            self._tw.emit(
                telemetry.KIND_HEALTH,
                health={"event": "fleet_drain", "reason": reason,
                        "clean": clean})
        # stdlib BaseServer.shutdown() blocks on an event that only
        # serve_forever() sets — never call it when the loop never ran
        # (e.g. a startup abort before serve_forever).
        if self._serving.is_set():
            self.httpd.shutdown()
        self._done.set()
        log.info("fleet drain complete (clean=%s)", clean)
        return clean

    def install_sigterm_drain(self) -> None:
        """SIGTERM → graceful fleet drain (same contract as the single
        engine and the trainer: supervisors treat drain-exit-0 as
        success)."""

        def _drain():
            try:
                self.shutdown("sigterm")
            except BaseException as e:  # noqa: BLE001 — surface, don't hang
                log.error("sigterm fleet drain failed", exc_info=True)
                self._drain_error = FleetDrainError(e)
                self._done.set()
                if self._serving.is_set():
                    self.httpd.shutdown()

        def _on_term(signum, frame):
            del signum, frame
            threading.Thread(
                target=_drain, name="dtf-fleet-drain", daemon=True).start()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

    def serve_forever(self) -> None:
        """Block until shutdown() (or SIGTERM via the installed
        handler); re-raise stored drain/prober failures."""
        log.info("fleet router on http://%s:%d fronting %d replica(s)",
                 self.host, self.port, len(self._replicas))
        if not self._draining.is_set():
            self._serving.set()
            self.httpd.serve_forever()
        self.httpd.server_close()
        if self._drain_error is not None:
            raise self._drain_error
        with self._lock:
            prober_error = self._prober_error
        if prober_error is not None:
            raise prober_error
