"""Stdlib-only HTTP front end for the inference engine.

One ThreadingHTTPServer (a worker thread per connection, each blocking in
``engine.predict`` while the batcher coalesces across them — that
blocking IS the dynamic batching window) and two routes:

  * ``POST /predict`` — ``{"inputs": {...}}`` in, ``{"outputs": [...]}``
    out. Typed engine errors map to useful statuses: validation and
    oversize/too-long → 400, backpressure and draining → 503 (retryable),
    anything else → 500.
  * ``POST /reload`` — ``{"artifact_dir": ...}``: live weight swap via
    the engine's between-batches reload. Verification failure → 409 with
    the old weights still serving.
  * ``GET /healthz`` — liveness + the artifact's input spec (the load
    generator reads it to synthesize traffic) + engine counters + the
    digest of the artifact actually being served (the fleet router's
    mixed-version visibility during a rolling reload).

SIGTERM mirrors the trainer's graceful-preemption contract
(core/supervision.py): stop admission, finish every queued request
within ``serve.drain_timeout_s``, then exit 0 — the supervisor treats a
serving drain as success, not a crash to back off from.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from distributed_tensorflow_framework_tpu.core import telemetry, tracing
from distributed_tensorflow_framework_tpu.core.config import ServeConfig
from distributed_tensorflow_framework_tpu.serve.decode import (
    CacheFullError,
    StreamTooLongError,
)
from distributed_tensorflow_framework_tpu.serve.engine import (
    EngineClosedError,
    InferenceEngine,
    OversizeRequestError,
    QueueFullError,
    ReloadError,
    SequenceTooLongError,
    ServeError,
)

log = logging.getLogger(__name__)

_MAX_BODY = 64 * 1024 * 1024  # refuse absurd request bodies outright


class ServeDrainError(RuntimeError):
    """The signal-triggered drain thread failed. Stored by the drain
    thread and re-raised when :meth:`ServingServer.serve_forever`
    returns — without it a drain failure leaves ``serve_forever`` and
    every ``shutdown()`` waiter blocked forever with the error lost to a
    daemon thread's stderr."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"serve drain failed: {type(cause).__name__}: {cause}")
        self.__cause__ = cause


class ServingServer:
    """Engine + ThreadingHTTPServer, owning the drain choreography."""

    def __init__(self, engine: InferenceEngine, serve_cfg: ServeConfig, *,
                 decode_engine=None, telemetry_writer=None):
        self.engine = engine
        # Optional serve/decode.DecodeEngine (decode.enabled + mlm task):
        # adds the streaming POST /generate route; None keeps the server
        # byte-identical to a single-shot deployment.
        self.decode_engine = decode_engine
        self.cfg = serve_cfg
        self._tw = telemetry_writer
        self._draining = threading.Event()
        self._done = threading.Event()
        self._drain_error: ServeDrainError | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through logging
                log.debug("%s %s", self.address_string(), fmt % args)

            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/healthz":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                outer.handle_healthz(self)

            def do_POST(self):
                if self.path == "/predict":
                    outer.handle_predict(self)
                elif self.path == "/generate":
                    outer.handle_generate(self)
                elif self.path == "/reload":
                    outer.handle_reload(self)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

        class Server(ThreadingHTTPServer):
            # The socketserver default accept backlog of 5 drops
            # connections under concurrent load (urllib clients open a
            # fresh connection per request) — size it to the engine's
            # admission bound instead.
            request_queue_size = max(128, serve_cfg.queue_capacity)

        # Port 0 asks the OS for an ephemeral port; cli/serve.py writes
        # the RESOLVED endpoint to endpoint.json so tooling can find it.
        self.httpd = Server((serve_cfg.host, serve_cfg.port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]

    # ------------------------------------------------------------ routes

    def handle_predict(self, handler) -> None:
        # Incoming X-DTF-Trace (router attempt or direct client): adopt
        # the sender's clock sample, open the replica-side request span,
        # and hand its context to the engine so queue/batch/compute spans
        # chain under it. A malformed header never fails the request.
        ctx = tracing.safe_parse(handler.headers.get(tracing.TRACE_HEADER))
        tracer = self.engine.tracer
        span = None
        if ctx is not None:
            tracer.adopt(ctx)
            span = tracer.start("serve.request", ctx)
        sent: dict[str, int] = {}

        def reply(status: int, payload: dict) -> None:
            sent["status"] = status
            handler._reply(status, payload)

        try:
            if self._draining.is_set():
                reply(503, {"error": "draining", "retryable": True})
                return
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                reply(400, {"error": f"bad Content-Length {length}"})
                return
            payload = json.loads(handler.rfile.read(length))
            inputs = payload.get("inputs")
            if not isinstance(inputs, dict):
                reply(400, {"error": "body must be {\"inputs\": {...}}"})
                return
            outputs = self.engine.predict(
                inputs, timeout=self.cfg.drain_timeout_s,
                trace=span.context() if span is not None else None)
            reply(200, {
                "outputs": np.asarray(outputs).tolist(),
                "rows": int(np.asarray(outputs).shape[0]),
                "step": self.engine.artifact.step,
            })
        except (OversizeRequestError, SequenceTooLongError) as e:
            reply(400, {"error": str(e)})
        except (QueueFullError, EngineClosedError) as e:
            reply(503, {"error": str(e), "retryable": True})
        except ServeError as e:
            reply(400, {"error": str(e)})
        except json.JSONDecodeError as e:
            reply(400, {"error": f"invalid JSON: {e}"})
        except Exception as e:  # noqa: BLE001 — server must outlive a bad request
            log.exception("predict failed")
            reply(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            if span is not None:
                status = sent.get("status", 500)
                span.end(status="ok" if status < 400 else f"http_{status}",
                         http_status=status)

    @staticmethod
    def _write_chunk(handler, data: bytes, flush: bool = True) -> None:
        """One HTTP/1.1 chunked-transfer frame — a token event must
        reach the client the moment it exists (TTFT/TPOT are measured
        from these frame arrivals, docs/SERVING.md). ``flush=False``
        lets the generate loop coalesce a burst of already-queued
        frames into one syscall; the burst's LAST frame always flushes."""
        handler.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        if flush:
            handler.wfile.flush()

    @staticmethod
    def _end_chunks(handler) -> None:
        handler.wfile.write(b"0\r\n\r\n")
        handler.wfile.flush()

    def handle_generate(self, handler) -> None:
        """``POST /generate {"prompt": [ids...], ...}`` — streamed
        autoregressive decode. The reply is chunked NDJSON: one
        ``{"token": ..., "index": ...}`` line per generated token as the
        continuous batcher produces it, closed by one ``{"done": true,
        ...summary}`` line. Submit-time errors map like /predict
        (too-long/never-fits → 400, backpressure/draining → 503
        retryable); a mid-stream failure becomes an ``{"error": ...}``
        line because the 200 status is already on the wire."""
        if self.decode_engine is None:
            handler._reply(404, {
                "error": "decode disabled — set decode.enabled=true and "
                         "serve an mlm artifact"})
            return
        try:
            if self._draining.is_set():
                handler._reply(503, {"error": "draining", "retryable": True})
                return
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                handler._reply(400, {"error": f"bad Content-Length {length}"})
                return
            payload = json.loads(handler.rfile.read(length))
            prompt = payload.get("prompt")
            if not isinstance(prompt, list) or not prompt:
                handler._reply(400, {
                    "error": "body must be {\"prompt\": [token ids...]}"})
                return
            stream = self.decode_engine.submit(
                prompt,
                max_new_tokens=payload.get("max_new_tokens"),
                eos_id=payload.get("eos_id"),
                return_logits=bool(payload.get("return_logits")))
        except (StreamTooLongError, CacheFullError) as e:
            # Neither gets better on retry: the stream as requested can
            # never be admitted.
            handler._reply(400, {"error": str(e)})
            return
        except (QueueFullError, EngineClosedError) as e:
            handler._reply(503, {"error": str(e), "retryable": True})
            return
        except ServeError as e:
            handler._reply(400, {"error": str(e)})
            return
        except json.JSONDecodeError as e:
            handler._reply(400, {"error": f"invalid JSON: {e}"})
            return
        except Exception as e:  # noqa: BLE001 — server must outlive a bad request
            log.exception("generate submit failed")
            handler._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.send_header("X-DTF-Step",
                            str(self.decode_engine.artifact.step))
        handler.end_headers()
        try:
            for kind, ev in stream.events(timeout=self.cfg.drain_timeout_s):
                if kind == "token":
                    if "logits" in ev:
                        line = json.dumps(
                            dict(ev, logits=ev["logits"].tolist()))
                    else:
                        # Hand-rolled frame for the hot path: at one
                        # frame per generated token, json.dumps is
                        # measurable scheduler-thread GIL steal.
                        line = ('{"token":%d,"index":%d}'
                                % (ev["token"], ev["index"]))
                else:
                    line = json.dumps({"done": True, **ev})
                self._write_chunk(handler, (line + "\n").encode(),
                                  flush=stream.pending() == 0)
            self._end_chunks(handler)
        except Exception as e:  # noqa: BLE001 — status already on the wire
            log.warning("generate stream aborted: %s: %s",
                        type(e).__name__, e)
            try:
                self._write_chunk(handler, (json.dumps(
                    {"error": f"{type(e).__name__}: {e}",
                     "retryable": isinstance(e, EngineClosedError)})
                    + "\n").encode())
                self._end_chunks(handler)
            except OSError:
                pass  # client already gone

    def handle_reload(self, handler) -> None:
        """``POST /reload {"artifact_dir": ...}`` — live weight swap.

        Not idempotent and not proxied-with-retry: a rejected reload
        (tamper, mismatch) is 409 with the engine still on the old
        weights; only the fleet router's rolling deploy should normally
        call this directly.
        """
        if self._draining.is_set():
            handler._reply(503, {"error": "draining", "retryable": True})
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                handler._reply(400, {"error": f"bad Content-Length {length}"})
                return
            payload = json.loads(handler.rfile.read(length))
            artifact_dir = payload.get("artifact_dir")
            if not isinstance(artifact_dir, str) or not artifact_dir:
                handler._reply(
                    400, {"error": "body must be {\"artifact_dir\": ...}"})
                return
            result = self.engine.reload(
                artifact_dir, timeout=self.cfg.drain_timeout_s)
            if self.decode_engine is not None:
                # Same artifact, second engine: the decode swap blocks
                # until its in-flight streams finish on the old weights
                # (decode.request_reload drain contract), so give it the
                # full drain budget. A decode-side rejection is the same
                # 409 contract — but the single-shot engine already
                # swapped, so say so.
                try:
                    decode_result = self.decode_engine.reload(
                        artifact_dir, timeout=self.cfg.drain_timeout_s)
                except ReloadError as e:
                    raise ReloadError(
                        f"decode engine rejected the reload (single-shot "
                        f"engine already swapped): {e}") from e
                result = {**result, "decode": decode_result}
            handler._reply(200, {"reloaded": True, **result})
        except ReloadError as e:
            handler._reply(409, {"error": str(e), "reloaded": False})
        except EngineClosedError as e:
            handler._reply(503, {"error": str(e), "retryable": True})
        except json.JSONDecodeError as e:
            handler._reply(400, {"error": f"invalid JSON: {e}"})
        except Exception as e:  # noqa: BLE001 — server must outlive a bad request
            log.exception("reload failed")
            handler._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def handle_healthz(self, handler) -> None:
        status = 503 if self._draining.is_set() else 200
        art = self.engine.artifact
        handler._reply(status, {
            "status": "draining" if status == 503 else "ok",
            "task": art.task,
            "model": art.model_config.name,
            "step": art.step,
            "vocab_size": art.vocab_size,
            "input_spec": art.input_spec,
            # Which weights am I ACTUALLY serving — mid-roll, mixed-
            # version replicas answer with different digests here.
            "artifact": self.engine.artifact_info(),
            "engine": self.engine.stats(),
            # Live HBM + goodput snapshots: load_gen diffs these across a
            # bench window to attribute serve-side memory pressure and
            # compute fraction to its own traffic (docs/OBSERVABILITY.md).
            "memory": self.engine.memory_snapshot(),
            "goodput": self.engine.goodput_snapshot(),
            # KV-cache occupancy + stream counters when the decode path
            # is enabled (None otherwise, schema-additive).
            "decode": (self.decode_engine.stats()
                       if self.decode_engine is not None else None),
        })

    # ------------------------------------------------------------- drain

    def shutdown(self, reason: str = "shutdown") -> bool:
        """Stop admission → drain the engine → stop the HTTP loop.

        Idempotent; safe from any thread (including a signal handler's
        helper thread). Returns the engine's drained-clean verdict.
        """
        if self._draining.is_set():
            self._done.wait(self.cfg.drain_timeout_s)
            return True
        self._draining.set()
        log.info("drain started (%s): refusing new requests, %d queued",
                 reason, self.engine.stats()["queue_depth"])
        drained = self.engine.drain(self.cfg.drain_timeout_s)
        if self.decode_engine is not None:
            # Streams still get their remaining tokens during the drain
            # window — a deploy must not truncate mid-generation.
            drained = self.decode_engine.drain(
                self.cfg.drain_timeout_s) and drained
        if self._tw:
            self._tw.emit(
                telemetry.KIND_HEALTH,
                health={"event": "serve_drain", "reason": reason,
                        "clean": drained})
        self.httpd.shutdown()
        self._done.set()
        log.info("drain complete (clean=%s)", drained)
        return drained

    def install_sigterm_drain(self) -> None:
        """SIGTERM → graceful drain, from the main thread (signal module
        requirement). The handler only spawns the drain thread — all real
        work happens off the signal path."""

        def _drain():
            try:
                self.shutdown("sigterm")
            except BaseException as e:  # noqa: BLE001 — surface, don't hang
                log.error("sigterm drain failed", exc_info=True)
                self._drain_error = ServeDrainError(e)
                # A failure inside shutdown() can fire before it reaches
                # httpd.shutdown()/_done.set(); do both here so
                # serve_forever() and shutdown() waiters unblock and the
                # stored error surfaces instead of the process hanging.
                self._done.set()
                self.httpd.shutdown()

        def _on_term(signum, frame):
            del signum, frame
            threading.Thread(
                target=_drain, name="dtf-serve-drain", daemon=True).start()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

    def serve_forever(self) -> None:
        """Block until shutdown() (or SIGTERM via the installed handler)."""
        log.info("serving on http://%s:%d (predict, healthz)",
                 self.host, self.port)
        self.httpd.serve_forever()
        self.httpd.server_close()
        if self._drain_error is not None:
            raise self._drain_error
