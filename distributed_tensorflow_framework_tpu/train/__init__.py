"""Training runtime: jitted steps, schedules, loop, hooks.

Replaces the reference's L2 (SURVEY.md §2 rows 3, 9, 10): loss + optimizer
wrapping (SyncReplicasOptimizer in sync mode), MonitoredTrainingSession's
step loop, and its hook set (StopAtStep, NaN guard, checkpoint, summaries).
The sync-replica barrier disappears: a jitted step over a sharded batch is
synchronous by construction.
"""

from distributed_tensorflow_framework_tpu.train.state import TrainState  # noqa: F401
from distributed_tensorflow_framework_tpu.train.loop import Trainer  # noqa: F401
