"""In-process anomaly detection + in-memory rollback (docs/RESILIENCE.md).

PR 2's recovery contract is *kill → relaunch → resume*: correct, but the
most expensive path we have (relaunch + restore + recompile) and overkill
for a single poisoned batch or transient loss spike. The systems in this
framework's lineage (TensorFlow's fault-tolerance story, TF-Replicator's
researcher-facing resilience contract) recover from transient numeric
faults *in process*; this module is that rung of the ladder:

  detect (here)  →  rollback + skip batch (train/loop.py)  →
  LR re-warmup (train/schedules.py, optional)  →
  escalate (NaNGuardHook → ANOMALY_ESCALATION_RC) only when the anomaly
  survives ``max_rollbacks`` consecutive recoveries.

Detection reads ONLY already-on-host metrics (the Trainer's metric-fetch
cadence), so the ladder adds no device syncs to off-interval steps. The
rollback ring holds device→host snapshots of the train state (the same
pack/unpack discipline as the async checkpoint pipeline, minus the disk):
restoring one costs a host→device transfer instead of a process relaunch.

Skip-batch semantics: a rollback restores MODEL state only — the data
iterator is deliberately NOT rewound. The batches consumed between the
snapshot and the anomaly (including the offending one) are gone from the
stream, so resuming forward replays the step COUNT with fresh data. That
is the point: re-feeding the poisoned batch would reproduce the anomaly.

Every rung emits versioned telemetry (``anomaly_detected`` / ``rollback``
/ ``batch_skipped``) rolled up by scripts/analyze_trace.py run summaries.
"""

from __future__ import annotations

import collections
import logging
import math
from dataclasses import dataclass, field
from typing import Any, Mapping

import jax

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.config import ResilienceConfig

log = logging.getLogger(__name__)


class PersistentAnomalyError(FloatingPointError):
    """The recovery ladder is exhausted: ``max_rollbacks`` consecutive
    rollbacks each landed back on an anomalous step (a poisoned data
    region, not a transient). Subclasses FloatingPointError so callers of
    the pre-ladder NaNGuardHook contract keep catching it; cli/train.py
    maps it to supervision.ANOMALY_ESCALATION_RC so the supervisor can
    classify the relaunch without feeding the crash-loop breaker.
    """

    def __init__(self, message: str, provenance: dict | None = None):
        super().__init__(message)
        self.provenance = provenance or {}


@dataclass
class Verdict:
    """One anomalous classification: what fired, on which metric."""

    anomaly: str            # non_finite_metric | loss_spike | grad_norm_explosion
    metric: str
    value: float | str
    step: int
    detail: dict = field(default_factory=dict)

    def to_health(self) -> dict:
        return {"anomaly": self.anomaly, "metric": self.metric,
                "value": str(self.value), **self.detail}


class AnomalyDetector:
    """Classify a step from its already-fetched host metrics.

    Three checks, cheapest first:
      * non-finite value in ANY numeric metric (the NanTensorHook class);
      * finite ``grad_norm`` above the hard ceiling ``grad_norm_max``;
      * ``loss`` more than ``loss_spike_zscore`` EWMA standard deviations
        above its running mean (needs ``min_observations`` clean fetches
        of warmup before it may fire — a cold EWMA has no baseline).

    ``observe`` feeds the EWMA and must only be called with CLEAN metrics
    — an anomalous loss folded into the baseline would teach the detector
    that spikes are normal.
    """

    def __init__(self, cfg: ResilienceConfig):
        self.cfg = cfg
        self._n = 0
        self._mean = 0.0
        self._var = 0.0

    @property
    def observations(self) -> int:
        return self._n

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def std(self) -> float:
        # Relative floor: a near-constant loss has ~zero EWMA variance and
        # would flag numeric jitter as an infinite-z spike.
        return max(math.sqrt(max(self._var, 0.0)),
                   1e-3 * abs(self._mean), 1e-8)

    def observe(self, metrics: Mapping[str, float]) -> None:
        loss = _finite_float(metrics.get("loss"))
        if loss is None:
            return
        if self._n == 0:
            self._mean, self._var = loss, 0.0
        else:
            beta = self.cfg.loss_ewma_beta
            diff = loss - self._mean
            self._mean += (1.0 - beta) * diff
            self._var = beta * (self._var + (1.0 - beta) * diff * diff)
        self._n += 1

    def classify(self, step: int, metrics: Mapping[str, float]) -> Verdict | None:
        for name, v in metrics.items():
            try:
                val = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(val):
                return Verdict("non_finite_metric", name, v, step)
        gmax = self.cfg.grad_norm_max
        gnorm = _finite_float(metrics.get("grad_norm"))
        if gmax > 0 and gnorm is not None and gnorm > gmax:
            return Verdict("grad_norm_explosion", "grad_norm", gnorm, step,
                           detail={"grad_norm_max": gmax})
        zmax = self.cfg.loss_spike_zscore
        loss = _finite_float(metrics.get("loss"))
        if (zmax > 0 and loss is not None
                and self._n >= max(1, self.cfg.min_observations)):
            z = (loss - self._mean) / self.std
            if z > zmax:
                return Verdict("loss_spike", "loss", loss, step,
                               detail={"zscore": round(z, 2),
                                       "ewma_mean": round(self._mean, 6),
                                       "ewma_std": round(self.std, 6)})
        return None


def _finite_float(v: Any) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


# ---------------------------------------------------------------- snapshots

def snapshot_state(state: Any) -> tuple[Any, Any]:
    """Device→host copy of a TrainState, checkpoint-style packed.

    The typed PRNG key is converted to raw key data first (the same
    discipline as ckpt/checkpoint.py's ``_pack``) so the host tree is
    plain arrays. Returns ``(host_tree, shardings_tree)`` — the shardings
    are captured so the restore lands every leaf on its original mesh
    placement, not a default device.
    """
    packed = state.replace(rng=jax.random.key_data(state.rng))
    shardings = jax.tree.map(lambda x: x.sharding, packed)
    host = jax.device_get(packed)
    return host, shardings


def restore_state(host: Any, shardings: Any, like: Any) -> Any:
    """Host→device restore of ``snapshot_state`` output. ``like`` is any
    live TrainState (its rng carries the key impl to re-wrap with)."""
    dev = jax.tree.map(jax.device_put, host, shardings)
    impl = jax.random.key_impl(like.rng)
    return dev.replace(rng=jax.random.wrap_key_data(dev.rng, impl=impl))


def _fully_addressable(state: Any) -> bool:
    ok = True
    for leaf in jax.tree.leaves(state):
        if hasattr(leaf, "is_fully_addressable"):
            ok = ok and bool(leaf.is_fully_addressable)
    return ok


@dataclass
class Snapshot:
    step: int
    host: Any
    shardings: Any
    data_state: dict | None = None


class SnapshotRing:
    """Bounded ring of in-memory state snapshots, newest-last."""

    def __init__(self, depth: int):
        self._ring: collections.deque[Snapshot] = collections.deque(
            maxlen=max(1, depth))

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def steps(self) -> list[int]:
        return [s.step for s in self._ring]

    def push(self, snap: Snapshot) -> None:
        self._ring.append(snap)

    def latest(self) -> Snapshot:
        return self._ring[-1]


# ------------------------------------------------------------------ manager

class RecoveryManager:
    """Policy + state for the in-process recovery ladder.

    Owned by the Trainer; the loop calls ``classify`` at every metric
    fetch, ``take_snapshot`` opportunistically on clean steps, and
    ``rollback`` on an anomaly while ``can_rollback()`` holds. When it
    does not, the loop sets ``exhausted`` and lets the anomalous metrics
    flow to the hooks — NaNGuardHook (the escalation tail) raises
    ``PersistentAnomalyError`` with the provenance collected here.
    """

    def __init__(self, cfg: ResilienceConfig,
                 telemetry_writer: telemetry.TelemetryWriter | None = None):
        self.cfg = cfg
        self.detector = AnomalyDetector(cfg)
        self.ring = SnapshotRing(cfg.snapshot_depth)
        self._telemetry = telemetry_writer
        self.consecutive_rollbacks = 0
        self.total_rollbacks = 0
        self.anomalies_detected = 0
        self.exhausted = False
        self.last_verdict: Verdict | None = None
        self._last_snapshot_step: int | None = None
        self._disabled_reason: str | None = None

    # -- telemetry helper -------------------------------------------------
    def _emit(self, kind: str, step: int, health: dict) -> None:
        if self._telemetry is not None:
            self._telemetry.emit(kind, step=step, health=health)

    @property
    def armed(self) -> bool:
        return self._disabled_reason is None

    def disable(self, reason: str) -> None:
        if self._disabled_reason is None:
            self._disabled_reason = reason
            log.warning(
                "in-memory rollback DISABLED (%s) — anomalies will "
                "escalate straight to the supervisor", reason,
            )

    # -- snapshots --------------------------------------------------------
    def take_snapshot(self, step: int, state: Any,
                      data_state: dict | None = None,
                      force: bool = False) -> bool:
        if not self.armed:
            return False
        if (not force and self._last_snapshot_step is not None
                and step - self._last_snapshot_step
                < max(1, self.cfg.snapshot_interval_steps)):
            return False
        if not _fully_addressable(state):
            # Multi-host sharded state: the device_get snapshot only sees
            # this process's shards (same restriction as the async saver's
            # snapshot path — checkpoint.async_save documents it).
            self.disable("train state is not fully addressable on this host")
            return False
        host, shardings = snapshot_state(state)
        self.ring.push(Snapshot(step=step, host=host, shardings=shardings,
                                data_state=dict(data_state or {})))
        self._last_snapshot_step = step
        return True

    # -- classification ---------------------------------------------------
    def classify(self, step: int, metrics: Mapping[str, float]) -> Verdict | None:
        """Classify one fetched-metrics step. Clean steps feed the EWMA
        baseline and reset the consecutive-rollback streak; anomalous
        steps emit ``anomaly_detected`` and return the verdict."""
        verdict = self.detector.classify(step, metrics)
        if verdict is None:
            self.detector.observe(metrics)
            self.consecutive_rollbacks = 0
            return None
        self.last_verdict = verdict
        self.anomalies_detected += 1
        log.warning(
            "anomaly detected at step %d: %s (%s=%s)",
            step, verdict.anomaly, verdict.metric, verdict.value,
        )
        self._emit(
            telemetry.KIND_ANOMALY, step,
            {**verdict.to_health(),
             "consecutive_rollbacks": self.consecutive_rollbacks},
        )
        return verdict

    # -- rollback ---------------------------------------------------------
    def can_rollback(self) -> bool:
        return (self.armed and len(self.ring) > 0
                and self.consecutive_rollbacks < self.cfg.max_rollbacks)

    def rollback(self, live_state: Any, from_step: int) -> tuple[Any, Snapshot]:
        """Restore the newest snapshot; returns ``(state, snapshot)``.
        Emits ``rollback`` and ``batch_skipped`` — the skipped range is
        the data consumed between the snapshot and the anomaly, which the
        resumed stream will never replay (skip-batch semantics)."""
        snap = self.ring.latest()
        state = restore_state(snap.host, snap.shardings, like=live_state)
        self.consecutive_rollbacks += 1
        self.total_rollbacks += 1
        log.warning(
            "rolling back: step %d -> %d (rollback %d/%d this incident, "
            "%d total)", from_step, snap.step, self.consecutive_rollbacks,
            self.cfg.max_rollbacks, self.total_rollbacks,
        )
        self._emit(telemetry.KIND_ROLLBACK, from_step, {
            "from_step": from_step, "to_step": snap.step,
            "consecutive_rollbacks": self.consecutive_rollbacks,
        })
        self._emit(telemetry.KIND_BATCH_SKIPPED, from_step, {
            "from_step": snap.step + 1, "to_step": from_step,
            "batches": from_step - snap.step,
        })
        return state, snap

    # -- escalation -------------------------------------------------------
    def provenance(self) -> dict:
        v = self.last_verdict
        return {
            "anomaly": v.anomaly if v else None,
            "metric": v.metric if v else None,
            "value": str(v.value) if v else None,
            "step": v.step if v else None,
            "consecutive_rollbacks": self.consecutive_rollbacks,
            "max_rollbacks": self.cfg.max_rollbacks,
            "total_rollbacks": self.total_rollbacks,
            "snapshot_steps": self.ring.steps,
            "disabled_reason": self._disabled_reason,
        }

    def escalation_message(self) -> str:
        v = self.last_verdict
        what = (f"{v.anomaly} ({v.metric}={v.value}) at step {v.step}"
                if v else "anomaly")
        if not self.armed:
            why = f"in-memory rollback disabled: {self._disabled_reason}"
        elif len(self.ring) == 0:
            why = "no snapshot available to roll back to"
        else:
            why = (f"{self.consecutive_rollbacks} consecutive rollbacks "
                   f"all landed back on a bad step (max_rollbacks="
                   f"{self.cfg.max_rollbacks})")
        return (
            f"Persistent anomaly: {what} — {why}. Escalating to the "
            f"supervisor (rc=ANOMALY_ESCALATION_RC): this looks like a "
            f"poisoned data region or a deterministic numeric bug, not a "
            f"transient."
        )
