"""Training hooks — MonitoredTrainingSession's hook set, SPMD-style.

SURVEY.md §2 row 10: the reference's loop runs under
MonitoredTrainingSession with StopAtStepHook, NanTensorHook, checkpoint
saver and summary saver hooks. Same extension points here, as plain Python
objects driven by the Trainer. Hooks only ever touch host-side metric
values (already-fetched scalars) so they never force extra device syncs.
"""

from __future__ import annotations

from typing import Any, Mapping, Protocol

import math


class Hook(Protocol):
    def on_start(self, trainer: Any) -> None: ...
    def after_step(self, trainer: Any, step: int,
                   metrics: Mapping[str, float] | None) -> None: ...
    def on_end(self, trainer: Any) -> None: ...


class BaseHook:
    def on_start(self, trainer) -> None:
        pass

    def after_step(self, trainer, step, metrics) -> None:
        pass

    def on_end(self, trainer) -> None:
        pass


class NaNGuardHook(BaseHook):
    """NanTensorHook analogue: abort when the loss goes non-finite.

    Checks only at metric-fetch steps (metrics is None otherwise) to avoid
    per-step device→host syncs.
    """

    def after_step(self, trainer, step, metrics) -> None:
        if metrics is None:
            return
        for name, v in metrics.items():
            try:
                val = float(v)  # accepts python/numpy scalars + 0-d arrays
            except (TypeError, ValueError):
                continue
            if not math.isfinite(val):
                raise FloatingPointError(
                    f"Non-finite metric {name}={v} at step {step} — aborting "
                    f"(NaNGuardHook; reference NanTensorHook contract)"
                )


class ThroughputHook(BaseHook):
    """Tracks examples/sec(/chip) — the BASELINE.json tracked metric."""

    def __init__(self, batch_size: int, num_chips: int):
        from distributed_tensorflow_framework_tpu.core.metrics import ThroughputMeter

        self.batch_size = batch_size
        self.meter = ThroughputMeter(num_chips)

    def on_start(self, trainer) -> None:
        self.meter.start()

    def after_step(self, trainer, step, metrics) -> None:
        self.meter.update(self.batch_size)

    def rates(self) -> dict[str, float]:
        return self.meter.rates()


class LoggingHook(BaseHook):
    def __init__(self, writer, interval: int, throughput: ThroughputHook | None = None):
        self.writer = writer
        self.interval = max(1, interval)
        self.throughput = throughput

    def after_step(self, trainer, step, metrics) -> None:
        # The Trainer only fetches metrics at its own log cadence; the
        # interval here additionally guards custom loops that fetch more
        # often (final step always logs).
        if metrics is None:
            return
        if step % self.interval and step < trainer.config.train.total_steps:
            return
        out = dict(metrics)
        if self.throughput is not None:
            out.update(self.throughput.rates())
            self.throughput.meter.reset()
        self.writer.write(step, out)


class CheckpointHook(BaseHook):
    def __init__(self, manager, interval: int):
        self.manager = manager
        self.interval = max(1, interval)

    def after_step(self, trainer, step, metrics) -> None:
        if step > 0 and step % self.interval == 0:
            self.manager.save(step, trainer.state,
                              dataset_state=trainer.data_ckpt_state)

    def on_end(self, trainer) -> None:
        self.manager.save(int(trainer.host_step), trainer.state,
                          dataset_state=trainer.data_ckpt_state, force=True)
        self.manager.wait_until_finished()


class ProfileHook(BaseHook):
    """Captures an XPlane trace over steps [start, stop) — the analogue of
    the reference's tf.profiler/timeline option (SURVEY.md §5)."""

    def __init__(self, logdir: str, start: int, stop: int):
        self.logdir = logdir
        # after_step first fires at step=1, so a start of 0 means "from the
        # beginning"; the trace then covers steps (start, stop].
        self.start = max(1, start)
        self.stop = stop
        self._active = False

    def after_step(self, trainer, step, metrics) -> None:
        import jax

        if step >= self.start and step < self.stop and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop and self._active:
            jax.block_until_ready(trainer.state.params)
            jax.profiler.stop_trace()
            self._active = False

    def on_end(self, trainer) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


class EvalHook(BaseHook):
    """Mid-training eval — the reference's eval loop (SURVEY.md §3.4).

    ``num_batches`` caps each firing (train.eval_steps); None walks the
    full validation set every interval — usually only wanted for small
    sets.
    """

    def __init__(self, eval_fn, interval: int, *, num_batches: int | None = None):
        self.eval_fn = eval_fn
        self.interval = max(1, interval)
        self.num_batches = num_batches

    def after_step(self, trainer, step, metrics) -> None:
        if step > 0 and step % self.interval == 0:
            self.eval_fn(step, num_batches=self.num_batches)
