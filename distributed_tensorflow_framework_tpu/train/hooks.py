"""Training hooks — MonitoredTrainingSession's hook set, SPMD-style.

SURVEY.md §2 row 10: the reference's loop runs under
MonitoredTrainingSession with StopAtStepHook, NanTensorHook, checkpoint
saver and summary saver hooks. Same extension points here, as plain Python
objects driven by the Trainer. Hooks only ever touch host-side metric
values (already-fetched scalars) so they never force extra device syncs.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Any, Mapping, Protocol

from distributed_tensorflow_framework_tpu.core import telemetry

log = logging.getLogger(__name__)


class Hook(Protocol):
    def on_start(self, trainer: Any) -> None: ...
    def after_step(self, trainer: Any, step: int,
                   metrics: Mapping[str, float] | None) -> None: ...
    def on_end(self, trainer: Any) -> None: ...


class BaseHook:
    def on_start(self, trainer) -> None:
        pass

    def after_step(self, trainer, step, metrics) -> None:
        pass

    def on_end(self, trainer) -> None:
        pass


class NaNGuardHook(BaseHook):
    """NanTensorHook analogue: abort when the loss goes non-finite.

    Checks only at metric-fetch steps (metrics is None otherwise) to avoid
    per-step device→host syncs. The abort carries provenance — which
    metric, which step, and the last-good checkpoint to restart from — and
    lands in the run's telemetry as a ``failure`` event, so post-mortems
    don't start from a bare stack trace.

    With the in-process recovery ladder armed (train/anomaly.py) this hook
    is the ladder's ESCALATION TAIL, not the first responder: a rolled-back
    anomaly never reaches it (the Trainer suppresses the poisoned metrics),
    so a non-finite value here means the ladder is exhausted — the abort
    becomes ``PersistentAnomalyError`` carrying the ladder's provenance,
    which cli/train.py maps to supervision.ANOMALY_ESCALATION_RC so the
    supervisor can classify poisoned-data-region vs transient.
    """

    def after_step(self, trainer, step, metrics) -> None:
        if metrics is None:
            return
        for name, v in metrics.items():
            try:
                val = float(v)  # accepts python/numpy scalars + 0-d arrays
            except (TypeError, ValueError):
                continue
            if not math.isfinite(val):
                ckpt = self._last_good_checkpoint(trainer)
                self._emit_failure(trainer, step, name, v, ckpt)
                restart = (
                    f"restart from {ckpt}" if ckpt
                    else "no checkpoint saved — restart from scratch"
                )
                rec = getattr(trainer, "recovery", None)
                if rec is not None and rec.exhausted:
                    from distributed_tensorflow_framework_tpu.train.anomaly import (
                        PersistentAnomalyError)

                    raise PersistentAnomalyError(
                        f"{rec.escalation_message()} Non-finite metric "
                        f"{name}={v} at step {step}. Last good checkpoint: "
                        f"{restart}.",
                        provenance=rec.provenance(),
                    )
                raise FloatingPointError(
                    f"Non-finite metric {name}={v} at step {step} — aborting "
                    f"(NaNGuardHook; reference NanTensorHook contract). "
                    f"Last good checkpoint: {restart}."
                )

    @staticmethod
    def _last_good_checkpoint(trainer) -> str | None:
        mgr = getattr(trainer, "_ckpt_manager", None)
        if mgr is None:
            return None
        try:
            last = mgr.latest_step()
        except Exception:
            return None
        if last is None:
            return None
        return os.path.join(trainer.config.checkpoint.directory, str(last))

    @staticmethod
    def _emit_failure(trainer, step, name, value, ckpt) -> None:
        writer = getattr(trainer, "writer", None)
        if writer is None or not hasattr(writer, "telemetry"):
            return
        writer.telemetry.emit(
            telemetry.KIND_FAILURE,
            step=step,
            health={"failure": "non_finite_metric", "metric": name,
                    "value": str(value),
                    "last_good_checkpoint": ckpt or ""},
        )


class ThroughputHook(BaseHook):
    """Tracks examples/sec(/chip) — the BASELINE.json tracked metric."""

    def __init__(self, batch_size: int, num_chips: int):
        from distributed_tensorflow_framework_tpu.core.metrics import ThroughputMeter

        self.batch_size = batch_size
        self.meter = ThroughputMeter(num_chips)

    def on_start(self, trainer) -> None:
        self.meter.start()

    def after_step(self, trainer, step, metrics) -> None:
        self.meter.update(self.batch_size)

    def rates(self) -> dict[str, float]:
        return self.meter.rates()


class LoggingHook(BaseHook):
    def __init__(self, writer, interval: int, throughput: ThroughputHook | None = None):
        self.writer = writer
        self.interval = max(1, interval)
        self.throughput = throughput

    def after_step(self, trainer, step, metrics) -> None:
        # The Trainer only fetches metrics at its own log cadence; the
        # interval here additionally guards custom loops that fetch more
        # often (final step always logs).
        if metrics is None:
            return
        if step % self.interval and step < trainer.config.train.total_steps:
            return
        out = dict(metrics)
        if self.throughput is not None:
            out.update(self.throughput.rates())
            self.throughput.meter.reset()
        self.writer.write(
            step, out,
            collectives=getattr(trainer, "collectives_summary", None),
        )


class CheckpointHook(BaseHook):
    """Interval saver. With ``checkpoint.async_save`` on, ``save`` returns
    after the device→host snapshot and the commit (orbax write + manifest
    + fsync) lands on the background saver thread — the step loop is not
    blocked for the write. ``on_end`` is the flush path: the final
    force-save plus ``wait_until_finished`` block until every in-flight
    commit is durable, so both normal completion and SIGTERM graceful
    preemption (rc 83) exit with nothing half-written."""

    def __init__(self, manager, interval: int):
        self.manager = manager
        self.interval = max(1, interval)

    def _traced_save(self, trainer, step: int, *, force: bool = False):
        """Save under a ``ckpt.save`` span when the trainer carries a
        tracer + run span (core/tracing.py) — with async_save on, the
        span covers the device→host snapshot the step loop actually
        blocks on, not the background commit."""
        tracer = getattr(trainer, "tracer", None)
        run_span = getattr(trainer, "run_span", None)
        span = (tracer.start("ckpt.save", run_span, step=step, force=force)
                if tracer is not None and run_span is not None else None)
        try:
            self.manager.save(step, trainer.state,
                              dataset_state=trainer.data_ckpt_state,
                              force=force)
        finally:
            if span is not None:
                span.end()

    def after_step(self, trainer, step, metrics) -> None:
        if step > 0 and step % self.interval == 0:
            self._traced_save(trainer, step)

    def on_end(self, trainer) -> None:
        self._traced_save(trainer, int(trainer.host_step), force=True)
        self.manager.wait_until_finished()


class HeartbeatHook(BaseHook):
    """Liveness file for external watchdogs (scripts/train_resilient.py).

    Atomically rewrites a small JSON file — run_id, pid, the last COMPLETED
    step, wall time, the last fetched metrics — every ``min_interval_s`` of
    wall time. A supervisor distinguishes "slow" from "wedged" by the
    record's age instead of attaching a debugger to a silent process (the
    XLA:CPU collective-freeze failure mode, core/platform.py), and asserts
    forward progress — not just liveness — from ``last_completed_step``.

    Write discipline: pid-suffixed temp file (a dying predecessor's
    half-written temp can never collide with ours), contents fsync'd, then
    one atomic ``os.replace`` — readers see the old record or the new one,
    never a torn file, on every platform where replace is atomic (POSIX
    and Windows alike).
    """

    def __init__(self, path: str, *, min_interval_s: float = 10.0):
        self.path = path
        self.min_interval_s = min_interval_s
        self._last_write = 0.0
        self._last_metrics: dict | None = None

    def on_start(self, trainer) -> None:
        self._write(trainer, step=int(trainer.host_step), status="running")

    def after_step(self, trainer, step, metrics) -> None:
        if metrics is not None:
            self._last_metrics = {k: float(v) for k, v in metrics.items()}
        now = time.time()
        if now - self._last_write >= self.min_interval_s:
            self._write(trainer, step=step, status="running", now=now)

    def on_end(self, trainer) -> None:
        status = ("preempted" if getattr(trainer, "preempted", False)
                  else "finished")
        self._write(trainer, step=int(trainer.host_step), status=status)

    def _write(self, trainer, *, step, status, now=None) -> None:
        now = time.time() if now is None else now
        record = {
            "schema": telemetry.SCHEMA,
            "run_id": getattr(trainer, "run_id", ""),
            "status": status,
            # "step" kept for readers of the original record shape;
            # last_completed_step is the explicit progress counter the
            # watchdog's crash-loop accounting uses.
            "step": step,
            "last_completed_step": step,
            "t": now,
            "pid": os.getpid(),
            "last_metrics": self._last_metrics,
        }
        tmp = f"{self.path}.{os.getpid()}.tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(record, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)  # atomic: readers never see a torn file
        self._last_write = now


class MoECollapseHook(BaseHook):
    """Detects expert-routing collapse from the step metrics.

    Collapse signatures (models/moe.py): ``moe_drop_frac`` climbing toward
    1 - 1/num_experts (all tokens racing to one expert, the rest dropped
    by capacity) and ``moe_aux_loss`` rising well above its balanced value
    of ~1.0. Either alone can be a transient; this hook warns loudly —
    structured, with the run context — once a threshold holds for
    ``patience`` consecutive metric fetches, and emits a telemetry
    ``health`` event so the collapse is visible in the run's event stream,
    not just the console. It never aborts: collapsed runs often still
    carry signal and the operator may want the checkpoint.
    """

    def __init__(self, *, drop_frac_threshold: float = 0.35,
                 aux_loss_threshold: float = 2.0, patience: int = 2):
        self.drop_frac_threshold = drop_frac_threshold
        self.aux_loss_threshold = aux_loss_threshold
        self.patience = max(1, patience)
        self._streak = 0
        self.fired_steps: list[int] = []

    def after_step(self, trainer, step, metrics) -> None:
        if metrics is None:
            return
        drop = metrics.get("moe_drop_frac")
        aux = metrics.get("moe_aux_loss")
        if drop is None and aux is None:
            return
        violations = {}
        if drop is not None and float(drop) > self.drop_frac_threshold:
            violations["moe_drop_frac"] = {
                "value": float(drop), "threshold": self.drop_frac_threshold}
        if aux is not None and float(aux) > self.aux_loss_threshold:
            violations["moe_aux_loss"] = {
                "value": float(aux), "threshold": self.aux_loss_threshold}
        if not violations:
            self._streak = 0
            return
        self._streak += 1
        if self._streak < self.patience:
            return
        self.fired_steps.append(step)
        payload = {
            "warning": "moe_collapse",
            "step": step,
            "streak": self._streak,
            "violations": violations,
        }
        log.warning("MOE COLLAPSE SUSPECTED %s", json.dumps(payload))
        writer = getattr(trainer, "writer", None)
        if writer is not None and hasattr(writer, "telemetry"):
            writer.telemetry.emit(
                telemetry.KIND_HEALTH, step=step,
                health={"warning": "moe_collapse", "streak": self._streak,
                        **{f"{k}_value": v["value"]
                           for k, v in violations.items()}},
            )


class ProfileHook(BaseHook):
    """Captures an XPlane trace over steps [start, stop) — the analogue of
    the reference's tf.profiler/timeline option (SURVEY.md §5).

    Alongside the trace it writes the compiled train step's optimized HLO
    (``train_step.hlo.txt``) when the Trainer captured it: trace events
    carry bare HLO instruction names, and the HLO text's op_name metadata
    is what lets scripts/analyze_trace.py attribute them to named scopes
    (optimizer_update etc.)."""

    def __init__(self, logdir: str, start: int, stop: int):
        self.logdir = logdir
        # after_step first fires at step=1, so a start of 0 means "from the
        # beginning"; the trace then covers steps (start, stop].
        self.start = max(1, start)
        self.stop = stop
        self._active = False

    def _dump_hlo(self, trainer) -> None:
        hlo = getattr(trainer, "compiled_hlo", None)
        if not hlo:
            return
        os.makedirs(self.logdir, exist_ok=True)
        path = os.path.join(self.logdir, "train_step.hlo.txt")
        with open(path, "w") as fh:
            fh.write(hlo)
        log.info("wrote compiled HLO for trace attribution: %s", path)

    def after_step(self, trainer, step, metrics) -> None:
        import jax

        if step >= self.start and step < self.stop and not self._active:
            self._dump_hlo(trainer)
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop and self._active:
            jax.block_until_ready(trainer.state.params)
            jax.profiler.stop_trace()
            self._active = False

    def on_end(self, trainer) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


class EvalHook(BaseHook):
    """Mid-training eval — the reference's eval loop (SURVEY.md §3.4).

    ``num_batches`` caps each firing (train.eval_steps); None walks the
    full validation set every interval — usually only wanted for small
    sets.
    """

    def __init__(self, eval_fn, interval: int, *, num_batches: int | None = None):
        self.eval_fn = eval_fn
        self.interval = max(1, interval)
        self.num_batches = num_batches

    def after_step(self, trainer, step, metrics) -> None:
        if step > 0 and step % self.interval == 0:
            self.eval_fn(step, num_batches=self.num_batches)
