"""The training loop — MonitoredTrainingSession, SPMD-style.

SURVEY.md §3.1: the reference's hot loop is ``while not stop:
session.run(train_op)`` under MonitoredTrainingSession (checkpoint restore
on start, hooks each step, chief-only services). The Trainer keeps that
contract: build → maybe-restore → step loop with hooks → final save, with
two differences that matter on TPU:

  * metrics are fetched only at log intervals — each step returns device
    arrays that are NOT synced unless a hook needs them, so the loop stays
    ahead of the device (async dispatch);
  * there are no session/graph handles: the "session" is a compiled
    function and the "server" is the mesh.
"""

from __future__ import annotations

import collections
import logging
import os
import sys
import time
from typing import Any

import jax

from distributed_tensorflow_framework_tpu.core.config import ExperimentConfig
from distributed_tensorflow_framework_tpu.core import (
    cluster, faults, goodput, memstats, profiling, supervision, telemetry,
    tracing)
from distributed_tensorflow_framework_tpu.core.mesh import MeshRuntime, initialize_runtime
from distributed_tensorflow_framework_tpu.core.metrics import MetricWriter, setup_logging
from distributed_tensorflow_framework_tpu.data import get_dataset, packing
from distributed_tensorflow_framework_tpu.data import shard as data_shard
from distributed_tensorflow_framework_tpu.data.infeed import (
    InfeedStallError, prefetch_to_device, to_global)
from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.train import anomaly as anomaly_lib
from distributed_tensorflow_framework_tpu.train import hooks as hooks_lib
from distributed_tensorflow_framework_tpu.train import schedules
from distributed_tensorflow_framework_tpu.train.step import StepBuilder

log = logging.getLogger(__name__)


def _poison_batch(batch: dict) -> dict:
    """nan_grads fault effect: NaN every floating-point input array so the
    step's loss and gradients go non-finite and the NaN-provenance path
    (NaNGuardHook → failure telemetry → abort) is exercised end-to-end."""
    import jax.numpy as jnp

    return {
        k: v * jnp.asarray(float("nan"), dtype=v.dtype)
        if jnp.issubdtype(v.dtype, jnp.floating) else v
        for k, v in batch.items()
    }


def _scale_batch(batch: dict, factor: float) -> dict:
    """loss_spike fault effect: blow up the floating-point inputs by a
    large FINITE factor — the loss jumps orders of magnitude but stays
    finite, so only the EWMA z-score rung of the detector can catch it
    (the non-finite check must not)."""
    import jax.numpy as jnp

    return {
        k: v * jnp.asarray(factor, dtype=v.dtype)
        if jnp.issubdtype(v.dtype, jnp.floating) else v
        for k, v in batch.items()
    }


class Trainer:
    def __init__(self, config: ExperimentConfig, runtime: MeshRuntime | None = None):
        setup_logging()
        # Startup-latency clock: construction → first completed step covers
        # restore + input build + compile, the relaunch cost a supervisor
        # pays on every preemption (emitted as a KIND_STARTUP event).
        self._init_t = time.perf_counter()
        self._init_mono = time.monotonic()  # train.startup span backfill
        self._startup_emitted = False
        self._restored_step: int | None = None
        self.config = config
        self.runtime = runtime or initialize_runtime(config.mesh)
        self.mesh = self.runtime.mesh
        self.dataset = get_dataset(
            config.data,
            process_index=self.runtime.process_index,
            process_count=self.runtime.process_count,
        )
        self.builder = StepBuilder(config, self.mesh)
        self.writer = MetricWriter(
            logdir=(config.checkpoint.directory or None),
            is_chief=self.runtime.is_chief,
            process_index=self.runtime.process_index,
            process_count=self.runtime.process_count,
        )
        self.run_id = self.writer.run_id
        # In-process recovery ladder (train/anomaly.py): detect → rollback
        # → re-warmup → escalate. None when resilience.rollback=false —
        # the loop then behaves exactly as before this rung existed
        # (NaNGuardHook aborts, supervisor relaunches from checkpoint).
        self.recovery = (
            anomaly_lib.RecoveryManager(
                config.resilience, telemetry_writer=self.writer.telemetry)
            if config.resilience.rollback else None
        )
        # Wall-clock accountant (core/goodput.py): absorbs StepTimer
        # phases and listens on the telemetry stream (ckpt_save blocked-ms
        # from the saver thread), so every second of this process lands in
        # a KIND_GOODPUT bucket. Backdated to _init_t: the runtime/dataset
        # build above must be inside the wall the startup bucket charges.
        self.goodput = goodput.GoodputLedger(
            self.writer.telemetry,
            interval_s=config.train.goodput_interval_s,
            t0_perf=self._init_t,
            process_id=(self.runtime.process_index
                        if self.runtime.process_count > 1 else None))
        self._startup_accounted = False
        # Periodic HBM sampling (core/memstats.py): device.memory_stats()
        # where the backend has it, host RSS where it doesn't.
        self.memstats = memstats.MemoryMonitor(
            self.writer.telemetry,
            interval_s=config.train.memory_interval_s, source="train")
        # Distributed tracing (core/tracing.py): spans for this worker's
        # run/startup/step-windows/ckpt-saves/rollbacks, parented on the
        # gang supervisor's attempt span when DTF_TRACE_CTX is set — the
        # whole gang then reconstructs as ONE supervisor-rooted tree.
        self.tracer = tracing.Tracer(
            self.writer.telemetry if config.trace.enabled else None,
            service=f"worker{self.runtime.process_index}")
        self._trace_parent = tracing.env_context()
        self.tracer.adopt(self._trace_parent)
        self.run_span: tracing.Span | None = None  # opened by train()
        # Flight recorder: recent telemetry ring, dumped on anomaly
        # escalation, graceful preemption, or SIGUSR1 — forensics that
        # survive a SIGKILLed or torn-JSONL attempt.
        self.flightrec = tracing.FlightRecorder(
            config.trace.ring_size,
            dump_dir=(config.trace.dump_dir
                      or config.checkpoint.directory or None),
            tracer=self.tracer).attach(self.writer.telemetry)
        self.flightrec.install_sigusr1()
        # Set by _rebuild_with_rewarmup: the next dispatch re-jits, so its
        # wall time belongs in the recompile bucket, not step_compute.
        self._recompile_pending = False
        self.state: Any = None
        self.host_step = 0
        self._ckpt_manager = None
        # True once a SIGTERM was honored gracefully (in-flight step
        # finished, checkpoint saved by CheckpointHook.on_end) — the CLI
        # exits supervision.GRACEFUL_PREEMPT_RC on it.
        self.preempted = False
        # Per-collective (calls, bytes) recorded while tracing the train
        # step; None until the first dispatch compiles. Shape-static, so
        # one trace describes every step of the executable.
        self.collectives_summary: dict[str, int] | None = None
        # Iterator snapshot aligned with host_step (see data/infeed.py).
        self.data_ckpt_state: dict = self.dataset.state()

    # -------------------------------------------------------------- setup --
    def build(self) -> None:
        self.writer.telemetry.emit_run_meta(
            argv=list(sys.argv),
            config_name=self.config.name,
            spmd_mode=self.config.train.spmd_mode,
            model=self.config.model.name,
            dataset=self.config.data.name,
            global_batch_size=self.config.data.global_batch_size,
            mesh={k: int(v) for k, v in self.mesh.shape.items()},
            process_count=self.runtime.process_count,
            process_index=self.runtime.process_index,
        )
        # Shard-assignment record (data/shard.py): validate this host's
        # slice of every global batch against the gang AND the mesh's
        # data-parallel extent before the first batch moves, and put the
        # layout in the telemetry record (KIND_DATA_SHARD) — the exactly-
        # once drill reads it back per attempt.
        mesh_shape = {k: int(v) for k, v in self.mesh.shape.items()}
        data_parallel = (mesh_shape.get("data", 1)
                         * mesh_shape.get("fsdp", 1)) or None
        shard_layout = data_shard.shard_plan(
            data_shard.ShardAssignment(
                process_index=self.runtime.process_index,
                process_count=self.runtime.process_count),
            global_batch=self.config.data.global_batch_size,
            data_parallel=data_parallel,
            shard_mode=self.config.data.shard_mode)
        self.writer.telemetry.emit(
            telemetry.KIND_DATA_SHARD, step=self.host_step,
            shard=shard_layout)
        stages = int(getattr(self.config.model, "pipeline_stages", 0) or 0)
        if stages > 0:
            # One record of the resolved schedule so step-time rollups
            # (telemetry.summarize_events) read against the right bubble.
            from distributed_tensorflow_framework_tpu.parallel import (
                schedule as pipe_sched,
            )

            name = self.config.model.pipeline_schedule
            micro = (self.config.model.pipeline_microbatches or stages)
            virtual = pipe_sched.resolve_virtual(
                name, stages, micro,
                self.config.model.pipeline_virtual_stages,
                self.config.model.num_layers)
            self.writer.telemetry.emit(
                telemetry.KIND_PIPELINE,
                schedule=name, stages=stages, microbatches=micro,
                virtual_stages=virtual,
                bubble_frac=pipe_sched.bubble_frac(
                    name, stages, micro, virtual),
                peak_inflight=pipe_sched.peak_inflight(
                    name, stages, micro, virtual),
            )
        # Peek one batch for shapes, then restore the stream to the start.
        start_state = self.dataset.state()
        host_batch = next(self.dataset)
        self.dataset.restore(start_state)
        sample = to_global(host_batch, self.mesh)
        # Kept for post-rollback re-jitting (LR re-warmup rebuilds the
        # optimizer, which needs a recompile against the same shapes).
        self._sample = sample
        self.state = self.builder.init_state(self.config.train.seed, sample)
        self.train_step = self.builder.make_train_step(sample)
        if getattr(self.builder, "_zero", False):
            # One record of the static shard/bucket plan so byte and
            # step-time rollups read against the overlap structure that
            # produced them (parallel/zero.plan_summary).
            from distributed_tensorflow_framework_tpu.parallel import zero
            self.writer.telemetry.emit(
                telemetry.KIND_ZERO_UPDATE,
                **zero.plan_summary(
                    self.builder._zero_plan,
                    wire_dtype=self.config.parallel.collective_dtype or None,
                    block_size=self.config.parallel.collective_block_size,
                ),
            )
        # Optimized-HLO capture for trace attribution (ProfileHook dumps
        # it next to the .xplane.pb). Only when profiling is armed: the
        # explicit lower+compile does not populate the jit call cache, so
        # it costs one extra compile — acceptable for a profiling run,
        # not for every training launch.
        self.compiled_hlo = None
        tcfg = self.config.train
        profiled = tcfg.profile_stop > tcfg.profile_start and self.runtime.is_chief
        if profiled or (tcfg.memory_analysis and self.runtime.is_chief):
            try:
                # This lower+compile populates the jit call cache, so the
                # loop's first-dispatch tally would see an already-traced
                # step — capture the collective counters here instead.
                with coll.tally() as tly:
                    lowered = self.train_step.lower(self.state, sample)
                self.collectives_summary = tly.summary()
                compiled = lowered.compile()
                if profiled:
                    self.compiled_hlo = compiled.as_text()
                # Static memory budget of the step (KIND_MEMORY with
                # extra.analysis) — free here, the compile is already paid.
                self.memstats.capture_compiled(compiled, label="train_step")
            except Exception:
                log.warning("could not capture compiled HLO", exc_info=True)
        # eval_step compiles from the EVAL stream's sample batch (its
        # element spec differs from training: weight key, no aug). Built
        # HERE rather than at the first evaluate() when eval will run, so
        # any eval-config error (e.g. a native reader with no exact-eval
        # path) fails at startup — not hours in, after training finishes.
        self.eval_step = None
        # eval_steps > 0 is the single eval on-switch (eval_interval alone
        # does nothing — default_hooks logs that case), so only then pay
        # the eval pipeline build + compile up front.
        if self.config.train.eval_steps > 0:
            self._ensure_eval()
        # Checkpoint manager + auto-restore (MonitoredTrainingSession
        # contract: restore latest from checkpoint_dir if present).
        if self.config.checkpoint.restore_step >= 0 and not (
                self.config.checkpoint.directory
                and self.config.checkpoint.restore):
            # The knob's contract is fail-loudly; silently starting from
            # scratch because restore is off would be the exact fallback
            # it exists to prevent.
            raise ValueError(
                "checkpoint.restore_step set but restoring is disabled — "
                "need checkpoint.directory non-empty and "
                "checkpoint.restore=true"
            )
        if self.config.checkpoint.directory:
            from distributed_tensorflow_framework_tpu.ckpt import CheckpointManager

            self._ckpt_manager = CheckpointManager(
                self.config.checkpoint, is_chief=self.runtime.is_chief,
                telemetry_writer=self.writer.telemetry,
                mesh=self.mesh,
                process_count=self.runtime.process_count,
            )
            # Data-plane plumbing for the manifest commit record + restore
            # gate (data/shard.py): the dataset's repartition capability
            # decides whether an N→M refit may reuse its state, and
            # data.resume_strict gates the digest/host-count checks.
            self._ckpt_manager.set_data_sources(
                repartition=self.dataset.repartition,
                resume_strict=self.config.data.resume_strict)
            if self.config.checkpoint.restore:
                want = self.config.checkpoint.restore_step
                if want >= 0 and want not in self._ckpt_manager.all_steps():
                    # Saver contract: asking for a specific snapshot that
                    # does not exist (never saved, or GC'd by max_to_keep)
                    # must fail loudly, not fall back to latest.
                    raise ValueError(
                        f"checkpoint.restore_step={want} not found in "
                        f"{self.config.checkpoint.directory!r} (available: "
                        f"{sorted(self._ckpt_manager.all_steps())})"
                    )
                restored = self._ckpt_manager.restore(
                    self.state, dataset=self.dataset,
                    step=want if want >= 0 else None)
                if restored is not None:
                    self.state = restored
                    self.host_step = int(jax.device_get(self.state.step))
                    self._restored_step = self.host_step
                    # Re-align the checkpointable snapshot with the
                    # RESTORED stream position: the __init__ snapshot is
                    # the initial state, and a rollback baseline built
                    # from it would mis-compute skip ordinals.
                    self.data_ckpt_state = self.dataset.state()
                    log.info("Restored checkpoint at step %d", self.host_step)

    def default_hooks(self) -> list:
        cfg = self.config
        tp = hooks_lib.ThroughputHook(
            batch_size=cfg.data.global_batch_size,
            num_chips=self.runtime.global_device_count,
        )
        hooks = [tp, hooks_lib.LoggingHook(self.writer, cfg.train.log_interval, tp)]
        if cfg.train.nan_guard:
            hooks.append(hooks_lib.NaNGuardHook())
        if cfg.checkpoint.directory and (
                self.runtime.is_chief or self.runtime.process_count > 1):
            # Gang runs: EVERY worker beats its own heartbeat-p<i>.json so
            # the cluster supervisor can tell a hung worker from a hung
            # gang; single-process runs keep the legacy heartbeat.json.
            hooks.append(hooks_lib.HeartbeatHook(
                cluster.heartbeat_path(
                    cfg.checkpoint.directory,
                    self.runtime.process_index,
                    self.runtime.process_count),
                min_interval_s=cfg.cluster.heartbeat_interval_s,
            ))
        if cfg.model.num_experts > 0:
            hooks.append(hooks_lib.MoECollapseHook())
        if self._ckpt_manager is not None:
            hooks.append(
                hooks_lib.CheckpointHook(
                    self._ckpt_manager, cfg.checkpoint.save_interval_steps
                )
            )
        if cfg.train.eval_interval > 0:
            if cfg.train.eval_steps > 0:
                # Mid-training evals are BOUNDED by eval_steps (a full
                # 50k-image pass every interval would stall training); the
                # final eval and --eval-only walk the complete set.
                hooks.append(hooks_lib.EvalHook(
                    self.evaluate, cfg.train.eval_interval,
                    num_batches=cfg.train.eval_steps,
                ))
            else:
                # eval_steps=0 disables eval everywhere — don't silently
                # flip to a full-set pass per interval.
                log.warning(
                    "train.eval_interval=%d but eval_steps=0 — mid-training "
                    "eval disabled", cfg.train.eval_interval,
                )
        if cfg.train.profile_stop > cfg.train.profile_start and self.runtime.is_chief:
            trace_dir = os.path.join(
                cfg.checkpoint.directory or "/tmp/dtf_tpu", "traces"
            )
            hooks.append(
                hooks_lib.ProfileHook(
                    trace_dir, cfg.train.profile_start, cfg.train.profile_stop
                )
            )
        return hooks

    # --------------------------------------------------------------- train --
    def train(self, hooks: list | None = None) -> dict[str, float]:
        if self.state is None:
            self.build()
        ck = self.config.checkpoint
        if (self._ckpt_manager is not None and ck.restore_step >= 0
                and ck.restore_step < (self._ckpt_manager.latest_step() or 0)):
            # Saving a branched lineage into a directory that already holds
            # NEWER steps would silently no-op at every already-saved step
            # (CheckpointManager.save skips existing steps) and a restart
            # would re-restore restore_step, losing the branch. Evaluating
            # an old snapshot (--eval-only) is fine; branched TRAINING
            # needs a fresh directory.
            raise ValueError(
                f"checkpoint.restore_step={ck.restore_step} is older than "
                f"the directory's latest step "
                f"({self._ckpt_manager.latest_step()}) — training would "
                f"interleave two lineages. Copy the checkpoint into a "
                f"fresh checkpoint.directory to branch, or use --eval-only."
            )
        cfg = self.config.train
        hooks = self.default_hooks() if hooks is None else hooks
        # The worker-side root span: parented on the supervisor's attempt
        # span (DTF_TRACE_CTX) when one launched us, a fresh trace
        # otherwise. Startup/step-window/ckpt/rollback spans chain under
        # it; left open on a crash so the flight recorder's open-span
        # snapshot still shows the fault's ancestry.
        self.run_span = self.tracer.start(
            "worker.run", self._trace_parent,
            process=self.runtime.process_index, start_step=self.host_step)
        for h in hooks:
            h.on_start(self)

        last_metrics: dict[str, float] = {}
        infeed = prefetch_to_device(
            self.dataset, self.mesh, size=self.config.data.prefetch,
            background=self.config.data.async_infeed,
            deadline_s=self.config.resilience.infeed_deadline_s,
        )
        if self._ckpt_manager is not None:
            # Every save records the prefetch watermark (batches the
            # producer ran ahead) in its data-state commit record — the
            # post-mortem "how far ahead was the infeed?" number.
            self._ckpt_manager.set_data_sources(
                watermark_source=infeed.watermark)
        if self.recovery is not None:
            # Baseline snapshot: the ladder must be able to roll back even
            # if the first anomaly lands before the first clean fetch.
            self.recovery.take_snapshot(
                self.host_step, self.state,
                data_state=self.data_ckpt_state, force=True)
        # Host-side phase timing (core/profiling.py): infeed vs dispatch vs
        # metric-fetch wall time, reported at every log interval — the
        # cheap always-on signal for "is the input pipeline the wall?"
        # (SURVEY.md §7 hard part 1) without capturing a trace.
        timer = profiling.StepTimer()
        # Bounded dispatch-ahead (train.dispatch_ahead): a deque of each
        # in-flight step's metrics; once full, sync on the OLDEST entry
        # before dispatching another step. The sync is a scalar
        # device_get, never block_until_ready (the axon tunnel returns
        # early from the latter — bench.py documents the same rule).
        pending: collections.deque = collections.deque()
        if not self._startup_accounted:
            # Construction → loop entry (restore + input/eval build; the
            # first compile lands in the recompile bucket at dispatch).
            self._startup_accounted = True
            self.goodput.add(
                "startup", time.perf_counter() - self._init_t)
        try:
            while self.host_step < cfg.total_steps:
                if supervision.preemption_requested():
                    # Graceful preemption (SIGTERM): the previous step is
                    # complete, hooks' on_end below force-saves a
                    # checkpoint, and the CLI exits GRACEFUL_PREEMPT_RC so
                    # the supervisor relaunches without burning an attempt.
                    self.preempted = True
                    log.warning(
                        "preemption requested — stopping at step %d for a "
                        "final checkpoint", self.host_step,
                    )
                    self.writer.telemetry.emit(
                        telemetry.KIND_HEALTH, step=self.host_step,
                        health={"event": "graceful_preemption",
                                "step": self.host_step},
                    )
                    # Hard-exit durability: the supervisor SIGKILLs after
                    # its grace window, so make the JSONL durable and dump
                    # the flight recorder NOW, not at interpreter exit.
                    self.writer.telemetry.flush()
                    self.flightrec.dump("graceful_preemption")
                    break
                with timer.phase("infeed"):
                    batch, self.data_ckpt_state = self._next_batch(infeed)
                # Fault injection (core/faults.py, DTF_FAULTS): crash_at_step
                # SIGKILLs here; nan_grads/repeat_nan poison this step's
                # batch (NaN provenance / escalation drills) and loss_spike
                # scales it by a large finite factor (EWMA z-score drill).
                for fault in faults.fire("step_begin", step=self.host_step + 1):
                    if fault.kind in ("nan_grads", "repeat_nan"):
                        batch = _poison_batch(batch)
                    elif fault.kind == "loss_spike":
                        batch = _scale_batch(batch, 1e4)
                if cfg.dispatch_ahead > 0 and len(pending) >= cfg.dispatch_ahead:
                    with timer.phase("backpressure"):
                        float(jax.device_get(
                            next(iter(pending.popleft().values()))))
                first_dispatch = self.collectives_summary is None
                # A dispatch that traces+compiles (first step, or the one
                # after a rollback rebuild) is recompile overhead in the
                # goodput ledger, not step compute.
                compiling = first_dispatch or self._recompile_pending
                with timer.phase("compile" if compiling else "dispatch"), \
                        profiling.annotate("train_step"):
                    if first_dispatch:
                        # First dispatch traces/compiles the step; the
                        # tally sees every collective the executable will
                        # ever run (jit traces once per shape).
                        with coll.tally() as tly:
                            self.state, metrics = self.train_step(
                                self.state, batch)
                        self.collectives_summary = tly.summary()
                    else:
                        self.state, metrics = self.train_step(self.state, batch)
                if compiling:
                    self._recompile_pending = False
                    self.goodput.count("recompiles")
                if cfg.dispatch_ahead > 0:
                    pending.append(metrics)
                self.host_step += 1
                if not self._startup_emitted:
                    # Restart → first-step latency (restore + input build +
                    # compile): the number the persistent XLA compilation
                    # cache (core/platform.py) exists to shrink.
                    self._startup_emitted = True
                    self.writer.telemetry.emit(
                        telemetry.KIND_STARTUP, step=self.host_step,
                        time_to_first_step_s=(
                            time.perf_counter() - self._init_t),
                        restored_step=self._restored_step,
                        compilation_cache_dir=(
                            self.config.train.compilation_cache_dir or None),
                    )
                    # Construction → first completed step as one span:
                    # the relaunch cost a coordinated restart pays, and
                    # the segment the gang drill expects on the critical
                    # path after a supervisor-driven relaunch.
                    self.tracer.emit_span(
                        "train.startup", self.run_span,
                        start_mono=self._init_mono,
                        end_mono=time.monotonic(),
                        first_step=self.host_step,
                        restored_step=self._restored_step)
                    self._window_mono = time.monotonic()
                    self._window_step = self.host_step
                fetch = (
                    self.host_step % cfg.log_interval == 0
                    or self.host_step >= cfg.total_steps
                )
                host_metrics = None
                if fetch:
                    # Only here does the host fully sync with the device;
                    # off-interval steps dispatch asynchronously (at most
                    # dispatch_ahead deep).
                    with timer.phase("metrics_fetch"):
                        host_metrics = {
                            k: float(v)
                            for k, v in jax.device_get(metrics).items()
                        }
                    host_metrics.update(timer.means())
                    self.goodput.absorb_phases(timer.totals)
                    timer.reset()
                    pending.clear()
                    # Recovery ladder rung (train/anomaly.py): a successful
                    # rollback returns None — the anomalous metrics never
                    # reach the hooks (no NaNGuard abort, no poisoned
                    # LoggingHook record) and host_step has been rewound.
                    host_metrics = self._maybe_recover(host_metrics)
                    self.goodput.maybe_emit(step=self.host_step)
                    self.memstats.maybe_sample(step=self.host_step)
                    # Packing census (data/packing.py counters riding the
                    # iterator state): goodput per padded token, emitted
                    # at the same cadence as the metrics fetch. Cumulative
                    # counters — the last event of an attempt is its total.
                    real = self.data_ckpt_state.get(packing.REAL_TOKENS_KEY)
                    if real is not None:
                        self.writer.telemetry.emit(
                            telemetry.KIND_DATA_PACKING, step=self.host_step,
                            metrics=packing.packing_stats(
                                int(real),
                                int(self.data_ckpt_state.get(
                                    packing.PADDED_TOKENS_KEY, 0))))
                    # One span per log-interval window of steps — coarse
                    # enough to stay cheap, fine enough that a gang
                    # restart's dead time shows as a gap between the last
                    # window of attempt N and startup of attempt N+1.
                    now_mono = time.monotonic()
                    self.tracer.emit_span(
                        "train.steps", self.run_span,
                        start_mono=getattr(self, "_window_mono", now_mono),
                        end_mono=now_mono,
                        start_step=getattr(self, "_window_step",
                                           self.host_step),
                        end_step=self.host_step)
                    self._window_mono = now_mono
                    self._window_step = self.host_step
                    if host_metrics is not None:
                        last_metrics = host_metrics
                for h in hooks:
                    h.after_step(self, self.host_step, host_metrics)
                if self.recovery is not None and self.recovery.exhausted:
                    # Finite-anomaly escalation (loss spike / grad-norm
                    # explosion past max_rollbacks): NaNGuardHook only
                    # fires on non-finite metrics, so the loop itself is
                    # the escalation tail here — also covers
                    # train.nan_guard=false runs. Dump the flight
                    # recorder FIRST: the ring holds the rollback spans
                    # and anomaly events leading up to this escalation,
                    # and the open worker.run span is its ancestry.
                    self.flightrec.dump("persistent_anomaly")
                    raise anomaly_lib.PersistentAnomalyError(
                        self.recovery.escalation_message(),
                        provenance=self.recovery.provenance(),
                    )
        finally:
            # Stop the background producer (async_infeed): it must not
            # keep pulling from the dataset the caller may reuse/restore.
            infeed.close()
            if self._ckpt_manager is not None:
                # The final force-save (CheckpointHook.on_end) must not
                # poll a closed infeed's queue for its watermark.
                self._ckpt_manager.set_data_sources(watermark_source=None)
            # Absorb the tail phases accumulated since the last fetch even
            # on the escalation path (the final rollup below only runs on
            # clean exit; an escalating or SIGKILLed attempt is covered by
            # its last periodic snapshot).
            self.goodput.absorb_phases(timer.totals)
            timer.reset()
        for h in hooks:
            h.on_end(self)
        if self._ckpt_manager is not None:
            # Exit/preemption barrier for the async checkpoint pipeline:
            # CheckpointHook.on_end already flushes, but custom hook lists
            # may not include it — never return (and never let the CLI exit
            # rc 83) with a commit still in flight on the saver thread.
            self._ckpt_manager.wait_until_finished()
            if (self.runtime.process_count > 1
                    and self.config.checkpoint.directory):
                # Coordinator-led exit barrier (core/cluster.py): the
                # chief confirms its manifest commit record is durable and
                # every survivor waits on the same record before returning
                # — a worker that exits early tears down the jax.distributed
                # coordinator and can strand its peers' in-flight commits.
                cluster.exit_barrier(
                    self.config.checkpoint.directory,
                    step=self.host_step,
                    timeout_s=self.config.cluster.exit_barrier_timeout_s,
                    poll_s=self.config.cluster.exit_barrier_poll_s,
                    is_chief=self.runtime.is_chief,
                )
        # Finalize AFTER the exit barrier so the last ckpt_save's
        # blocked-ms lands in the rollup, not past it.
        self.goodput.finalize(step=self.host_step)
        self.memstats.sample(step=self.host_step, final=True)
        if self.run_span is not None:
            self.run_span.end(
                status="preempted" if self.preempted else "ok",
                end_step=self.host_step)
        return last_metrics

    # ----------------------------------------------------- recovery ladder --
    def _next_batch(self, infeed):
        """One infeed pull behind the stall watchdog (data/infeed.py).

        With ``resilience.infeed_deadline_s`` armed, a pull that exceeds
        the deadline raises ``InfeedStallError``; the retry here waits out
        the SAME pull (the watchdog reports, it does not cancel) with
        linear backoff, emitting an ``infeed_stall`` event per attempt.
        Past ``infeed_retries`` the error propagates — the supervisor's
        heartbeat watchdog rung takes over.
        """
        rcfg = self.config.resilience
        attempt = 0
        while True:
            try:
                return next(infeed)
            except InfeedStallError as e:
                attempt += 1
                self.writer.telemetry.emit(
                    telemetry.KIND_INFEED_STALL, step=self.host_step,
                    health={"deadline_s": e.deadline_s, "attempt": attempt,
                            "max_retries": rcfg.infeed_retries},
                )
                if attempt > rcfg.infeed_retries:
                    log.error(
                        "infeed stalled past %d retries — escalating",
                        rcfg.infeed_retries,
                    )
                    raise
                backoff = rcfg.infeed_backoff_s * attempt
                log.warning(
                    "infeed stall (attempt %d/%d, deadline %.1fs) — "
                    "retrying in %.2fs", attempt, rcfg.infeed_retries,
                    e.deadline_s, backoff,
                )
                time.sleep(backoff)

    def _maybe_recover(self, host_metrics: dict[str, float]) -> dict[str, float] | None:
        """Classify a fetched-metrics step; roll back if anomalous.

        Returns the metrics unchanged for clean steps (after feeding the
        EWMA baseline and opportunistically snapshotting), None when a
        rollback consumed the anomaly (host_step is rewound; the hooks
        must not see the poisoned metrics), and the ANOMALOUS metrics with
        ``recovery.exhausted`` set when the ladder is out of rungs — the
        caller escalates after the hooks run.
        """
        rec = self.recovery
        if rec is None:
            return host_metrics
        verdict = rec.classify(self.host_step, host_metrics)
        if verdict is None:
            rec.take_snapshot(self.host_step, self.state,
                              data_state=self.data_ckpt_state)
            return host_metrics
        if not rec.can_rollback():
            rec.exhausted = True
            return host_metrics
        from_step = self.host_step
        t_rb = time.monotonic()
        with self.goodput.timed("rollback"):
            self.state, snap = rec.rollback(self.state, from_step=self.host_step)
            # Skip-batch semantics: host_step rewinds, the data iterator
            # does NOT — the replayed step range consumes fresh batches and
            # the poisoned region is never re-fed. Record WHICH consumed
            # ordinals were skipped into the iterator state, so a restart
            # that restores a pre-rollback data state replays the stream
            # with those ordinals discarded instead of double-counting
            # them (docs/RESILIENCE.md "Exactly-once data").
            snap_consumed = int((snap.data_state or {}).get("consumed", 0))
            live_consumed = int(self.data_ckpt_state.get("consumed", 0))
            if live_consumed > snap_consumed:
                skipped = range(snap_consumed + 1, live_consumed + 1)
                self.dataset.record_skipped(skipped)
                # REBIND into the step-aligned snapshot too (never mutate:
                # queued save snapshots share nested lists by reference) —
                # the next checkpoint's data state must carry the record.
                merged = sorted(
                    {int(o) for o in
                     self.data_ckpt_state.get("batches_skipped", ())}
                    | set(skipped))
                self.data_ckpt_state = {
                    **self.data_ckpt_state, "batches_skipped": merged}
            self.host_step = snap.step
            if self.config.resilience.lr_rewarmup_steps > 0:
                self._rebuild_with_rewarmup(snap.step)
        self.tracer.emit_span(
            "train.rollback", self.run_span,
            start_mono=t_rb, end_mono=time.monotonic(),
            from_step=from_step, to_step=snap.step)
        return None

    def _rebuild_with_rewarmup(self, resume_step: int) -> None:
        """Swap the LR schedule for a re-warmed copy and re-jit the step.

        optax schedule state is a bare step counter, so the restored
        opt_state is structurally identical under the new chain — the
        rebuild costs one recompile (same shapes, warm XLA cache), not a
        state migration.
        """
        steps = self.config.resilience.lr_rewarmup_steps
        log.info(
            "re-warming learning rate over steps [%d, %d) after rollback",
            resume_step, resume_step + steps,
        )
        self.builder.set_schedule_wrapper(
            lambda sched: schedules.with_rewarmup(sched, resume_step, steps))
        self.train_step = self.builder.make_train_step(self._sample)
        self._recompile_pending = True

    # ---------------------------------------------------------------- eval --
    def _ensure_eval(self):
        """Build the eval pipeline + compiled eval step ONCE per eval
        config; reused across every EvalHook firing and final eval
        (rebuilding the TFRecord pipeline per call was the round-1 waste).
        Swapping ``config.eval_data`` invalidates the cache — the next
        evaluate() rebuilds pipeline AND compiled step."""
        eval_cfg = self.config.eval_data or self.config.data
        if getattr(self, "_eval_ds", None) is None or \
                getattr(self, "_eval_cfg", None) is not eval_cfg:
            if getattr(self, "_eval_cfg", None) is not None \
                    and self._eval_cfg is not eval_cfg:
                self.eval_step = None  # element spec may differ — recompile
            self._eval_cfg = eval_cfg
            self._eval_ds = get_dataset(
                eval_cfg,
                process_index=self.runtime.process_index,
                process_count=self.runtime.process_count,
                train=False,
            )
            self._eval_start = self._eval_ds.state()
            sample_host = next(self._eval_ds)
            self._eval_ds.restore(self._eval_start)
            if self.eval_step is None:
                self.eval_step = self.builder.make_eval_step(
                    to_global(sample_host, self.mesh)
                )
        return self._eval_ds

    def evaluate(self, step: int | None = None, num_batches: int | None = None) -> dict[str, float]:
        """Exact evaluation (SURVEY.md §3.4 eval-loop contract).

        Finite eval streams (real datasets) are walked in ONE full pass —
        every validation example exactly once, padded final batch masked
        by per-example weights — and metrics are weighted means over real
        examples. Infinite streams (synthetic fallback) evaluate
        ``train.eval_steps`` batches. ``num_batches`` truncates either.
        """
        if self.state is None:
            self.build()
        ds = self._ensure_eval()
        ds.restore(self._eval_start)  # fresh pass every call
        if num_batches is not None:
            n = num_batches
            if ds.cardinality is not None:
                # Exact-by-construction: never trust config arithmetic to
                # reproduce the set size. num_batches >= cardinality means
                # "the full set" (clamped); below it is an explicit
                # truncation, surfaced loudly because a silently dropped
                # tail (e.g. eval_steps=12 vs 50k/4096=12.2) biases every
                # mid-training accuracy ever logged.
                if n >= ds.cardinality:
                    n = ds.cardinality
                else:
                    log.warning(
                        "eval truncated: %d of %d batches (set "
                        "train.eval_steps >= %d for full coverage)",
                        n, ds.cardinality, ds.cardinality,
                    )
        elif ds.cardinality is not None:
            n = ds.cardinality  # exact: the full validation set
        else:
            n = self.config.train.eval_steps
        totals: dict[str, float] = {}
        for i, (batch, _) in enumerate(prefetch_to_device(ds, self.mesh, size=2)):
            if i >= n:
                break
            m = jax.device_get(self.eval_step(self.state, batch))
            for k, v in m.items():
                totals[k] = totals.get(k, 0.0) + float(v)
        weight = totals.pop("weight_sum", 0.0)
        denom = max(weight, 1e-9)
        results = {
            f"eval_{k[: -len('_sum')]}": v / denom for k, v in totals.items()
        }
        # Real examples seen (masked tokens for MLM) — lets callers confirm
        # full-set coverage (e.g. 50000 for ImageNet validation).
        results["eval_examples"] = weight
        if step is not None:
            self.writer.write(step, results, kind=telemetry.KIND_EVAL)
        return results
