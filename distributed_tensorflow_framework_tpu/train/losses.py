"""Loss functions and in-step metrics.

SURVEY.md §2 row 9: softmax cross-entropy (+ weight decay, handled in the
optimizer chain) and top-1/top-5 metrics for the image models; masked-LM
cross-entropy for the BERT workload. All functions are pure and jit-safe;
losses are means over the *global* batch so that data-parallel gradient
aggregation is exactly the reference's SyncReplicasOptimizer mean.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def classification_loss(
    logits: jax.Array, labels: jax.Array, *, label_smoothing: float = 0.0
) -> tuple[jax.Array, dict[str, jax.Array]]:
    num_classes = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    if label_smoothing > 0:
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing
        )
        losses = optax.softmax_cross_entropy(logits, onehot)
    else:
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    loss = losses.mean()
    top1 = (jnp.argmax(logits, axis=-1) == labels).mean()
    metrics = {"loss": loss, "top1": top1}
    if num_classes > 5:
        top5_preds = jax.lax.top_k(logits, 5)[1]
        metrics["top5"] = (top5_preds == labels[:, None]).any(axis=-1).mean()
    return loss, metrics


def classification_metrics_sums(
    logits: jax.Array, labels: jax.Array, weight: jax.Array
) -> dict[str, jax.Array]:
    """Per-batch weighted metric SUMS for exact full-set evaluation.

    The eval loop (train/loop.py) accumulates these across the single-pass
    padded eval stream and divides by ``weight_sum`` at the end, so the
    result is the exact mean over real examples — zero-weight padding rows
    contribute nothing (reference eval-loop contract, SURVEY.md §3.4).
    """
    num_classes = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    w = weight.astype(jnp.float32)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    out = {
        "loss_sum": (losses * w).sum(),
        "top1_sum": (correct * w).sum(),
        "weight_sum": w.sum(),
    }
    if num_classes > 5:
        top5 = (jax.lax.top_k(logits, 5)[1] == labels[:, None]).any(axis=-1)
        out["top5_sum"] = (top5.astype(jnp.float32) * w).sum()
    return out


def mlm_metrics_sums(
    logits: jax.Array, targets: jax.Array, weight: jax.Array
) -> dict[str, jax.Array]:
    """MLM weighted metric SUMS over masked positions (see above).

    ``weight_sum`` counts masked tokens of real (weight-1) examples — the
    exact denominator for masked-LM loss/accuracy.
    """
    logits = logits.astype(jnp.float32)
    mask = mlm_mask(targets) * weight.astype(jnp.float32)[:, None]
    safe_targets = jnp.maximum(targets, 0)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, safe_targets)
    correct = (jnp.argmax(logits, axis=-1) == safe_targets).astype(jnp.float32)
    return {
        "loss_sum": (losses * mask).sum(),
        "mlm_acc_sum": (correct * mask).sum(),
        "weight_sum": mask.sum(),
    }


def mlm_mask(targets: jax.Array) -> jax.Array:
    """1.0 at masked (predicted) positions, 0.0 elsewhere — the single
    definition of the '-1 means unmasked' sentinel, shared with the
    grad-accumulation microbatch weighting (train/step.py)."""
    return (targets >= 0).astype(jnp.float32)


def mlm_loss(
    logits: jax.Array, targets: jax.Array
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Masked-LM CE. ``targets`` holds the original token at masked
    positions and -1 elsewhere."""
    logits = logits.astype(jnp.float32)
    mask = mlm_mask(targets)
    safe_targets = jnp.maximum(targets, 0)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, safe_targets)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (losses * mask).sum() / denom
    correct = (jnp.argmax(logits, axis=-1) == safe_targets).astype(jnp.float32)
    acc = (correct * mask).sum() / denom
    return loss, {"loss": loss, "mlm_acc": acc}
