"""Optimizer construction from config.

The reference wraps a base optimizer (momentum SGD / RMSProp family) in
SyncReplicasOptimizer for gradient aggregation (SURVEY.md §2 row 3). Here
aggregation is the mesh's job; this module only builds the local update
rule as an optax chain: grad-clip → base update → weight decay → lr
schedule.

Weight decay follows the recipe convention: applied to conv/dense kernels,
not to BN params or biases.
"""

from __future__ import annotations

from typing import Any

import optax

from distributed_tensorflow_framework_tpu.core.config import OptimizerConfig
from distributed_tensorflow_framework_tpu.train.schedules import make_schedule


def _decay_mask(params: Any) -> Any:
    """True where weight decay applies: rank≥2 kernels, not BN/bias."""
    import jax
    import numpy as np

    def keep(path, leaf) -> bool:
        names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
        if any(str(n) in ("bn", "scale", "bias") for n in names):
            return False
        return np.ndim(leaf) >= 2

    return jax.tree_util.tree_map_with_path(keep, params)


def decay_mask_tree(params: Any) -> Any:
    """Public twin of :func:`_decay_mask` — the precomputed boolean mask
    the fused ZeRO update walk subsets per bucket (its per-bucket update
    trees are flattened shards with rank and path both erased)."""
    return _decay_mask(params)


def make_optimizer(
    config: OptimizerConfig, total_steps: int,
    schedule_wrapper=None,
    decay_mask_ref: Any = None,
    decay_mask: Any = None,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Build the optax chain + schedule. ``schedule_wrapper`` (schedule →
    schedule) post-processes the schedule before the chain captures it —
    the hook the post-rollback LR re-warmup (train/schedules.with_rewarmup)
    uses to rebuild the optimizer without changing the opt-state pytree
    (optax schedule state is a bare step counter, schedule-agnostic).

    ``decay_mask_ref``: the tree whose paths/ranks decide the weight-decay
    mask, when the tree ``tx`` will RUN on is not that tree. The ZeRO
    shard_map path (parallel/zero.py) updates flattened 1-D per-replica
    shards — rank and path both lost — so StepBuilder passes the real
    param tree here and the PRECOMPUTED boolean mask rides along. The
    mask's values never change the opt-state structure (optax masked
    wrappers carry no per-leaf state), so swapping mask callables for a
    mask tree is checkpoint-compatible.

    ``decay_mask``: a fully-precomputed boolean mask tree, taking
    precedence over both the callable and ``decay_mask_ref`` — the fused
    ZeRO walk (parallel/zero.fused_update_walk) builds one tx per bucket
    and passes each bucket's positional subset of the full-tree mask."""
    sched = make_schedule(config, total_steps)
    if schedule_wrapper is not None:
        sched = schedule_wrapper(sched)
    # Callable by default (evaluated lazily on the update tree); a
    # precomputed bool pytree when a ref tree is given — the ref and the
    # update tree share a treedef, so the leaf pairing is positional.
    mask = (decay_mask if decay_mask is not None
            else _decay_mask if decay_mask_ref is None
            else _decay_mask(decay_mask_ref))
    chain = []
    if config.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(config.grad_clip_norm))
    name = config.name.lower()
    if name in ("sgd", "sgd_momentum", "momentum"):
        if config.weight_decay > 0:
            chain.append(optax.add_decayed_weights(config.weight_decay, mask=mask))
        chain.append(optax.sgd(sched, momentum=config.momentum, nesterov=config.nesterov))
    elif name == "adam":
        if config.weight_decay > 0:
            chain.append(optax.add_decayed_weights(config.weight_decay, mask=mask))
        chain.append(optax.adam(sched, b1=config.beta1, b2=config.beta2, eps=config.eps))
    elif name == "adamw":
        chain.append(
            optax.adamw(
                sched,
                b1=config.beta1,
                b2=config.beta2,
                eps=config.eps,
                weight_decay=config.weight_decay,
                mask=mask,
            )
        )
    elif name == "rmsprop":
        # The reference's Inception recipe family (SURVEY.md §2 row 4 is
        # RMSProp-based): decay/momentum/eps from config — canonical
        # Inception-v3 values are decay=0.9, momentum=0.9, eps=1.0.
        if config.weight_decay > 0:
            chain.append(optax.add_decayed_weights(config.weight_decay, mask=mask))
        # initial_scale=1.0: TF1's RMSPropOptimizer initializes the
        # mean-square slot to ones (optax defaults to zero) — without it
        # early updates are systematically larger than the reference's.
        chain.append(
            optax.rmsprop(
                sched,
                decay=config.rms_decay,
                eps=config.eps,
                momentum=config.momentum if config.momentum > 0 else None,
                initial_scale=1.0,
            )
        )
    elif name == "lars":
        chain.append(
            optax.lars(
                sched,
                weight_decay=config.weight_decay,
                weight_decay_mask=mask,
                momentum=config.momentum,
            )
        )
    else:
        raise ValueError(f"Unknown optimizer {config.name!r}")
    return optax.chain(*chain), sched
