"""Learning-rate schedules.

The reference class of recipes uses warmup + staircase decay for ResNet
(the classic ImageNet 30/60/80-epoch drops) and exponential/constant for
the smaller configs (SURVEY.md §2 row 9 context). All schedules here are
optax schedules usable inside jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from distributed_tensorflow_framework_tpu.core.config import OptimizerConfig


def make_schedule(config: OptimizerConfig, total_steps: int) -> optax.Schedule:
    base = config.learning_rate
    decay_steps = max(1, total_steps - config.warmup_steps)
    if config.schedule == "constant":
        sched = optax.constant_schedule(base)
    elif config.schedule == "cosine":
        sched = optax.cosine_decay_schedule(base, decay_steps)
    elif config.schedule == "linear":
        sched = optax.linear_schedule(base, 0.0, decay_steps)
    elif config.schedule == "staircase":
        # Config boundaries are absolute global steps; join_schedules feeds
        # the post-warmup schedule (step - warmup_steps), so shift them.
        boundaries = {
            int(b) - config.warmup_steps: config.decay_factor
            for b in config.boundaries
        }
        sched = optax.piecewise_constant_schedule(base, boundaries)
    else:
        raise ValueError(f"Unknown schedule {config.schedule!r}")
    if config.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, config.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [config.warmup_steps])
    return sched


def with_rewarmup(schedule: optax.Schedule, resume_step: int,
                  rewarmup_steps: int) -> optax.Schedule:
    """Post-rollback LR re-warmup (resilience.lr_rewarmup_steps).

    After an in-memory rollback (train/anomaly.py) the restored optimizer
    slots are a few steps stale relative to the fresh data stream; scaling
    the base schedule linearly from ~0 back to 1 over
    ``[resume_step, resume_step + rewarmup_steps)`` eases the re-entry the
    same way startup warmup eases cold slots. The restored step counter
    resumes AT ``resume_step`` (earlier steps never evaluate again), and
    at/after the window's end the base schedule is unchanged — the
    wrapper only bends the window.
    """
    if rewarmup_steps <= 0:
        return schedule

    def sched(step):
        frac = (jnp.asarray(step, jnp.float32) - float(resume_step) + 1.0
                ) / float(rewarmup_steps)
        return schedule(step) * jnp.clip(frac, 0.0, 1.0)

    return sched
