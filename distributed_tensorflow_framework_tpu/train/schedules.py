"""Learning-rate schedules.

The reference class of recipes uses warmup + staircase decay for ResNet
(the classic ImageNet 30/60/80-epoch drops) and exponential/constant for
the smaller configs (SURVEY.md §2 row 9 context). All schedules here are
optax schedules usable inside jit.
"""

from __future__ import annotations

import optax

from distributed_tensorflow_framework_tpu.core.config import OptimizerConfig


def make_schedule(config: OptimizerConfig, total_steps: int) -> optax.Schedule:
    base = config.learning_rate
    decay_steps = max(1, total_steps - config.warmup_steps)
    if config.schedule == "constant":
        sched = optax.constant_schedule(base)
    elif config.schedule == "cosine":
        sched = optax.cosine_decay_schedule(base, decay_steps)
    elif config.schedule == "linear":
        sched = optax.linear_schedule(base, 0.0, decay_steps)
    elif config.schedule == "staircase":
        # Config boundaries are absolute global steps; join_schedules feeds
        # the post-warmup schedule (step - warmup_steps), so shift them.
        boundaries = {
            int(b) - config.warmup_steps: config.decay_factor
            for b in config.boundaries
        }
        sched = optax.piecewise_constant_schedule(base, boundaries)
    else:
        raise ValueError(f"Unknown schedule {config.schedule!r}")
    if config.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, config.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [config.warmup_steps])
    return sched
