"""Training state pytree.

The SPMD replacement for the reference's PS-resident variable set +
``global_step`` + optimizer slots (SURVEY.md §2 rows 2–3): params, BN
running stats, optimizer state, step counter and the dropout RNG key in one
checkpointable pytree.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array          # int32 scalar — the reference's global_step
    params: Any
    batch_stats: Any         # BN running stats ({} for BN-free models)
    opt_state: optax.OptState
    rng: jax.Array           # dropout/noise root key (device-side)
    # Exponential moving average of params ({} when disabled) — the
    # tf.train.ExponentialMovingAverage of the reference recipe class;
    # eval reads these when optimizer.ema_decay > 0.
    ema_params: Any = flax.struct.field(default_factory=dict)
    # Error-feedback residual for the int8 quantized all-reduce ({} unless
    # parallel.collective_dtype="int8" with error feedback, shard_map
    # mode): one f32 leaf per param leaf, globally (n_dp, *param.shape)
    # sharded over the data axes — row i is replica i's uncompensated
    # compression error, re-injected into its next step's gradients
    # (parallel/collectives.allreduce_gradients_ef). Checkpointed like any
    # other state; resharding sum-folds rows (ckpt/reshard.fold_residual)
    # so the conserved total error survives a mesh change.
    collective_residual: Any = flax.struct.field(default_factory=dict)

    @classmethod
    def create(cls, *, params, batch_stats, tx: optax.GradientTransformation,
               rng: jax.Array, ema: bool = False,
               collective_residual: Any = None,
               opt_params: Any = None,
               opt_state: Any = None) -> "TrainState":
        """``opt_params``: the tree ``tx.init`` runs on, when it differs
        from ``params`` — the ZeRO shard_map path initializes slots at
        the stacked ``(n, chunk)`` layout (parallel/zero.stacked_shards)
        while the master params stay replicated at model shapes.
        ``opt_state``: a pre-built optimizer state, bypassing ``tx.init``
        entirely — the fused-update path (precision.fused_update) stores
        a TUPLE of per-bucket optax states (same bytes as the monolithic
        state, grouped by reduce-scatter bucket)."""
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=(tx.init(params if opt_params is None else opt_params)
                       if opt_state is None else opt_state),
            rng=rng,
            ema_params=jax.tree.map(jnp.copy, params) if ema else {},
            collective_residual=(
                {} if collective_residual is None else collective_residual),
        )
