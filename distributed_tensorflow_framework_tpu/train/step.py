"""Train/eval step construction — the framework's hot loop.

Replaces SURVEY.md §3.1's per-step pipeline (read vars from PS over grpc →
local fwd/bwd → NCCL grad aggregation → chief applies update → sync token)
with ONE compiled SPMD program in two selectable flavors:

  * ``spmd_mode="jit"``: the batch is a global array sharded over the data
    axes; the loss is a mean over the global batch, so XLA emits the
    cross-replica-sum for the gradients automatically. BN statistics are
    global (cross-replica) by construction.
  * ``spmd_mode="shard_map"``: per-replica code with explicit
    `pmean(grads)` — structurally the closest analogue of the reference's
    SyncReplicasOptimizer+NCCL pipeline, and the mode in which per-replica
    BN (the reference's exact semantics) is expressible.

Both modes produce bitwise-identical parameter trajectories for BN-free
models (tested in tests/test_train_lenet.py::test_jit_and_shard_map_agree).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.config import ExperimentConfig
from distributed_tensorflow_framework_tpu.core import prng
from distributed_tensorflow_framework_tpu.core.mesh import batch_spec
from distributed_tensorflow_framework_tpu.models import get_model
from distributed_tensorflow_framework_tpu.parallel import sharding as shd
from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.parallel import zero
from distributed_tensorflow_framework_tpu.train import losses
from distributed_tensorflow_framework_tpu.train.optimizers import make_optimizer
from distributed_tensorflow_framework_tpu.train.state import TrainState

DATA_AXES = ("data", "fsdp")


def _fsdp_dim(shape, fsdp_n: int) -> int:
    """Dim index the explicit-fsdp path shards over, or -1 for replicated
    leaves (no divisible dim, scalars). Delegates to the ONE tie-break
    rule in parallel/sharding.pick_fsdp_dim so the explicit layout can
    never diverge from the jit-spec one."""
    return shd.pick_fsdp_dim(tuple(shape), fsdp_n)


def task_for_model(name: str) -> str:
    from distributed_tensorflow_framework_tpu.models import custom_model_task

    custom = custom_model_task(name)
    if custom is not None:
        return custom
    return "mlm" if "bert" in name.lower() else "classification"


def model_inputs(task: str, batch: Any) -> tuple:
    if task == "mlm":
        if "segment_ids" in batch:
            # Packed sequences (data.pack_factor>1): block-diagonal
            # attention over the per-row segment ids.
            return (batch["input_ids"], batch["attention_mask"],
                    batch["segment_ids"])
        if "attention_mask" in batch:
            return (batch["input_ids"], batch["attention_mask"])
        return (batch["input_ids"],)
    return (batch["image"],)


class StepBuilder:
    """Builds the compiled init / train_step / eval_step for a workload."""

    def __init__(self, config: ExperimentConfig, mesh: Mesh):
        self.config = config
        self.mesh = mesh
        self.task = task_for_model(config.model.name)
        self.shard_map_mode = config.train.spmd_mode == "shard_map"
        # Collective wire format: parallel.collective_dtype, with the
        # deprecated train.grad_allreduce_dtype honored for configs built
        # without load_config's shim.
        self._collective_dtype = (config.parallel.collective_dtype
                                  or config.train.grad_allreduce_dtype)
        self._collective_block = config.parallel.collective_block_size
        if self._collective_dtype and not self.shard_map_mode:
            raise ValueError(
                "parallel.collective_dtype (and the deprecated "
                "train.grad_allreduce_dtype) only applies to the explicit "
                "collective path — set train.spmd_mode='shard_map' (under "
                "'jit' XLA owns the gradient reduction wire format)"
            )
        if config.train.grad_allreduce_accum not in ("float32", "wire"):
            raise ValueError(
                "train.grad_allreduce_accum must be 'float32' or 'wire', "
                f"got {config.train.grad_allreduce_accum!r}"
            )
        # Error-feedback residual rides the TrainState only for the int8
        # block-scaled collectives (parallel/collectives.py, parallel/zero.py).
        self._use_residual = (self.shard_map_mode
                              and self._collective_dtype == "int8"
                              and config.parallel.error_feedback)
        # ZeRO weight-update sharding (parallel/zero.py). "jit" is the
        # passive spec variant (the deprecated optimizer.shard_opt_state,
        # honored here for configs built without load_config's shim);
        # "shard_map" is the explicit bucketed reduce-scatter path.
        zs = config.optimizer.zero_sharding
        if config.optimizer.shard_opt_state and zs == "off":
            zs = "jit"
        self._zero = zs == "shard_map"
        self._zero_n = (mesh.shape.get("data", 1)
                        * mesh.shape.get("fsdp", 1))
        self._zero_plan = None
        if self._zero:
            if not self.shard_map_mode:
                raise ValueError(
                    "optimizer.zero_sharding='shard_map' is the explicit "
                    "bucketed reduce-scatter path and needs "
                    "train.spmd_mode='shard_map'; under spmd_mode='jit' "
                    "use optimizer.zero_sharding='jit' (XLA owns the "
                    "update-shard/all-gather pattern there)"
                )
            if self._zero_n <= 1:
                raise ValueError(
                    "optimizer.zero_sharding='shard_map' shards the weight "
                    "update over the data×fsdp replicas — this mesh has "
                    f"{self._zero_n}, so it would be a silent no-op"
                )
            if config.optimizer.name == "lars":
                raise ValueError(
                    "optimizer.name='lars' needs full per-layer "
                    "param/update norms, but zero_sharding='shard_map' "
                    "updates flattened parameter SHARDS — use "
                    "zero_sharding='jit' for lars"
                )
            if config.optimizer.grad_clip_norm > 0:
                raise ValueError(
                    "optimizer.grad_clip_norm>0 computes the global grad "
                    "norm inside the optimizer, which under "
                    "zero_sharding='shard_map' sees only gradient SHARDS "
                    "— use zero_sharding='jit' for clipped training"
                )
        # Fused donated optimizer update (precision.fused_update): the
        # optax apply moves into the bucketed reverse-layer walk
        # (parallel/zero.fused_update_walk) so each param shard is
        # read-modified-written once while hot. The walk IS the ZeRO
        # bucketed path, so it inherits zero_sharding='shard_map' and its
        # lars/grad-clip exclusions (validated above).
        precision = getattr(config, "precision", None)
        self._fused = bool(precision is not None and precision.fused_update)
        if self._fused and not self._zero:
            raise ValueError(
                "precision.fused_update=true fuses the optax apply into "
                "the ZeRO bucketed reverse-layer walk and therefore "
                "requires optimizer.zero_sharding='shard_map'"
            )
        self._fused_txs = None  # built lazily, one tx per plan bucket
        # shard_map + mesh.fsdp>1 runs EXPLICIT fsdp: params/opt state/EMA
        # sharded over fsdp, a hand-placed (optionally quantized)
        # all_gather around the fwd/bwd, grads sliced back to shards for
        # the update. With fsdp==1 the path is pure replicated DP as
        # before. Under ZeRO the fsdp axis instead folds into the shard
        # count (params stay replicated — no forward-pass gathers).
        self._explicit_fsdp = (self.shard_map_mode
                               and mesh.shape.get("fsdp", 1) > 1
                               and not self._zero)
        if self._explicit_fsdp:
            if config.optimizer.name == "lars":
                raise ValueError(
                    "optimizer.name='lars' needs full per-layer param/update "
                    "norms, but explicit fsdp (spmd_mode='shard_map' with "
                    "mesh.fsdp>1) updates parameter SHARDS — use "
                    "spmd_mode='jit' for lars+fsdp"
                )
            if config.optimizer.grad_clip_norm > 0:
                raise ValueError(
                    "optimizer.grad_clip_norm>0 computes the global grad "
                    "norm inside the optimizer, which under explicit fsdp "
                    "(spmd_mode='shard_map' with mesh.fsdp>1) sees only "
                    "gradient SHARDS — use spmd_mode='jit' for clipped "
                    "fsdp training"
                )
        if (self.task == "mlm"
                and getattr(config.data, "vocab_size", None) is not None
                and config.data.vocab_size > config.model.vocab_size):
            # Token ids at/above the embedding size clamp silently under
            # jit and the CE loss on out-of-range TARGETS goes NaN on the
            # first step — measured: a drive with model.vocab_size=512
            # over the recipe's 30522-token synthetic stream was
            # loss=nan at step 1 with nothing pointing at the cause.
            raise ValueError(
                f"data.vocab_size={config.data.vocab_size} exceeds "
                f"model.vocab_size={config.model.vocab_size}: the stream "
                f"can emit token ids the embedding/MLM head cannot "
                f"represent (silent clamp + NaN loss). Shrink "
                f"data.vocab_size or grow model.vocab_size."
            )
        if self.shard_map_mode and mesh.shape.get("expert", 1) > 1:
            raise ValueError(
                "spmd_mode='shard_map' is the pure-DP reference-parity path; "
                "expert parallelism (mesh.expert>1) requires spmd_mode='jit'"
            )
        self._zero_jit = zs == "jit"
        if self._zero_jit:
            if self.shard_map_mode:
                raise ValueError(
                    "optimizer.zero_sharding='jit' (and the deprecated "
                    "optimizer.shard_opt_state) needs spmd_mode='jit' — "
                    "XLA owns the update-shard/all-gather pattern there; "
                    "the explicit path is optimizer.zero_sharding="
                    "'shard_map'"
                )
            if mesh.shape.get("fsdp", 1) <= 1:
                raise ValueError(
                    "optimizer.zero_sharding='jit' (and the deprecated "
                    "optimizer.shard_opt_state) shards over the fsdp mesh "
                    "axis — set mesh.fsdp > 1 (it would be a silent no-op "
                    "on this mesh)"
                )
        pipe = mesh.shape.get("pipe", 1)
        stages = config.model.pipeline_stages
        self._pipe_virtual = 1
        if pipe > 1 or stages > 1 or config.model.pipeline_microbatches > 0:
            if stages <= 1:
                raise ValueError(
                    "pipeline_microbatches / mesh.pipe>1 require "
                    "model.pipeline_stages>1"
                )
            if "bert" not in config.model.name.lower():
                raise ValueError(
                    "pipeline parallelism is only wired for the transformer "
                    "(bert) models (parallel/pipeline.py)"
                )
            if stages != pipe:
                raise ValueError(
                    f"model.pipeline_stages={stages} must equal the mesh's "
                    f"pipe axis size {pipe}"
                )
            if self.shard_map_mode:
                raise ValueError(
                    "pipeline parallelism runs under spmd_mode='jit' (the "
                    "stage schedule is its own nested shard_map)"
                )
            if (
                mesh.shape.get("model", 1) > 1
                or mesh.shape.get("seq", 1) > 1
                or mesh.shape.get("expert", 1) > 1
                or config.model.num_experts > 0
            ):
                raise ValueError(
                    "v1 pipeline scope: pipe composes with data/fsdp only — "
                    "TP/seq/expert parallelism inside the pipelined stack "
                    "needs manual-mode collectives in the stage body"
                )
            # Schedule validation at StepBuilder level (fails before any
            # compile on a bad (schedule, S, M, v, L) tuple); the resolved
            # tuple also drives the per-step analytic bubble metric.
            from distributed_tensorflow_framework_tpu.parallel import (
                schedule as pipe_sched,
            )

            micro = config.model.pipeline_microbatches or stages
            self._pipe_virtual = pipe_sched.resolve_virtual(
                config.model.pipeline_schedule, stages, micro,
                config.model.pipeline_virtual_stages,
                config.model.num_layers,
            )
        # BN axis name: only meaningful under shard_map (under jit, stats
        # are global automatically; see models/layers.py docstring).
        bn_axis = None
        if self.shard_map_mode and config.model.bn_cross_replica:
            bn_axis = DATA_AXES
        self.model = get_model(config.model, bn_axis_name=bn_axis, mesh=mesh,
                               precision=precision)
        self.tx, self.schedule = make_optimizer(
            config.optimizer, config.train.total_steps
        )
        self._state_specs = None
        self._fsdp_dims = None  # params-shaped tree of shard dims (fsdp)
        self._schedule_wrapper = None
        # Set by state_specs once param shapes are known (ZeRO only): the
        # ref tree the weight-decay mask is computed from, since the tx
        # there runs on flattened shards with path/rank erased.
        self._decay_mask_ref = None

    def set_schedule_wrapper(self, wrapper) -> None:
        """Rebuild tx/schedule with ``wrapper`` applied (the post-rollback
        LR re-warmup, train/schedules.with_rewarmup; None restores the
        plain schedule). The opt-state pytree stays valid — optax keeps
        only a schedule-agnostic step counter — but the caller must
        rebuild its compiled train step afterwards (the old jit captured
        the old chain)."""
        self._schedule_wrapper = wrapper
        self.tx, self.schedule = make_optimizer(
            self.config.optimizer, self.config.train.total_steps,
            schedule_wrapper=wrapper,
            decay_mask_ref=self._decay_mask_ref,
        )
        # Per-bucket fused txs captured the old schedule — rebuild lazily.
        self._fused_txs = None

    # ------------------------------------------------------------- init --
    def _ensure_zero_plan(self, params: Any) -> "zero.ZeroPlan":
        """Build (once) the shard/bucket plan. Only shapes and tree paths
        are read, so tracers and ShapeDtypeStructs both work — the plan
        computed inside ``eval_shape`` is identical to the live one."""
        if self._zero_plan is None:
            self._zero_plan = zero.build_plan(
                params, self._zero_n, self.config.optimizer.zero_bucket_mb)
        return self._zero_plan

    def _ensure_fused_txs(self, params: Any) -> tuple:
        """One optax chain per ZeRO bucket (precision.fused_update), each
        carrying its bucket's positional subset of the weight-decay mask
        — the shard leaves the bucket update runs on have rank and path
        erased, so the mask must be precomputed from the real param tree
        (only paths/ranks are read: tracers and structs both work)."""
        if self._fused_txs is None:
            from distributed_tensorflow_framework_tpu.train.optimizers import (
                decay_mask_tree,
            )

            plan = self._ensure_zero_plan(params)
            mask_leaves = jax.tree.leaves(decay_mask_tree(params))
            self._fused_txs = tuple(
                make_optimizer(
                    self.config.optimizer, self.config.train.total_steps,
                    schedule_wrapper=self._schedule_wrapper,
                    decay_mask=tuple(
                        mask_leaves[lc.index] for lc in bucket),
                )[0]
                for bucket in plan.buckets
            )
        return self._fused_txs

    def _create_state(self, seed_arr: jax.Array, batch: Any) -> TrainState:
        root = jax.random.key(seed_arr[0])
        init_rng = prng.for_role(root, prng.ROLE_INIT)
        dropout_root = prng.for_role(root, prng.ROLE_DROPOUT)
        inputs = model_inputs(self.task, batch)
        variables = self.model.init(
            {"params": init_rng, "dropout": dropout_root}, *inputs, train=False
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        residual = None
        if self._use_residual:
            # One f32 row per data-parallel replica, globally
            # (n_dp, *param.shape) sharded over DATA_AXES — each replica's
            # local slice is its own uncompensated quantization error.
            n_dp = (self.mesh.shape.get("data", 1)
                    * self.mesh.shape.get("fsdp", 1))
            residual = jax.tree.map(
                lambda p: jnp.zeros((n_dp,) + p.shape, jnp.float32), params
            )
        opt_params = None
        opt_state = None
        if self._zero:
            # Slots are born at the stacked (n, chunk) layout — row i is
            # replica i's shard of the flattened leaf (parallel/zero.py).
            plan = self._ensure_zero_plan(params)
            opt_params = zero.stacked_shards(params, plan)
            if self._fused:
                # Fused update: one optax state per reduce-scatter bucket
                # (same slot bytes, grouped by the walk's issue order).
                txs = self._ensure_fused_txs(params)
                s_leaves = jax.tree.leaves(opt_params)
                opt_state = tuple(
                    tx_b.init(tuple(s_leaves[lc.index] for lc in bucket))
                    for tx_b, bucket in zip(txs, plan.buckets)
                )
                opt_params = None
        return TrainState.create(
            params=params, batch_stats=batch_stats, tx=self.tx,
            rng=dropout_root, ema=self.config.optimizer.ema_decay > 0,
            collective_residual=residual, opt_params=opt_params,
            opt_state=opt_state,
        )

    def state_specs(self, sample_batch: Any) -> Any:
        if self._state_specs is None:
            seed = jnp.zeros((1,), jnp.uint32)
            shapes = jax.eval_shape(self._create_state, seed, sample_batch)
            if self._zero:
                # Rebuild tx with the weight-decay mask PRECOMPUTED from
                # the real param tree: the shard-domain update sees
                # flattened 1-D leaves, so the rank/path-based mask
                # callable would misclassify every leaf. Mask values do
                # not change opt-state structure or init values (masked
                # optax wrappers are stateless), so the eval_shape above
                # — taken with the callable-mask tx — stays valid.
                self._decay_mask_ref = shapes.params
                self.tx, self.schedule = make_optimizer(
                    self.config.optimizer, self.config.train.total_steps,
                    schedule_wrapper=self._schedule_wrapper,
                    decay_mask_ref=self._decay_mask_ref,
                )
            if self.shard_map_mode:
                # Pure DP (reference semantics) replicates everything.
                # Explicit fsdp (mesh.fsdp>1) shards params / optimizer
                # slots / EMA over the fsdp axis by shape; the EF residual
                # shards its replica row over the combined data axes.
                specs = jax.tree.map(lambda _: P(), shapes)
                if self._explicit_fsdp:
                    if jax.tree.leaves(shapes.batch_stats):
                        raise ValueError(
                            "explicit fsdp (spmd_mode='shard_map' with "
                            "mesh.fsdp>1) does not support BN models: "
                            "running stats would be updated from gathered "
                            "params on every replica — use spmd_mode='jit' "
                            "or a BN-free model"
                        )
                    fsdp_n = self.mesh.shape["fsdp"]

                    def leaf_spec(s):
                        d = _fsdp_dim(s.shape, fsdp_n)
                        if d < 0:
                            return P()
                        parts = [None] * len(s.shape)
                        parts[d] = "fsdp"
                        return P(*parts)

                    self._fsdp_dims = jax.tree.map(
                        lambda s: _fsdp_dim(s.shape, fsdp_n), shapes.params)
                    specs = specs.replace(
                        params=jax.tree.map(leaf_spec, shapes.params),
                        opt_state=jax.tree.map(leaf_spec, shapes.opt_state),
                        ema_params=jax.tree.map(leaf_spec,
                                                shapes.ema_params),
                    )
                if self._zero:
                    # Stacked (n, chunk) slots shard their row dim over
                    # the combined data axes — per-device slot HBM ~1/n.
                    # Scalars (optax step counters) stay replicated.
                    specs = specs.replace(opt_state=jax.tree.map(
                        lambda s: (P(DATA_AXES)
                                   if getattr(s, "ndim", 0) >= 2 else P()),
                        shapes.opt_state))
                if self._use_residual:
                    specs = specs.replace(collective_residual=jax.tree.map(
                        lambda _: P(DATA_AXES), shapes.collective_residual))
                self._state_specs = specs
            elif self._zero_jit:
                # ZeRO-1 (cross-replica weight-update sharding): params /
                # BN stats / EMA replicated like pure DP, optimizer slots
                # sharded over fsdp. XLA partitions the weight update and
                # all-gathers the new params (SURVEY.md §7 hard part 5).
                base = shd.infer_param_specs(shapes, self.mesh, fsdp=False)
                opt = shd.infer_param_specs(shapes.opt_state, self.mesh,
                                            fsdp=True)
                self._state_specs = base.replace(opt_state=opt)
            else:
                self._state_specs = shd.infer_param_specs(shapes, self.mesh)
        return self._state_specs

    def init_state(self, seed: int, sample_batch: Any) -> TrainState:
        """Create the sharded TrainState directly on the mesh (params are
        materialized device-side with their final shardings — no host
        round-trip)."""
        specs = self.state_specs(sample_batch)
        out_sh = shd.specs_to_shardings(specs, self.mesh)
        create = jax.jit(self._create_state, out_shardings=out_sh)
        seed_arr = jnp.asarray([seed], jnp.uint32)
        return create(seed_arr, sample_batch)

    # ------------------------------------------------------- train step --
    def _has_bn(self, state: TrainState) -> bool:
        return bool(jax.tree.leaves(state.batch_stats))

    def _loss_and_updates(self, state: TrainState, batch: Any):
        """Shared fwd/bwd body (identical in both SPMD modes), with
        optional gradient accumulation over microbatches."""
        accum = self.config.train.grad_accum_steps
        if accum <= 1:
            return self._microbatch_grads(state, batch)
        return self._accumulated_grads(state, batch, accum)

    def _microbatch_grads(self, state: TrainState, batch: Any):
        step_rng = prng.fold_in_step(state.rng, state.step)
        has_bn = self._has_bn(state)
        inputs = model_inputs(self.task, batch)

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
            out = self.model.apply(
                variables,
                *inputs,
                train=True,
                mutable=["batch_stats"] if has_bn else False,
                rngs={"dropout": step_rng},
            )
            if has_bn:
                logits, new_model_state = out
            else:
                logits, new_model_state = out, {}
            if self.task == "mlm":
                moe_aux = moe_drop = moe_zloss = None
                if isinstance(logits, dict):  # MoE model: logits + aux dict
                    moe_aux = logits.get("moe_aux_loss")
                    # Router diagnostics arrive as EXPLICIT model outputs
                    # (models/moe.py) — return values thread through
                    # jax.checkpoint, so these stay observable under
                    # model.remat where sown intermediates would vanish.
                    moe_drop = logits.get("moe_drop_frac")
                    # z-loss is emitted only when the knob is armed, so
                    # moe_aux_loss — balance aux PLUS the weighted z term
                    # (the loss-side contract) — can be disambiguated when
                    # reading a collapse signature (docs/DISTRIBUTED.md).
                    moe_zloss = logits.get("moe_zloss")
                    logits = logits["logits"]
                loss, metrics = losses.mlm_loss(logits, batch["targets"])
                if moe_aux is not None:
                    loss = loss + self.config.train.moe_aux_weight * moe_aux
                    metrics["moe_aux_loss"] = moe_aux
                    metrics["total_loss"] = loss
                if moe_drop is not None:
                    # Mean over the model's MoE layers. Under grad
                    # accumulation this rides the shared masked-token
                    # metric weighting (slightly skewed vs a plain
                    # per-microbatch mean) — fine for a diagnostic.
                    metrics["moe_drop_frac"] = moe_drop
                if moe_zloss is not None:
                    metrics["moe_zloss"] = moe_zloss
            else:
                aux_logits = None
                if isinstance(logits, dict):  # Inception aux head
                    aux_logits = logits.get("aux_logits")
                    logits = logits["logits"]
                loss, metrics = losses.classification_loss(
                    logits,
                    batch["label"],
                    label_smoothing=self.config.train.label_smoothing,
                )
                if aux_logits is not None:
                    aux_loss, _ = losses.classification_loss(
                        aux_logits,
                        batch["label"],
                        label_smoothing=self.config.train.label_smoothing,
                    )
                    # Canonical Inception-v3 auxiliary weighting.
                    loss = loss + 0.4 * aux_loss
                    metrics["aux_loss"] = aux_loss
                    metrics["total_loss"] = loss
            return loss, (metrics, new_model_state)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, (metrics, new_model_state)), grads = grad_fn(state.params)
        return grads, metrics, new_model_state

    def _microbatch_weight(self, mb: Any) -> jax.Array:
        """Each microbatch's share of the full-batch loss denominator.

        Classification losses are means over examples (equal microbatches →
        equal weights); MLM normalizes by the masked-token count, which
        varies per microbatch under dynamic masking — weighting by it makes
        the accumulated gradient exactly the full-batch gradient."""
        if self.task == "mlm":
            return losses.mlm_mask(mb["targets"]).sum()
        return jnp.float32(1.0)

    def _accumulated_grads(self, state: TrainState, batch: Any, accum: int):
        """Split the batch into `accum` microbatches, scan fwd/bwd
        accumulating the denominator-weighted gradient sum — numerically
        the full-batch gradient at 1/accum the activation memory. BN
        running stats thread through the scan sequentially; the dropout
        rng differs per microbatch (step folded with the microbatch
        index). The MoE aux loss becomes a weighted mean of per-microbatch
        aux losses (routing capacity is per-microbatch under accumulation,
        so this is the quantity its gradient actually regularizes)."""

        def split(path, x):
            if x.shape[0] % accum:
                raise ValueError(
                    f"grad_accum_steps={accum} does not divide batch leaf "
                    f"{shd._path_str(path)} of size {x.shape[0]}"
                )
            return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

        micro = jax.tree_util.tree_map_with_path(split, batch)
        first = jax.tree.map(lambda x: x[0], micro)
        st0 = state.replace(step=state.step * accum)
        g_shape, m_shape, _ = jax.eval_shape(self._microbatch_grads, st0, first)
        zeros = lambda tree: jax.tree.map(  # noqa: E731
            lambda s: jnp.zeros(s.shape, s.dtype), tree
        )

        def body(carry, xs):
            stats, grads_sum, metrics_sum, w_sum = carry
            i, mb = xs
            st = state.replace(batch_stats=stats, step=state.step * accum + i)
            g, m, ms = self._microbatch_grads(st, mb)
            w = self._microbatch_weight(mb)
            return (
                ms.get("batch_stats", stats),
                jax.tree.map(lambda a, b: a + w * b, grads_sum, g),
                jax.tree.map(lambda a, b: a + w * b, metrics_sum, m),
                w_sum + w,
            ), None

        carry0 = (state.batch_stats, zeros(g_shape), zeros(m_shape),
                  jnp.float32(0.0))
        (stats, grads, metrics, w_sum), _ = jax.lax.scan(
            body, carry0, (jnp.arange(accum), micro)
        )
        inv = 1.0 / jnp.maximum(w_sum, 1e-9)
        grads = jax.tree.map(lambda g: g * inv, grads)
        metrics = jax.tree.map(lambda m: m * inv, metrics)
        new_model_state = {"batch_stats": stats} if self._has_bn(state) else {}
        return grads, metrics, new_model_state

    def _apply_updates(self, state, grads, metrics, new_model_state):
        # named_scope → op_name metadata on every optimizer HLO op, the
        # handle core/trace_analysis.py uses to attribute trace time to
        # the optimizer-update category.
        with jax.named_scope("optimizer_update"):
            updates, new_opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = coll.global_norm(grads)
        return self._finalize_state(state, new_params, new_opt_state,
                                    metrics, new_model_state)

    def _finalize_state(self, state, new_params, new_opt_state, metrics,
                        new_model_state):
        """Shared post-update tail: lr/bubble metrics, EMA, state.replace.
        Split from _apply_updates so the ZeRO path — whose update runs on
        shards and produces new_params/new_opt_state its own way — reuses
        the exact same trailing semantics."""
        metrics = dict(metrics)
        metrics["learning_rate"] = self.schedule(state.step)
        stages = self.config.model.pipeline_stages
        if stages > 1:
            # Analytic schedule bubble — fill/drain slots over total slots
            # (parallel/schedule.py, single source of truth per schedule;
            # gpipe keeps its original (S-1)/(M+S-1)). Static for a static
            # schedule — logged per step so PP runs carry their fill-drain
            # overhead in the metric stream (VERDICT r4 #6).
            from distributed_tensorflow_framework_tpu.parallel import (
                schedule as pipe_sched,
            )

            micro = self.config.model.pipeline_microbatches or stages
            metrics["pipe_bubble_frac"] = jnp.float32(pipe_sched.bubble_frac(
                self.config.model.pipeline_schedule, stages, micro,
                self._pipe_virtual))
        ema_decay = self.config.optimizer.ema_decay
        if ema_decay > 0:
            # tf.train.ExponentialMovingAverage(num_updates=step) schedule:
            # early steps track params closely, late steps converge to decay.
            t = state.step.astype(jnp.float32)
            d = jnp.minimum(ema_decay, (1.0 + t) / (10.0 + t))
            new_ema = jax.tree.map(
                lambda e, p: e * d + p.astype(e.dtype) * (1.0 - d),
                state.ema_params, new_params,
            )
        else:
            new_ema = state.ema_params
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_model_state.get("batch_stats", state.batch_stats),
            ema_params=new_ema,
        )
        return new_state, metrics

    def _train_step_jit(self, state: TrainState, batch: Any):
        # Mesh context (trace-time only) arms best-effort activation
        # sharding hints inside the models (shd.constrain_activation);
        # the shard_map twin deliberately never enters one.
        with self.mesh:
            grads, metrics, new_model_state = self._loss_and_updates(
                state, batch)
            # Loss is a global-batch mean → grads already carry the
            # cross-replica-sum; no explicit collective needed.
            return self._apply_updates(state, grads, metrics,
                                       new_model_state)

    def _zero_train_step_replica(self, state: TrainState, batch: Any):
        """Per-replica ZeRO step (optimizer.zero_sharding='shard_map').

        Replaces the monolithic all-reduce with: bucketed mean
        reduce-scatter of the grads (reverse layer order — each bucket's
        collective overlaps the backward of the layers issued after it,
        parallel/zero.py) → per-replica optax update on this replica's
        1/n of the flattened weights → bucketed all-gather of the UPDATE
        values → every replica applies the identical update to its full
        f32 master params. Params/EMA/BN stay replicated (pure-DP
        forward); only the slots and the update are sharded.
        """
        wire = self._collective_dtype or None
        block = self._collective_block
        plan = self._ensure_zero_plan(state.params)
        grads, metrics, new_model_state = self._loss_and_updates(
            state, batch)
        residual = None
        if self._use_residual:
            # Local (1, *shape) row of the global (n, *shape) residual —
            # this replica's carried int8 quantization error.
            residual = jax.tree.map(
                lambda r: r[0], state.collective_residual)
        if self._fused:
            # Fused donated update (precision.fused_update): per bucket,
            # RS → shard update → AG → apply, instead of three whole-tree
            # passes. Same collectives per bucket; params RMW'd once hot.
            txs = self._ensure_fused_txs(state.params)
            row = coll.linear_axis_index(DATA_AXES)
            new_params, new_opt, new_res, sq_sum = zero.fused_update_walk(
                plan, txs, grads, state.params, state.opt_state, DATA_AXES,
                wire_dtype=wire, block_size=block, residual=residual,
                row=row)
            metrics = coll.pmean(metrics, DATA_AXES)
            if self._has_bn(state):
                new_model_state = dict(new_model_state)
                new_model_state["batch_stats"] = coll.pmean(
                    new_model_state["batch_stats"], DATA_AXES)
            metrics = dict(metrics)
            # Same quantity shard_global_norm logs, from the walk's local
            # squared sums (coll.psum keeps the tally ledger identical).
            metrics["grad_norm"] = jnp.sqrt(
                coll.psum(sq_sum, DATA_AXES))
            new_state, metrics = self._finalize_state(
                state, new_params, new_opt, metrics, new_model_state)
            if new_res is not None:
                new_state = new_state.replace(
                    collective_residual=jax.tree.map(
                        lambda r: r[None], new_res))
            return new_state, metrics
        shard_grads, new_res = zero.bucketed_reduce_scatter(
            plan, grads, DATA_AXES, wire_dtype=wire, block_size=block,
            residual=residual)
        row = coll.linear_axis_index(DATA_AXES)
        param_shards = zero.local_shards(state.params, plan, row)
        opt_local = zero.squeeze_slots(state.opt_state)
        with jax.named_scope("optimizer_update"):
            updates, new_opt_local = self.tx.update(
                shard_grads, opt_local, param_shards)
        full_updates = zero.bucketed_all_gather(
            plan, updates, DATA_AXES, wire_dtype=wire, block_size=block)
        new_params = optax.apply_updates(state.params, full_updates)
        metrics = coll.pmean(metrics, DATA_AXES)
        if self._has_bn(state):
            new_model_state = dict(new_model_state)
            new_model_state["batch_stats"] = coll.pmean(
                new_model_state["batch_stats"], DATA_AXES)
        metrics = dict(metrics)
        # Norm of the full MEAN gradient, from its disjoint shards — the
        # same quantity the unsharded path logs.
        metrics["grad_norm"] = zero.shard_global_norm(shard_grads, DATA_AXES)
        new_state, metrics = self._finalize_state(
            state, new_params, zero.unsqueeze_slots(new_opt_local),
            metrics, new_model_state)
        if new_res is not None:
            new_state = new_state.replace(collective_residual=jax.tree.map(
                lambda r: r[None], new_res))
        return new_state, metrics

    def _train_step_replica(self, state: TrainState, batch: Any):
        if self._zero:
            return self._zero_train_step_replica(state, batch)
        wire = self._collective_dtype
        block = self._collective_block
        if self._explicit_fsdp:
            # Unshard params for fwd/bwd: the hand-placed (optionally
            # quantized) all_gather over fsdp — the explicit twin of the
            # jit path's XLA-inserted fsdp gather.
            def gather(p, dim):
                if dim < 0:
                    return p
                return coll.all_gather(p, "fsdp", axis=dim, tiled=True,
                                       wire_dtype=wire or None,
                                       block_size=block)

            full_params = jax.tree.map(gather, state.params, self._fsdp_dims)
            grads, metrics, new_model_state = self._loss_and_updates(
                state.replace(params=full_params), batch)
        else:
            grads, metrics, new_model_state = self._loss_and_updates(
                state, batch)
        # Explicit sync-DP: mean grads across replicas — the NCCL all-reduce
        # site of the reference (SURVEY.md §2 row 3). Optionally compressed
        # to a narrower wire dtype (parallel.collective_dtype): bfloat16
        # casts; int8 runs the block-scaled reduce, with the per-replica
        # quantization error carried in state.collective_residual when
        # error feedback is on.
        new_residual = None
        if self._use_residual:
            residual = jax.tree.map(lambda r: r[0], state.collective_residual)
            grads, new_res = coll.allreduce_gradients_ef(
                grads, residual, DATA_AXES, block_size=block)
            new_residual = jax.tree.map(lambda r: r[None], new_res)
        else:
            grads = coll.allreduce_gradients(
                grads, DATA_AXES,
                compute_dtype=jnp.dtype(wire) if wire else None,
                accumulate_f32=(
                    self.config.train.grad_allreduce_accum == "float32"),
                block_size=block,
            )
        full_grad_norm = None
        if self._explicit_fsdp:
            # The update runs on shards; grad_norm must come from the FULL
            # mean gradients, so take it before slicing.
            full_grad_norm = coll.global_norm(grads)
            fsdp_n = coll.axis_size("fsdp")
            idx = coll.axis_index("fsdp")

            def shard(g, dim):
                if dim < 0:
                    return g
                size = g.shape[dim] // fsdp_n
                return jax.lax.dynamic_slice_in_dim(
                    g, idx * size, size, axis=dim)

            grads = jax.tree.map(shard, grads, self._fsdp_dims)
        metrics = coll.pmean(metrics, DATA_AXES)
        if self._has_bn(state):
            # Running stats were updated from per/cross-replica batch stats;
            # average them so replicas stay consistent.
            new_model_state = dict(new_model_state)
            new_model_state["batch_stats"] = coll.pmean(
                new_model_state["batch_stats"], DATA_AXES
            )
        new_state, metrics = self._apply_updates(state, grads, metrics,
                                                 new_model_state)
        if full_grad_norm is not None:
            metrics["grad_norm"] = full_grad_norm
        if new_residual is not None:
            new_state = new_state.replace(collective_residual=new_residual)
        return new_state, metrics

    def make_train_step(self, sample_batch: Any) -> Callable:
        specs = self.state_specs(sample_batch)
        state_sh = shd.specs_to_shardings(specs, self.mesh)
        batch_sh = jax.tree.map(
            lambda _: NamedSharding(self.mesh, batch_spec(self.mesh)), sample_batch
        )
        if not self.shard_map_mode:
            return jax.jit(
                self._train_step_jit,
                in_shardings=(state_sh, batch_sh),
                out_shardings=(state_sh, None),
                donate_argnums=(0,),
            )

        state_P = specs
        batch_P = jax.tree.map(lambda _: batch_spec(self.mesh), sample_batch)
        # check_vma=False: with vma tracking on, jax's autodiff inserts the
        # cross-replica psum for replicated params itself and our explicit
        # pmean would double-count. The explicit-collective mode exists to
        # mirror the reference's SyncReplicasOptimizer pipeline, so we keep
        # the collectives visible and own them.
        mapped = coll.shard_map(
            self._train_step_replica,
            mesh=self.mesh,
            in_specs=(state_P, batch_P),
            out_specs=(state_P, P()),
            check_vma=False,
        )
        return jax.jit(
            mapped,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, None),
            donate_argnums=(0,),
        )

    # -------------------------------------------------------- eval step --
    def _eval_step(self, state: TrainState, batch: Any):
        """Weighted metric SUMS for one eval batch.

        Returns ``{*_sum, weight_sum}``; the eval loop accumulates and
        divides, making a full pass over a padded finite eval stream the
        EXACT metric over the real examples (SURVEY.md §3.4). Batches
        without a ``weight`` key (infinite synthetic streams) weight every
        example 1.0, which reproduces the plain batched mean.
        """
        has_bn = self._has_bn(state)
        use_ema = (
            self.config.optimizer.ema_decay > 0
            and self.config.train.eval_use_ema
            and jax.tree.leaves(state.ema_params)
        )
        variables = {"params": state.ema_params if use_ema else state.params}
        if has_bn:
            variables["batch_stats"] = state.batch_stats
        inputs = model_inputs(self.task, batch)
        with self.mesh:  # arm activation sharding hints (see train step)
            logits = self.model.apply(variables, *inputs, train=False)
        if isinstance(logits, dict):  # MoE aux loss / Inception aux head
            logits = logits["logits"]
        if self.task == "mlm":
            weight = batch.get(
                "weight", jnp.ones(batch["targets"].shape[0], jnp.float32)
            )
            return losses.mlm_metrics_sums(logits, batch["targets"], weight)
        weight = batch.get(
            "weight", jnp.ones(batch["label"].shape[0], jnp.float32)
        )
        return losses.classification_metrics_sums(logits, batch["label"], weight)

    def make_eval_step(self, sample_batch: Any) -> Callable:
        specs = self.state_specs(sample_batch)
        state_sh = shd.specs_to_shardings(specs, self.mesh)
        batch_sh = jax.tree.map(
            lambda _: NamedSharding(self.mesh, batch_spec(self.mesh)), sample_batch
        )
        return jax.jit(
            self._eval_step, in_shardings=(state_sh, batch_sh), out_shardings=None
        )
