"""Break a captured XPlane trace into time-by-category — the post-mortem
half of the telemetry subsystem (docs/OBSERVABILITY.md).

Takes a trace produced by ProfileHook (train.profile_start/stop) or
bench.py under BENCH_TRACE, prints the category table (GEMM/conv,
collectives, infeed, optimizer update, other compute, launch gaps) and
writes the same numbers as a schema-versioned ``trace_summary`` JSONL
event so the breakdown joins the run's other telemetry by run id.

Usage:

    python scripts/analyze_trace.py <trace.xplane.pb | trace dir> \
        [--hlo train_step.hlo.txt] [--json out.jsonl] [--run-id ID] [--top N]

With a directory, the newest ``*.xplane.pb`` under it is analyzed. The
optimized-HLO text (dumped next to the trace by ProfileHook/bench) is
auto-discovered when not given; without it, scope-based categories
(optimizer_update) fall back to other_compute.
"""

import argparse
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import trace_analysis as ta  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="*.xplane.pb file, or a directory to search")
    ap.add_argument("--hlo", default=None,
                    help="optimized HLO text for scope attribution "
                         "(default: auto-discover near the trace)")
    ap.add_argument("--json", default=None,
                    help="append the trace_summary event to this JSONL file "
                         "(default: <trace>.summary.jsonl)")
    ap.add_argument("--run-id", default=None,
                    help="run id to stamp on the summary event (use the id "
                         "from the run's events.jsonl to make them joinable)")
    ap.add_argument("--top", type=int, default=15,
                    help="number of top ops to list")
    args = ap.parse_args(argv)

    traces = ta.find_xplane_files(args.trace)
    if not traces:
        print(f"no *.xplane.pb under {args.trace!r}", file=sys.stderr)
        return 2
    trace = max(traces, key=os.path.getmtime)

    hlo_path = args.hlo or ta.find_hlo_text(trace)
    hlo_text = None
    if hlo_path and os.path.exists(hlo_path):
        with open(hlo_path) as fh:
            hlo_text = fh.read()

    report = ta.analyze_trace_file(trace, hlo_text, top_n=args.top)
    print(ta.format_report(report))
    if hlo_path and hlo_text:
        print(f"\nhlo: {hlo_path}")

    out = args.json or (trace + ".summary.jsonl")
    ta.write_summary_event(report, out, run_id=args.run_id)
    print(f"summary event appended to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
