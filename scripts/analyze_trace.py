"""Break a captured XPlane trace into time-by-category — the post-mortem
half of the telemetry subsystem (docs/OBSERVABILITY.md).

Takes a trace produced by ProfileHook (train.profile_start/stop) or
bench.py under BENCH_TRACE, prints the category table (GEMM/conv,
collectives, infeed, optimizer update, other compute, launch gaps) and
writes the same numbers as a schema-versioned ``trace_summary`` JSONL
event so the breakdown joins the run's other telemetry by run id.

Usage:

    python scripts/analyze_trace.py <trace.xplane.pb | trace dir> \
        [--hlo train_step.hlo.txt] [--json out.jsonl] [--run-id ID] [--top N]

With a directory, the newest ``*.xplane.pb`` under it is analyzed. The
optimized-HLO text (dumped next to the trace by ProfileHook/bench) is
auto-discovered when not given; without it, scope-based categories
(optimizer_update) fall back to other_compute.

Given an ``events.jsonl`` (or a run directory containing one), the tool
instead prints the run summary: event counts, step span, recovery
activity — quarantined checkpoints, restore fallbacks, supervisor
attempts, graceful preemptions (docs/RESILIENCE.md) — plus the
checkpoint save-stall accounting (loop-blocked vs total save time under
``checkpoint.async_save``), restart→first-step startup latency
(docs/PERFORMANCE.md), and the goodput ledger: every wall-clock second
across attempts bucketed into step compute vs overhead, restart gaps
stitched from supervisor events (core/goodput.py). Supervisor events
(``supervisor_events.jsonl`` next to it) are summarized too when
present.

Gang runs: a directory's ``events.jsonl`` + ``events-p<i>.jsonl``
siblings (the per-worker streams a multi-process run writes) are ONE
run — stitched together by run id + process_id into a single goodput
ledger with a per-host section, restart gaps classified from the
cluster supervisor's events. Multiple run-directory targets may be
given (per-worker run dirs on separate hosts); they merge the same way
and ``--json`` still emits ONE dtf-run-summary/1 object.

In run-summary mode ``--json`` (bare, or ``--json -``) prints the whole
summary as ONE machine-readable JSON object instead of the text tables
— drivers parse that; ``--json PATH`` writes the object to PATH and
still prints the text. In trace mode ``--json PATH`` keeps its original
meaning: the JSONL sink for the trace_summary event.
"""

import argparse
import json
import os
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import goodput  # noqa: E402
from distributed_tensorflow_framework_tpu.core import telemetry  # noqa: E402
from distributed_tensorflow_framework_tpu.core import trace_analysis as ta  # noqa: E402

RUN_SUMMARY_SCHEMA = "dtf-run-summary/1"
TRACE_SPANS_SCHEMA = "dtf-trace-spans/1"


def _events_files(target: str) -> list[str]:
    """events.jsonl paths for a target: the file itself, or any
    ``*events*.jsonl`` directly inside a run directory."""
    if os.path.isfile(target) and target.endswith(".jsonl"):
        return [target]
    if os.path.isdir(target):
        return sorted(
            os.path.join(target, name)
            for name in os.listdir(target)
            if name.endswith(".jsonl") and "events" in name
        )
    return []


# The per-worker telemetry streams of ONE gang run (core/metrics.py):
# the chief's events.jsonl plus each non-chief worker's events-p<i>.jsonl.
_GANG_STREAM_RE = re.compile(r"^events(-p\d+)?\.jsonl$")


def _group_streams(paths: list[str]) -> list[list[str]]:
    """Partition events files into run groups: every gang worker stream
    (events.jsonl / events-p<i>.jsonl, across ALL targets) folds into one
    group stitched by run id + process_id; anything else (e.g.
    supervisor_events.jsonl) stays its own single-file summary."""
    gang = [p for p in paths
            if _GANG_STREAM_RE.match(os.path.basename(p))]
    rest = [p for p in paths if p not in gang]
    groups: list[list[str]] = []
    if gang:
        # Chief stream first: the group's headline summary and the
        # stitched ledger's primary timeline both come from host 0.
        gang.sort(key=lambda p: (os.path.basename(p) != "events.jsonl", p))
        groups.append(gang)
    groups.extend([p] for p in rest)
    return groups


def summarize_run(targets, json_out: str | None = None) -> bool:
    """Print run summaries for every events JSONL under the target(s);
    False when there is none (caller falls through to trace analysis).

    ``json_out``: "-" prints ONLY the machine-readable object; a path
    writes the object there and still prints the text tables.
    """
    if isinstance(targets, str):
        targets = [targets]
    paths: list[str] = []
    for target in targets:
        for path in _events_files(target):
            if path not in paths:
                paths.append(path)
    if not paths:
        return False
    runs = []
    for group in _group_streams(paths):
        summary = telemetry.summarize_events(group[0])
        # Cross-attempt stitch: per-attempt goodput rollups + restart
        # gaps classified from supervisor_events.jsonl when present; a
        # gang group stitches every worker stream into one per-host
        # ledger keyed by run id + process_id.
        ledger = goodput.stitch_attempts(
            group if len(group) > 1 else group[0])
        runs.append((group, summary, ledger))
    if json_out:
        obj: dict = {"schema": RUN_SUMMARY_SCHEMA}
        docs = []
        for group, s, g in runs:
            doc = {"events_path": group[0], **s}
            if len(group) > 1:
                doc["worker_streams"] = group
            if g:
                doc["goodput_ledger"] = g
            docs.append(doc)
        if len(docs) == 1:
            obj.update(docs[0])
        else:
            obj["runs"] = docs
        text = json.dumps(obj, sort_keys=True, default=str)
        if json_out == "-":
            print(text)
            return True
        with open(json_out, "w") as fh:
            fh.write(text + "\n")
    for i, (group, summary, ledger) in enumerate(runs):
        if i:
            print()
        print(telemetry.format_run_summary(summary))
        if ledger:
            print(goodput.format_goodput_table(ledger))
    return True


# ---------------------------------------------------------------------------
# --spans: cross-process trace trees from KIND_SPAN telemetry
# ---------------------------------------------------------------------------

def collect_spans(paths: list[str]) -> list[dict]:
    """Normalized span records from every events JSONL given.

    Each record's ``t0``/``t1`` are ROOT-frame wall seconds: the raw
    ``t_start`` minus the emitting process's ``offset_s`` estimate
    (core/tracing.py clock model). Torn/non-JSON lines are skipped — a
    crashed writer must not take the post-mortem down with it.
    """
    spans: list[dict] = []
    seen: set = set()
    for path in paths:
        try:
            fh = open(path)
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except (ValueError, TypeError):
                    continue
                if not isinstance(ev, dict) \
                        or ev.get("kind") != telemetry.KIND_SPAN:
                    continue
                extra = ev.get("extra") or {}
                trace_id = extra.get("trace")
                span_id = extra.get("span")
                if not trace_id or not span_id \
                        or (trace_id, span_id) in seen:
                    continue
                seen.add((trace_id, span_id))
                try:
                    t_start = float(extra.get("t_start", 0.0))
                    offset_s = float(extra.get("offset_s", 0.0) or 0.0)
                    dur_ms = float(
                        (ev.get("metrics") or {}).get("dur_ms", 0.0))
                except (TypeError, ValueError):
                    continue
                t0 = t_start - offset_s
                spans.append({
                    "trace": str(trace_id), "span": str(span_id),
                    "parent": extra.get("parent") or None,
                    "name": str(extra.get("name", "?")),
                    "service": str(extra.get("service", "?")),
                    "status": str(extra.get("status", "?")),
                    "t0": t0, "t1": t0 + dur_ms / 1e3,
                    "dur_ms": dur_ms,
                    "attrs": extra.get("attrs") or {},
                })
    return spans


def _children_of(spans: list[dict]) -> dict:
    kids: dict = {}
    for s in spans:
        kids.setdefault(s["parent"], []).append(s)
    for group in kids.values():
        group.sort(key=lambda s: (s["t0"], -s["dur_ms"]))
    return kids


def build_traces(spans: list[dict]) -> list[dict]:
    """Group spans into per-trace trees and causally order them.

    Offset subtraction (done in collect_spans) handles skew between
    processes; the residual transmission-delay term can still float a
    child EARLIER than its parent's start, which is causally impossible
    — so children are clamped forward into the parent's window, the
    shift cascading down the subtree. Spans whose parent never got
    emitted (a crashed process) become extra roots rather than
    disappearing.
    """
    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    traces = []
    for trace_id, group in by_trace.items():
        ids = {s["span"] for s in group}
        roots = [s for s in group
                 if s["parent"] is None or s["parent"] not in ids]
        kids = _children_of(group)
        # Causal clamp, parents before children.
        stack = list(roots)
        while stack:
            parent = stack.pop()
            for child in kids.get(parent["span"], []):
                if child["t0"] < parent["t0"]:
                    shift = parent["t0"] - child["t0"]
                    child["t0"] += shift
                    child["t1"] += shift
                stack.append(child)
        t0 = min(s["t0"] for s in group)
        t1 = max(s["t1"] for s in group)
        traces.append({
            "trace": trace_id,
            "t0": t0,
            "dur_ms": (t1 - t0) * 1e3,
            "services": sorted({s["service"] for s in group}),
            "roots": sorted(roots, key=lambda s: s["t0"]),
            "children": kids,
            "spans": sorted(group, key=lambda s: (s["t0"], -s["dur_ms"])),
        })
    traces.sort(key=lambda t: t["t0"])
    return traces


def critical_path(trace: dict) -> dict:
    """Where a trace's wall-clock went, in ms buckets.

    queue        engine admission wait (engine.queue)
    compute      device time (engine.compute)
    batch_wait   in the batch window but not under compute
    retry        failed router attempts + backoff sleeps
    restart_gap  dead time between supervisor attempts
    """
    buckets = {"queue": 0.0, "compute": 0.0, "batch_wait": 0.0,
               "retry": 0.0, "restart_gap": 0.0}
    batch_ms = 0.0
    for s in trace["spans"]:
        name, dur = s["name"], s["dur_ms"]
        if name == "engine.queue":
            buckets["queue"] += dur
        elif name == "engine.compute":
            buckets["compute"] += dur
        elif name == "engine.batch":
            batch_ms += dur
        elif name == "fleet.attempt" and s["status"] != "ok":
            buckets["retry"] += dur
        elif name == "fleet.backoff":
            buckets["retry"] += dur
        elif name == "supervisor.restart_gap":
            buckets["restart_gap"] += dur
    buckets["batch_wait"] = max(0.0, batch_ms - buckets["compute"])
    buckets["total"] = trace["dur_ms"]
    return buckets


def format_trace_tree(trace: dict) -> str:
    """One trace as an indented tree, offsets relative to the trace root."""
    lines = [
        f"trace {trace['trace']}  "
        f"({trace['dur_ms']:.1f} ms, {len(trace['spans'])} span(s), "
        f"services: {', '.join(trace['services'])})"
    ]

    def walk(span: dict, depth: int) -> None:
        rel = (span["t0"] - trace["t0"]) * 1e3
        attrs = ""
        if span["attrs"]:
            attrs = "  " + " ".join(
                f"{k}={v}" for k, v in sorted(span["attrs"].items())
                if v is not None)
        lines.append(
            f"  {'  ' * depth}{span['name']} [{span['service']}]  "
            f"+{rel:.1f}ms {span['dur_ms']:.1f}ms {span['status']}{attrs}")
        for child in trace["children"].get(span["span"], []):
            walk(child, depth + 1)

    for root in trace["roots"]:
        walk(root, 0)
    cp = critical_path(trace)
    parts = [f"{k} {v:.1f}" for k, v in cp.items()
             if k != "total" and v > 0]
    if parts:
        lines.append("  critical path (ms): " + ", ".join(parts)
                     + f"  / total {cp['total']:.1f}")
    return "\n".join(lines)


def perfetto_export(traces: list[dict]) -> dict:
    """Chrome trace-event JSON (load in Perfetto / chrome://tracing).

    Complete events (``ph: "X"``), one process track per emitting
    service, timestamps in microseconds relative to the earliest span.
    """
    events: list[dict] = []
    services: dict[str, int] = {}
    epoch = min((t["t0"] for t in traces), default=0.0)
    for trace in traces:
        for s in trace["spans"]:
            pid = services.setdefault(s["service"], len(services) + 1)
            events.append({
                "name": s["name"], "cat": s["service"], "ph": "X",
                "pid": pid, "tid": 1,
                "ts": (s["t0"] - epoch) * 1e6,
                "dur": s["dur_ms"] * 1e3,
                "args": {"trace": s["trace"], "span": s["span"],
                         "parent": s["parent"], "status": s["status"],
                         **{k: v for k, v in s["attrs"].items()}},
            })
    for service, pid in services.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": service}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def summarize_spans(targets, json_out: str | None = None,
                    perfetto_out: str | None = None) -> bool:
    """--spans driver: trace trees + critical paths across run dirs;
    False when no events file holds a single span."""
    if isinstance(targets, str):
        targets = [targets]
    paths: list[str] = []
    for target in targets:
        for path in _events_files(target):
            if path not in paths:
                paths.append(path)
    spans = collect_spans(paths)
    if not spans:
        return False
    traces = build_traces(spans)
    if perfetto_out:
        with open(perfetto_out, "w") as fh:
            json.dump(perfetto_export(traces), fh)
            fh.write("\n")
    if json_out:
        obj = {
            "schema": TRACE_SPANS_SCHEMA,
            "traces": [{
                "trace": t["trace"], "t0": t["t0"], "dur_ms": t["dur_ms"],
                "services": t["services"],
                "critical_path": critical_path(t),
                "spans": [{k: v for k, v in s.items()}
                          for s in t["spans"]],
            } for t in traces],
        }
        text = json.dumps(obj, sort_keys=True, default=str)
        if json_out == "-":
            print(text)
            return True
        with open(json_out, "w") as fh:
            fh.write(text + "\n")
    for i, trace in enumerate(traces):
        if i:
            print()
        print(format_trace_tree(trace))
    if perfetto_out:
        print(f"\nperfetto export written to {perfetto_out}")
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="*.xplane.pb file, a run directory, or several "
                         "per-worker run directories (merged into one "
                         "summary)")
    ap.add_argument("--hlo", default=None,
                    help="optimized HLO text for scope attribution "
                         "(default: auto-discover near the trace)")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    help="run-summary mode: print (bare / '-') or write "
                         "(PATH) the summary as one JSON object; trace "
                         "mode: append the trace_summary event to this "
                         "JSONL file (default: <trace>.summary.jsonl)")
    ap.add_argument("--run-id", default=None,
                    help="run id to stamp on the summary event (use the id "
                         "from the run's events.jsonl to make them joinable)")
    ap.add_argument("--top", type=int, default=15,
                    help="number of top ops to list")
    ap.add_argument("--spans", action="store_true",
                    help="span mode: stitch KIND_SPAN telemetry across the "
                         "given run dirs into causally ordered trace trees "
                         "with per-request critical paths (--json '-' for "
                         "the machine-readable object)")
    ap.add_argument("--perfetto", default=None, metavar="PATH",
                    help="span mode: also write a Chrome trace-event JSON "
                         "(open in https://ui.perfetto.dev)")
    args = ap.parse_args(argv)

    if args.spans:
        if not summarize_spans(args.trace, json_out=args.json,
                               perfetto_out=args.perfetto):
            print(f"no span events under {args.trace!r}", file=sys.stderr)
            return 2
        return 0

    # events.jsonl → run summary (recovery activity); a run DIRECTORY gets
    # both the run summary and, below, its newest trace when one exists.
    primary = args.trace[0]
    summarized = summarize_run(args.trace, json_out=args.json)
    if summarized and (len(args.trace) > 1 or os.path.isfile(primary)
                       or args.json == "-"):
        return 0

    traces = ta.find_xplane_files(primary)
    if not traces:
        if summarized:
            return 0
        print(f"no *.xplane.pb under {primary!r}", file=sys.stderr)
        return 2
    if summarized:
        print()
    trace = max(traces, key=os.path.getmtime)

    hlo_path = args.hlo or ta.find_hlo_text(trace)
    hlo_text = None
    if hlo_path and os.path.exists(hlo_path):
        with open(hlo_path) as fh:
            hlo_text = fh.read()

    report = ta.analyze_trace_file(trace, hlo_text, top_n=args.top)
    print(ta.format_report(report))
    if hlo_path and hlo_text:
        print(f"\nhlo: {hlo_path}")

    out = (args.json if args.json and args.json != "-"
           else trace + ".summary.jsonl")
    ta.write_summary_event(report, out, run_id=args.run_id)
    print(f"summary event appended to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
