"""Break a captured XPlane trace into time-by-category — the post-mortem
half of the telemetry subsystem (docs/OBSERVABILITY.md).

Takes a trace produced by ProfileHook (train.profile_start/stop) or
bench.py under BENCH_TRACE, prints the category table (GEMM/conv,
collectives, infeed, optimizer update, other compute, launch gaps) and
writes the same numbers as a schema-versioned ``trace_summary`` JSONL
event so the breakdown joins the run's other telemetry by run id.

Usage:

    python scripts/analyze_trace.py <trace.xplane.pb | trace dir> \
        [--hlo train_step.hlo.txt] [--json out.jsonl] [--run-id ID] [--top N]

With a directory, the newest ``*.xplane.pb`` under it is analyzed. The
optimized-HLO text (dumped next to the trace by ProfileHook/bench) is
auto-discovered when not given; without it, scope-based categories
(optimizer_update) fall back to other_compute.

Given an ``events.jsonl`` (or a run directory containing one), the tool
instead prints the run summary: event counts, step span, recovery
activity — quarantined checkpoints, restore fallbacks, supervisor
attempts, graceful preemptions (docs/RESILIENCE.md) — plus the
checkpoint save-stall accounting (loop-blocked vs total save time under
``checkpoint.async_save``) and restart→first-step startup latency
(docs/PERFORMANCE.md). Supervisor events (``supervisor_events.jsonl``
next to it) are summarized too when present.
"""

import argparse
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import telemetry  # noqa: E402
from distributed_tensorflow_framework_tpu.core import trace_analysis as ta  # noqa: E402


def _events_files(target: str) -> list[str]:
    """events.jsonl paths for a target: the file itself, or any
    ``*events*.jsonl`` directly inside a run directory."""
    if os.path.isfile(target) and target.endswith(".jsonl"):
        return [target]
    if os.path.isdir(target):
        return sorted(
            os.path.join(target, name)
            for name in os.listdir(target)
            if name.endswith(".jsonl") and "events" in name
        )
    return []


def summarize_run(target: str) -> bool:
    """Print run summaries for every events JSONL under ``target``; False
    when there is none (caller falls through to trace analysis)."""
    paths = _events_files(target)
    if not paths:
        return False
    for i, path in enumerate(paths):
        if i:
            print()
        print(telemetry.format_run_summary(telemetry.summarize_events(path)))
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="*.xplane.pb file, or a directory to search")
    ap.add_argument("--hlo", default=None,
                    help="optimized HLO text for scope attribution "
                         "(default: auto-discover near the trace)")
    ap.add_argument("--json", default=None,
                    help="append the trace_summary event to this JSONL file "
                         "(default: <trace>.summary.jsonl)")
    ap.add_argument("--run-id", default=None,
                    help="run id to stamp on the summary event (use the id "
                         "from the run's events.jsonl to make them joinable)")
    ap.add_argument("--top", type=int, default=15,
                    help="number of top ops to list")
    args = ap.parse_args(argv)

    # events.jsonl → run summary (recovery activity); a run DIRECTORY gets
    # both the run summary and, below, its newest trace when one exists.
    summarized = summarize_run(args.trace)
    if summarized and os.path.isfile(args.trace):
        return 0

    traces = ta.find_xplane_files(args.trace)
    if not traces:
        if summarized:
            return 0
        print(f"no *.xplane.pb under {args.trace!r}", file=sys.stderr)
        return 2
    if summarized:
        print()
    trace = max(traces, key=os.path.getmtime)

    hlo_path = args.hlo or ta.find_hlo_text(trace)
    hlo_text = None
    if hlo_path and os.path.exists(hlo_path):
        with open(hlo_path) as fh:
            hlo_text = fh.read()

    report = ta.analyze_trace_file(trace, hlo_text, top_n=args.top)
    print(ta.format_report(report))
    if hlo_path and hlo_text:
        print(f"\nhlo: {hlo_path}")

    out = args.json or (trace + ".summary.jsonl")
    ta.write_summary_event(report, out, run_id=args.run_id)
    print(f"summary event appended to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
