"""Break a captured XPlane trace into time-by-category — the post-mortem
half of the telemetry subsystem (docs/OBSERVABILITY.md).

Takes a trace produced by ProfileHook (train.profile_start/stop) or
bench.py under BENCH_TRACE, prints the category table (GEMM/conv,
collectives, infeed, optimizer update, other compute, launch gaps) and
writes the same numbers as a schema-versioned ``trace_summary`` JSONL
event so the breakdown joins the run's other telemetry by run id.

Usage:

    python scripts/analyze_trace.py <trace.xplane.pb | trace dir> \
        [--hlo train_step.hlo.txt] [--json out.jsonl] [--run-id ID] [--top N]

With a directory, the newest ``*.xplane.pb`` under it is analyzed. The
optimized-HLO text (dumped next to the trace by ProfileHook/bench) is
auto-discovered when not given; without it, scope-based categories
(optimizer_update) fall back to other_compute.

Given an ``events.jsonl`` (or a run directory containing one), the tool
instead prints the run summary: event counts, step span, recovery
activity — quarantined checkpoints, restore fallbacks, supervisor
attempts, graceful preemptions (docs/RESILIENCE.md) — plus the
checkpoint save-stall accounting (loop-blocked vs total save time under
``checkpoint.async_save``), restart→first-step startup latency
(docs/PERFORMANCE.md), and the goodput ledger: every wall-clock second
across attempts bucketed into step compute vs overhead, restart gaps
stitched from supervisor events (core/goodput.py). Supervisor events
(``supervisor_events.jsonl`` next to it) are summarized too when
present.

Gang runs: a directory's ``events.jsonl`` + ``events-p<i>.jsonl``
siblings (the per-worker streams a multi-process run writes) are ONE
run — stitched together by run id + process_id into a single goodput
ledger with a per-host section, restart gaps classified from the
cluster supervisor's events. Multiple run-directory targets may be
given (per-worker run dirs on separate hosts); they merge the same way
and ``--json`` still emits ONE dtf-run-summary/1 object.

In run-summary mode ``--json`` (bare, or ``--json -``) prints the whole
summary as ONE machine-readable JSON object instead of the text tables
— drivers parse that; ``--json PATH`` writes the object to PATH and
still prints the text. In trace mode ``--json PATH`` keeps its original
meaning: the JSONL sink for the trace_summary event.
"""

import argparse
import json
import os
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import goodput  # noqa: E402
from distributed_tensorflow_framework_tpu.core import telemetry  # noqa: E402
from distributed_tensorflow_framework_tpu.core import trace_analysis as ta  # noqa: E402

RUN_SUMMARY_SCHEMA = "dtf-run-summary/1"


def _events_files(target: str) -> list[str]:
    """events.jsonl paths for a target: the file itself, or any
    ``*events*.jsonl`` directly inside a run directory."""
    if os.path.isfile(target) and target.endswith(".jsonl"):
        return [target]
    if os.path.isdir(target):
        return sorted(
            os.path.join(target, name)
            for name in os.listdir(target)
            if name.endswith(".jsonl") and "events" in name
        )
    return []


# The per-worker telemetry streams of ONE gang run (core/metrics.py):
# the chief's events.jsonl plus each non-chief worker's events-p<i>.jsonl.
_GANG_STREAM_RE = re.compile(r"^events(-p\d+)?\.jsonl$")


def _group_streams(paths: list[str]) -> list[list[str]]:
    """Partition events files into run groups: every gang worker stream
    (events.jsonl / events-p<i>.jsonl, across ALL targets) folds into one
    group stitched by run id + process_id; anything else (e.g.
    supervisor_events.jsonl) stays its own single-file summary."""
    gang = [p for p in paths
            if _GANG_STREAM_RE.match(os.path.basename(p))]
    rest = [p for p in paths if p not in gang]
    groups: list[list[str]] = []
    if gang:
        # Chief stream first: the group's headline summary and the
        # stitched ledger's primary timeline both come from host 0.
        gang.sort(key=lambda p: (os.path.basename(p) != "events.jsonl", p))
        groups.append(gang)
    groups.extend([p] for p in rest)
    return groups


def summarize_run(targets, json_out: str | None = None) -> bool:
    """Print run summaries for every events JSONL under the target(s);
    False when there is none (caller falls through to trace analysis).

    ``json_out``: "-" prints ONLY the machine-readable object; a path
    writes the object there and still prints the text tables.
    """
    if isinstance(targets, str):
        targets = [targets]
    paths: list[str] = []
    for target in targets:
        for path in _events_files(target):
            if path not in paths:
                paths.append(path)
    if not paths:
        return False
    runs = []
    for group in _group_streams(paths):
        summary = telemetry.summarize_events(group[0])
        # Cross-attempt stitch: per-attempt goodput rollups + restart
        # gaps classified from supervisor_events.jsonl when present; a
        # gang group stitches every worker stream into one per-host
        # ledger keyed by run id + process_id.
        ledger = goodput.stitch_attempts(
            group if len(group) > 1 else group[0])
        runs.append((group, summary, ledger))
    if json_out:
        obj: dict = {"schema": RUN_SUMMARY_SCHEMA}
        docs = []
        for group, s, g in runs:
            doc = {"events_path": group[0], **s}
            if len(group) > 1:
                doc["worker_streams"] = group
            if g:
                doc["goodput_ledger"] = g
            docs.append(doc)
        if len(docs) == 1:
            obj.update(docs[0])
        else:
            obj["runs"] = docs
        text = json.dumps(obj, sort_keys=True, default=str)
        if json_out == "-":
            print(text)
            return True
        with open(json_out, "w") as fh:
            fh.write(text + "\n")
    for i, (group, summary, ledger) in enumerate(runs):
        if i:
            print()
        print(telemetry.format_run_summary(summary))
        if ledger:
            print(goodput.format_goodput_table(ledger))
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="*.xplane.pb file, a run directory, or several "
                         "per-worker run directories (merged into one "
                         "summary)")
    ap.add_argument("--hlo", default=None,
                    help="optimized HLO text for scope attribution "
                         "(default: auto-discover near the trace)")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    help="run-summary mode: print (bare / '-') or write "
                         "(PATH) the summary as one JSON object; trace "
                         "mode: append the trace_summary event to this "
                         "JSONL file (default: <trace>.summary.jsonl)")
    ap.add_argument("--run-id", default=None,
                    help="run id to stamp on the summary event (use the id "
                         "from the run's events.jsonl to make them joinable)")
    ap.add_argument("--top", type=int, default=15,
                    help="number of top ops to list")
    args = ap.parse_args(argv)

    # events.jsonl → run summary (recovery activity); a run DIRECTORY gets
    # both the run summary and, below, its newest trace when one exists.
    primary = args.trace[0]
    summarized = summarize_run(args.trace, json_out=args.json)
    if summarized and (len(args.trace) > 1 or os.path.isfile(primary)
                       or args.json == "-"):
        return 0

    traces = ta.find_xplane_files(primary)
    if not traces:
        if summarized:
            return 0
        print(f"no *.xplane.pb under {primary!r}", file=sys.stderr)
        return 2
    if summarized:
        print()
    trace = max(traces, key=os.path.getmtime)

    hlo_path = args.hlo or ta.find_hlo_text(trace)
    hlo_text = None
    if hlo_path and os.path.exists(hlo_path):
        with open(hlo_path) as fh:
            hlo_text = fh.read()

    report = ta.analyze_trace_file(trace, hlo_text, top_n=args.top)
    print(ta.format_report(report))
    if hlo_path and hlo_text:
        print(f"\nhlo: {hlo_path}")

    out = (args.json if args.json and args.json != "-"
           else trace + ".summary.jsonl")
    ta.write_summary_event(report, out, run_id=args.run_id)
    print(f"summary event appended to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
