#!/usr/bin/env python
"""Goodput-driven autotuner CLI (tools/autotune; docs/PERFORMANCE.md
"Autotuning").

Two modes, one journal/runner/scoring machinery:

  --space SPEC.json     roofline-pruned config search over a typed knob
                        space (tools/autotune/space): candidates the
                        analytic traffic model predicts more than
                        autotune.prune_margin worse than the incumbent
                        on the binding resource are skipped with the
                        prediction logged; survivors run as supervised
                        bench.py subprocesses, are scored
                        goodput-weighted from their run summary, and the
                        winner is pinned in configs/leaderboard.json +
                        configs/best_<workload>.yaml (bench.py reads the
                        pin back and flags regressions).
  --plan chip_window    the compiled scripts/chip_window_queue.sh
                        backlog (§0/§0b preflights, BENCH_r02
                        revalidation first, then the §13 precision
                        ladder, then §7–§17 and the round-5 tail) run
                        through the same journal. --dry-run prints the
                        prioritized trial list without spending anything.

Exit codes follow the queue's taxonomy: 0 done, 1 real failure (a §0/§0b
preflight failing refuses the window), 3 probe hang — the WINDOW is
aborted but the dtf-autotune-journal/1 journal keeps every settled trial,
so re-landing the same command continues where it stopped.

SPEC.json: {"workload": ..., "incumbent": {chip, n_chips, flops_per_step,
hbm_bytes_per_step, wire_bytes_per_step, opt_state_bytes,
examples_per_step}, "knobs": [{"path": "precision.activation_dtype",
"values": ["", "bf16"], "env": "BENCH_PRECISION"}, ...]} — knob paths are
validated against the real config dataclasses; each knob's FIRST value is
the incumbent's setting.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="autotune.py",
        description="roofline-pruned, goodput-scored config search")
    p.add_argument("--plan", choices=("chip_window",),
                   help="run a compiled plan instead of a space search")
    p.add_argument("--space", help="SearchSpace spec JSON (see docstring)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the trial list and exit (plan mode)")
    p.add_argument("--config",
                   help="experiment YAML supplying the autotune.* knobs")
    p.add_argument("--set", action="append", default=[], dest="overrides",
                   metavar="K=V", help="config override (load_config)")
    p.add_argument("--journal", help="journal path (default: "
                   "autotune.journal_path or <out-dir>/autotune_journal"
                   ".jsonl)")
    p.add_argument("--out-dir", help="leaderboard/best-yaml dir "
                   "(default: autotune.out_dir)")
    p.add_argument("--fake-runner", metavar="SPEC.json",
                   help="deterministic canned runner (the CPU test tier)")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-trial subprocess timeout")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if bool(args.plan) == bool(args.space):
        print("autotune: exactly one of --plan / --space is required",
              file=sys.stderr)
        return 1

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.telemetry import (
        TelemetryWriter,
    )
    from tools import autotune as tune_lib

    try:
        # No --config still goes through load_config so bare --set
        # overrides apply (and get validated) against the defaults.
        cfg = load_config(args.config, overrides=args.overrides)
    except (OSError, ValueError) as e:
        print(f"autotune: bad config: {e}", file=sys.stderr)
        return 1
    tune = cfg.autotune
    out_dir = args.out_dir or tune.out_dir
    journal_path = (args.journal or tune.journal_path
                    or os.path.join(out_dir, "autotune_journal.jsonl"))

    # Plan mode --dry-run needs no runner/journal — print and leave.
    if args.plan:
        trials = tune_lib.compile_chip_window_plan()
        if args.dry_run:
            print(tune_lib.format_plan(trials))
            return 0
    else:
        try:
            with open(args.space) as fh:
                spec = json.load(fh)
            space = tune_lib.SearchSpace.from_spec(spec)
        except (OSError, ValueError) as e:
            print(f"autotune: bad --space: {e}", file=sys.stderr)
            return 1
        profile = tune_lib.TrafficProfile(
            **{k: v for k, v in (spec.get("incumbent") or {}).items()})

    if args.fake_runner:
        runner = tune_lib.FakeRunner.from_file(args.fake_runner)
    else:
        runner = tune_lib.SubprocessRunner(
            str(_ROOT), bench_wait_min=tune.bench_wait_min,
            timeout_s=args.timeout_s)

    journal = tune_lib.TrialJournal(journal_path)
    events_path = os.path.join(
        os.path.dirname(os.path.abspath(journal_path)),
        "autotune_events.jsonl")
    writer = TelemetryWriter(events_path)
    try:
        if args.plan:
            result = tune_lib.run_plan(trials, runner, journal,
                                       writer=writer)
        else:
            result = tune_lib.run_space_search(
                space, profile, runner, journal,
                prune_margin=tune.prune_margin,
                max_trials=tune.max_trials, writer=writer)
            # Pin only a COMPLETED window's winner — an aborted window
            # resumes from the journal and pins when it finishes.
            if result.get("best") and not result.get("aborted"):
                tune_lib.pin_winner(
                    result,
                    leaderboard_path=os.path.join(out_dir,
                                                  "leaderboard.json"),
                    best_yaml_path=os.path.join(
                        out_dir, f"best_{space.workload}.yaml"),
                    regression_margin=tune.regression_margin,
                    provenance={"run_id": writer.run_id,
                                "journal": journal_path,
                                "spec": args.space})
    finally:
        writer.close()
    print(json.dumps(dict(result)))
    if result.get("aborted"):
        return 3
    if result.get("preflight_failed"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
