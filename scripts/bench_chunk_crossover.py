"""Ring-chunk implementation crossover microbench (real TPU).

Times fwd+bwd of one ring chunk — Pallas flash kernel vs the plain-XLA
chain — across chunk lengths, to (re)calibrate FLASH_CHUNK_MIN in
parallel/ring.py. Round 3 measured the crossover at 2048 with
f32-upcast kernel dots; the round-4 input-dtype kernels run ~2x faster,
so the constant must be re-derived, not trusted (PERF_NOTES.md).

Usage (serial with nothing else on the host — see the verify skill):

    python scripts/bench_chunk_crossover.py [chunk ...]

Prints one line per (chunk, impl): median fwd+bwd wall ms over ``reps``
timed calls after a warmup, synced by fetching a scalar VALUE (never
block_until_ready — the axon tunnel returns early from it).
"""

import os
import pathlib
import statistics
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

if os.environ.get("BCC_CPU", "0") not in ("", "0"):
    # CPU plumbing dry-run (timings meaningless): the sitecustomize
    # force-selects axon, so an in-process override is the only way to
    # validate the script without a chip (cf. verify_fused_bwd.py).
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.ops import flash_attention as _fa
from distributed_tensorflow_framework_tpu.parallel import ring

B, H, D = 4, 12, 64
REPS = 12


def time_impl(c: int, use_flash: bool) -> float:
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, c, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, c, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, c, H, D), jnp.bfloat16)
    bias = jnp.zeros((B, c), jnp.float32)

    def chunk(q, k, v, bias):
        # Time the PRODUCTION dispatch arms, not a copy: force
        # ring._chunk_attention down one arm by pinning its module-level
        # crossover (the documented force-path hook, cf.
        # tests/test_packed_attention.py). Trace-time mutation is safe —
        # each jit below traces exactly once, under its own pin.
        saved = ring.FLASH_CHUNK_MIN
        ring.FLASH_CHUNK_MIN = 0 if use_flash else 10**9
        try:
            out = ring._chunk_attention(q, k, v, bias)
        finally:
            ring.FLASH_CHUNK_MIN = saved
        return out

    @jax.jit
    def fwd_bwd(q, k, v, bias):
        def loss(q, k, v, bias):
            o, lse = chunk(q, k, v, bias)
            return (jnp.sum(o.astype(jnp.float32) ** 2)
                    + jnp.sum(lse.astype(jnp.float32)))

        val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v, bias)
        return val + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

    float(fwd_bwd(q, k, v, bias))  # compile + warmup, synced by value fetch
    samples = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(fwd_bwd(q, k, v, bias))
        samples.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(samples)


def main() -> None:
    chunks = [int(a) for a in sys.argv[1:]] or [256, 512, 1024, 2048, 4096]
    print(f"chunk fwd+bwd median ms (B={B} H={H} D={D}, reps={REPS}), "
          f"dispatch FLASH_CHUNK_MIN={ring.FLASH_CHUNK_MIN}")
    for c in chunks:
        # Above MAX_SEQ_VMEM the dispatch routes to the flash kernels even
        # with FLASH_CHUNK_MIN pinned high (ring._chunk_attention's
        # `c > MAX_SEQ_VMEM` clause), so an "xla" timing there would
        # silently be a flash timing — and honestly forcing the XLA chain
        # would materialize a c x c f32 score block (12.9 GB at 8192).
        # Refuse instead (ADVICE r4).
        if c > _fa.MAX_SEQ_VMEM:
            if not _fa.chunk_supported(c):
                print(f"chunk {c:5d}: skipped — exceeds "
                      f"MAX_SEQ_VMEM={_fa.MAX_SEQ_VMEM} but is not a "
                      f"BLOCK_Q multiple, so neither arm can take it")
                continue
            flash_ms = time_impl(c, use_flash=True)
            print(f"chunk {c:5d}: xla      n/a ms   flash {flash_ms:8.2f} ms"
                  f"   -> flash (xla arm refused: chunk > "
                  f"MAX_SEQ_VMEM={_fa.MAX_SEQ_VMEM} would materialize a "
                  f"{c}x{c} score block)")
            continue
        xla_ms = time_impl(c, use_flash=False)
        flash_ms = time_impl(c, use_flash=True)
        winner = "flash" if flash_ms < xla_ms else "xla"
        print(f"chunk {c:5d}: xla {xla_ms:8.2f} ms   flash {flash_ms:8.2f} ms"
              f"   -> {winner}")


if __name__ == "__main__":
    main()
