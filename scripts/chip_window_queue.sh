#!/bin/bash
# Round-5 chip-window measurement queue (PERF_NOTES.md round-4 closeout).
# Run DETACHED the moment a tunnel probe succeeds:
#
#   setsid nohup bash scripts/chip_window_queue.sh > /tmp/chipq.log 2>&1 &
#
# Rules baked in (verify skill): serial runs, nothing else on the host,
# never killed mid-run; each run's JSON line + stderr tail go to the log.
# Priority order = VERDICT r4 "Next round" items 1-2, 5.
set -u
cd "$(dirname "$0")/.."
echo "=== chip queue start $(date -u +%FT%TZ) ==="

run() {
  local label="$1"; shift
  echo "--- [$label] $* $(date -u +%H:%M:%S)"
  "$@" 2>/tmp/chipq_err.log
  local rc=$?
  echo "--- [$label] rc=$rc $(date -u +%H:%M:%S)"
  [ $rc -ne 0 ] && tail -5 /tmp/chipq_err.log
  return $rc
}

# 0. Preflight: graftcheck static analysis (docs/STATIC_ANALYSIS.md). A
#    finding here means the tree has an untallied collective / broken
#    telemetry contract — measuring it would waste the chip window on
#    numbers the ledger can't explain. Runs on CPU, never touches the chip.
run graftcheck env JAX_PLATFORMS=cpu python scripts/graftcheck.py || exit 1

# 0b. Chip preflight: ONE bounded backend probe before any workload
#     burns its BENCH_WAIT budget (rounds r03–r05: a dead tunnel cost
#     BENCH_WAIT *per dial* before anything failed). Exit 3 here is the
#     probe-hang class — chip access is down, abort the whole queue and
#     re-land it later; nothing to revert.
run probe env BENCH_PROBE_ONLY=1 python bench.py
rc=$?
if [ $rc -eq 3 ]; then
  echo "chipq: preflight probe HANG — chip access down, aborting queue (exit 3)"
  exit 3
elif [ $rc -ne 0 ]; then
  echo "chipq: preflight probe failed rc=$rc — aborting queue"
  exit $rc
fi

# 1. The headline number: driver-format ResNet-50 bench (expect ~2512).
run resnet python bench.py || exit 1   # if the probe fails, stop — tunnel is down

# 2. Dense-BERT MFU lever: fused-qkv A/B at the production shape.
run bert-base    env BENCH_WORKLOAD=bert python bench.py
run bert-fqkv    env BENCH_WORKLOAD=bert BENCH_FUSED_QKV=1 python bench.py

# 3. Post-dtype tile confirms at seq 8192 (streaming regime).
#    FLASH_FUSED_BWD=0 pins the TWO-PASS backward: since the round-5
#    default flip (ops/flash_attention.py) an env-less run takes the
#    fused backward, which would turn 4b below into fused-vs-fused.
run tile-512-1024  env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=8192 BENCH_BS=4 FLASH_BLOCK_Q_KB=512 FLASH_BLOCK_K_KB=1024 FLASH_FUSED_BWD=0 python bench.py
run tile-1024-1024 env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=8192 BENCH_BS=4 FLASH_BLOCK_Q_KB=1024 FLASH_BLOCK_K_KB=1024 FLASH_FUSED_BWD=0 python bench.py

# 4. FLASH_CHUNK_MIN re-derive against the 2x-faster round-4 kernels.
run crossover python scripts/bench_chunk_crossover.py 256 512 1024 2048 4096

# 4b. Fused one-pass streaming backward: ON-DEVICE NUMERICS FIRST (the
#     revisited-output flush ordering is unverifiable in interpret mode),
#     then the A/B (PERF_NOTES predicts ~-30% VPU work at seq 8192;
#     compare vs tile-512-1024 above). Skip the bench if numerics fail.
run fused-bwd-verify python scripts/verify_fused_bwd.py 8192 && \
run fused-bwd env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=8192 BENCH_BS=4 FLASH_FUSED_BWD=1 python bench.py

# 4c. Grad-accum fragmentation lever A/B at the production shape
#     (effective batch 4x at fixed per-micro memory; compare bert-base).
run bert-accum4 env BENCH_WORKLOAD=bert BENCH_ACCUM=4 python bench.py

# 5. Roofline close-out trace for the 2512-vs-2670 question.
run trace env BENCH_TRACE=/tmp/bench_trace python bench.py

# 6. Third-workload coverage: Inception-v3 at its recipe shapes
#    (299px, RMSProp, aux head). Expect ~1959 img/s, HBM-bound.
run inception env BENCH_WORKLOAD=inception python bench.py

# 7. Whole-K takeover band (round 5): verify numerics on-device FIRST
#    (per seq — gates only its own pair), then A/B fused-takeover vs
#    whole-K two-pass. Pairs are independent so a transient failure in
#    one cannot cancel the rest of an unattended window; each A/B is a
#    same-epoch adjacent pair (PERF_NOTES variance rules).
#    NOTE: since the precision-ladder arming the takeover default is now
#    DTYPE-AWARE (ops/flash_attention.py fused_whole_k_min: bf16 inputs
#    take the fused backward from 2048 up with NO env set; f32 stays
#    parked above MAX_SEQ_VMEM). The bert bench runs bf16, so the
#    "fused" arms below are env-less and the two-pass arms pin the old
#    behavior with the explicit huge threshold; keep-or-revert
#    FUSED_WHOLE_K_MIN_BF16 on this pair's delta.
if run wk-verify-2048 python scripts/verify_fused_bwd.py 2048; then
  run wk2048-fused env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=2048 BENCH_BS=16 python bench.py
  run wk2048-two   env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=2048 BENCH_BS=16 FLASH_FUSED_WHOLE_K_MIN=1000000000 python bench.py
fi
if run wk-verify-4096 python scripts/verify_fused_bwd.py 4096; then
  run wk4096-fused env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=4096 BENCH_BS=8 python bench.py
  run wk4096-two   env BENCH_WORKLOAD=bert BENCH_ATTN=pallas BENCH_SEQ=4096 BENCH_BS=8 FLASH_FUSED_WHOLE_K_MIN=1000000000 python bench.py
fi

# 8. Pipeline-schedule A/B on a dp+pp mesh (docs/DISTRIBUTED.md): same
#    mesh and microbatch budget — gpipe (bubble 3/11 at S=4,M=8) vs 1F1B
#    (same analytic bubble, O(S) activation residency) vs interleaved
#    (v=12/4=3 → bubble 3/27). Re-probe the tunnel with the stock bench
#    first so a backend that died mid-window fails cheap, not mid-A/B.
run pp-sanity python bench.py
run pp-gpipe       env BENCH_WORKLOAD=bert BENCH_PP=4 BENCH_MICRO=8 BENCH_SCHEDULE=gpipe python bench.py
run pp-1f1b        env BENCH_WORKLOAD=bert BENCH_PP=4 BENCH_MICRO=8 BENCH_SCHEDULE=1f1b python bench.py
run pp-interleaved env BENCH_WORKLOAD=bert BENCH_PP=4 BENCH_MICRO=8 BENCH_SCHEDULE=interleaved python bench.py

# 9. Quantized-collective wire-format A/B (docs/PERFORMANCE.md): each
#    dial runs its OWN f32-wire shard_map baseline on the same ladder,
#    so the JSON line is self-contained (wire-byte ratio + throughput
#    delta) — CPU-verified ratio is ~3.6x for int8, the chip question is
#    whether DCN/ICI time drops enough to show up in img/s at this
#    scale. bench.py exits 3 (not 1) when the backend PROBE hangs:
#    that is chip access flakiness, not a code regression — re-land the
#    dial in the next window instead of reverting (BENCH_r04/r05 both
#    died to a wedged tunnel, not to the code under test).
run coll-f32  env BENCH_COLLECTIVE=f32 python bench.py
run coll-bf16 env BENCH_COLLECTIVE=bf16 python bench.py
run coll-int8 env BENCH_COLLECTIVE=int8 python bench.py

# 10. Serving latency/throughput A/B (docs/SERVING.md): dynamic batching
#     ON (max_batch_size=8) vs OFF (=1) against the same exported
#     artifact — the win is the p99-vs-req/s spread between the two
#     SERVE_BENCH json files (closed 32-way + open-loop 200 req/s each).
#     Self-contained: short synthetic lenet train → export (the 1-device
#     serving mesh makes serve.allow_reshard mandatory) → standing
#     server per arm, drained via SIGTERM (exit 0 = clean drain).
serve_ab() {
  local label="$1" batch="$2"
  rm -rf /tmp/chipq_serve/artifact/serve_logs
  python -m distributed_tensorflow_framework_tpu.cli.serve \
      --artifact /tmp/chipq_serve/artifact \
      --set serve.port=0 --set serve.max_batch_size="$batch" \
      --set serve.max_wait_ms=5 > /tmp/chipq_serve_"$label".log 2>&1 &
  local pid=$!
  for _ in $(seq 120); do
    [ -f /tmp/chipq_serve/artifact/serve_logs/endpoint.json ] && break
    sleep 1
  done
  run serve-"$label" python scripts/load_gen.py \
      --endpoint /tmp/chipq_serve/artifact/serve_logs/endpoint.json \
      --requests 512 --concurrency 32 --rate 200 --mode both \
      --out SERVE_BENCH_"$label".json
  kill -TERM "$pid" 2>/dev/null
  wait "$pid"
  echo "--- [serve-$label] drain rc=$? (0 = clean SIGTERM drain)"
  run serve-"$label"-slo python scripts/analyze_trace.py \
      /tmp/chipq_serve/artifact/serve_logs/events.jsonl
}
rm -rf /tmp/chipq_serve
run serve-train python train.py --config configs/lenet_mnist.yaml \
    --set data.name=synthetic_images --set train.total_steps=30 \
    --set checkpoint.directory=/tmp/chipq_serve/ckpt \
    --set checkpoint.save_interval_steps=30 --set checkpoint.async_save=false
run serve-export python -m distributed_tensorflow_framework_tpu.cli.export \
    --config configs/lenet_mnist.yaml \
    --set data.name=synthetic_images \
    --set checkpoint.directory=/tmp/chipq_serve/ckpt \
    --set serve.allow_reshard=true --output /tmp/chipq_serve/artifact
serve_ab batched 8
serve_ab unbatched 1

# 11. ZeRO weight-update sharding A/B (docs/PERFORMANCE.md): each dial
#     runs its OWN replicated-optimizer shard_map baseline on the same
#     ladder, so the JSON line is self-contained (per-chip opt-state
#     byte ratio read off the placed shardings + throughput delta).
#     CPU-verified: f32 update parity vs the monolithic all-reduce is
#     ~1e-8 and slots land at 1/(data*fsdp) per device — the chip
#     question is how much step time the bucketed reduce-scatter /
#     all-gather pair costs once XLA overlaps the reverse-order buckets
#     with the backward (plan_summary estimates (B-1)/B of RS hidden).
#     Same exit-3 probe-hang rule as §9: re-land, don't revert.
run zero-off       env BENCH_ZERO=off python bench.py
run zero-shard_map env BENCH_ZERO=shard_map python bench.py

# 12. HBM memory close-out (ROADMAP item 5, docs/OBSERVABILITY.md): one
#     stock-bench run with its telemetry pinned to a known sink, then
#     the machine-readable run summary. The JSON line's
#     hbm_peak_bytes_per_chip / hbm_headroom_frac say how much batch
#     headroom the 0.94-bw-util step has left on THIS chip (first
#     on-chip read of device memory_stats — CPU rehearsals only ever
#     saw the memory_analysis estimate), and the events file carries
#     the raw KIND_MEMORY samples for the before/after of any round-6
#     remat/donation dial.
run mem-headline env BENCH_JSONL=/tmp/chipq_mem_events.jsonl python bench.py
run mem-summary  python scripts/analyze_trace.py /tmp/chipq_mem_events.jsonl --json -

# 13. Precision ladder (ISSUE 13, docs/PERFORMANCE.md "Flipping the
#     bound"): four rungs on the same shard_map+ZeRO substrate, each
#     dial running its OWN all-f32-compute baseline on the same batch
#     ladder so every JSON line is self-contained (per-chip peak-HBM
#     ratio + ai_flops_per_byte + throughput delta). CPU-verified:
#     fused-update params are BITWISE equal to the unfused ZeRO walk
#     over 3 steps, bf16 masters stay f32, int8 matmul error is inside
#     the 2*maxabs/254 block-codec bound — the chip question is how
#     much of the rungs' byte cut the roofline returns as img/s, and
#     whether ai_flops_per_byte crosses the v5e ridge (~240) anywhere
#     on the ladder. NOTE the budgets CPU caveat (tools/graftcheck/
#     hlo_passes.py BUDGET_PROGRAMS): CPU float normalization stages
#     bf16 math through f32 copies, so these rungs' memory win is only
#     measurable HERE, on a chip with native bf16 kernels. Same exit-3
#     probe-hang rule as §9: re-land, don't revert.
run prec-f32        env BENCH_PRECISION=f32 python bench.py
run prec-bf16       env BENCH_PRECISION=bf16 python bench.py
run prec-bf16-fused env BENCH_PRECISION=bf16_fused python bench.py
run prec-bf16-int8  env BENCH_PRECISION=bf16_int8 python bench.py

# 14. Fleet-vs-single serving A/B (ISSUE 14, docs/SERVING.md): the same
#     closed+open load against one engine (§10's artifact, batched arm)
#     vs a 3-replica fleet behind the health-aware router. The win is
#     the p99-vs-req/s spread between SERVE_BENCH_batched.json and
#     SERVE_BENCH_fleet.json (the /2 schema's fleet section carries
#     per-replica routing counts + router retry/shed deltas, so skew is
#     readable straight off the JSON line). Reuses §10's artifact; a
#     failed §10 export already aborted the queue. Drained via SIGTERM
#     like every serving arm (exit 0 = clean fleet drain).
python -m distributed_tensorflow_framework_tpu.cli.fleet \
    --artifact /tmp/chipq_serve/artifact --replicas 3 \
    --set serve.log_dir=/tmp/chipq_fleet \
    --set serve.max_batch_size=8 --set serve.max_wait_ms=5 \
    > /tmp/chipq_fleet.log 2>&1 &
fleet_pid=$!
for _ in $(seq 240); do
  [ -f /tmp/chipq_fleet/endpoint.json ] && break
  sleep 1
done
run serve-fleet python scripts/load_gen.py \
    --endpoint /tmp/chipq_fleet/endpoint.json \
    --requests 512 --concurrency 32 --rate 200 --mode both \
    --out SERVE_BENCH_fleet.json
kill -TERM "$fleet_pid" 2>/dev/null
wait "$fleet_pid"
echo "--- [serve-fleet] drain rc=$? (0 = clean fleet drain)"
run serve-fleet-slo python scripts/analyze_trace.py \
    /tmp/chipq_fleet/events.jsonl

# 15. Two-host-sim gang A/B (ISSUE 15, docs/RESILIENCE.md "Gang
#     supervision"): the same LeNet workload, same GLOBAL batch, as one
#     process with 4 devices vs a 2-process jax.distributed gang with
#     2 devices each through scripts/train_cluster.py — the DCN-path
#     overhead (coordinator handshake, cross-process collectives, exit
#     barrier) read off the two chiefs' step-time/goodput telemetry via
#     the multi-dir analyze_trace join. Gated behind its own §0b-style
#     preflight: cluster.probe_gang() is ONE cheap subprocess round-trip
#     that detects backends whose compiler rejects multi-process
#     programs (stock CPU jaxlib) — skip the section, don't burn the
#     window on a gang that can never compile.
if run gang-probe python -c "
import sys
from distributed_tensorflow_framework_tpu.core import cluster
ok, detail = cluster.probe_gang(procs=2, devices_per_proc=2)
if not ok:
    print(detail[-800:], file=sys.stderr)
sys.exit(0 if ok else 1)
"; then
  rm -rf /tmp/chipq_gang
  run gang-1p python scripts/train_cluster.py \
      --procs 1 --devices-per-proc 4 --workdir /tmp/chipq_gang/w1 \
      --max-attempts 1 -- \
      --config configs/lenet_mnist.yaml \
      --set train.total_steps=200 --set train.log_interval=50 \
      --set train.eval_steps=0 --set train.eval_interval=0 \
      --set data.global_batch_size=32 --set mesh.data=-1 \
      --set checkpoint.directory=/tmp/chipq_gang/ck1
  run gang-2p python scripts/train_cluster.py \
      --procs 2 --devices-per-proc 2 --workdir /tmp/chipq_gang/w2 \
      --max-attempts 1 -- \
      --config configs/lenet_mnist.yaml \
      --set train.total_steps=200 --set train.log_interval=50 \
      --set train.eval_steps=0 --set train.eval_interval=0 \
      --set data.global_batch_size=32 --set mesh.data=-1 \
      --set checkpoint.directory=/tmp/chipq_gang/ck2
  run gang-ab python scripts/analyze_trace.py /tmp/chipq_gang/ck1
  run gang-ab-2p python scripts/analyze_trace.py /tmp/chipq_gang/ck2
else
  echo "--- [gang-probe] backend cannot run multi-process gangs — skipping §15"
fi

# 16. Autoregressive decode A/Bs (ISSUE 18, docs/SERVING.md
#     "Autoregressive decode"): one down-scaled BERT mlm artifact, then
#     two self-contained dials against standing decode servers:
#     (a) continuous batching vs the static batch-synchronous arm on
#         the mixed-length workload (every 8th stream runs the full
#         token budget, the rest an eighth) — the win is the tokens/s +
#         TTFT spread between DECODE_BENCH_{continuous,static}.json
#         (CPU-verified >= 2x; the chip question is what the ratio does
#         when a decode step stops being CPU-dispatch-bound);
#     (b) f32 vs int8 KV pages on the continuous arm — ~4x resident
#         streams per replica for a per-token logit drift inside the
#         block-codec bound; the JSON's ttft/tpot + decode_delta
#         sections carry the capacity-vs-latency story. Drained via
#     SIGTERM like every serving arm (exit 0 = clean drain).
decode_ab() {
  local label="$1"; shift
  python -m distributed_tensorflow_framework_tpu.cli.serve \
      --artifact /tmp/chipq_decode/artifact \
      --set serve.port=0 \
      --set serve.log_dir=/tmp/chipq_decode/logs_"$label" \
      --set decode.enabled=true --set decode.max_len=128 \
      --set decode.page_size=16 --set decode.num_pages=256 \
      --set decode.max_streams=8 --set decode.max_new_tokens=96 \
      --set decode.stream_interval=8 "$@" \
      > /tmp/chipq_decode_"$label".log 2>&1 &
  local pid=$!
  for _ in $(seq 120); do
    [ -f /tmp/chipq_decode/logs_"$label"/endpoint.json ] && break
    sleep 1
  done
  run decode-"$label" python scripts/load_gen.py \
      --endpoint /tmp/chipq_decode/logs_"$label"/endpoint.json \
      --mode decode --requests 64 --concurrency 8 \
      --max-new-tokens 96 --out DECODE_BENCH_"$label".json
  kill -TERM "$pid" 2>/dev/null
  wait "$pid"
  echo "--- [decode-$label] drain rc=$? (0 = clean SIGTERM drain)"
}
rm -rf /tmp/chipq_decode
run decode-train python train.py --config configs/bert_base_mlm.yaml \
    --set data.name=synthetic_mlm --set train.total_steps=30 \
    --set model.hidden_size=256 --set model.num_layers=4 \
    --set model.num_heads=4 --set model.mlp_dim=1024 \
    --set model.max_seq_len=128 --set data.seq_len=128 \
    --set data.global_batch_size=32 --set train.eval_steps=0 \
    --set train.eval_interval=0 \
    --set checkpoint.directory=/tmp/chipq_decode/ckpt \
    --set checkpoint.save_interval_steps=30 \
    --set checkpoint.async_save=false
run decode-export python -m distributed_tensorflow_framework_tpu.cli.export \
    --config configs/bert_base_mlm.yaml \
    --set data.name=synthetic_mlm \
    --set model.hidden_size=256 --set model.num_layers=4 \
    --set model.num_heads=4 --set model.mlp_dim=1024 \
    --set model.max_seq_len=128 --set data.seq_len=128 \
    --set checkpoint.directory=/tmp/chipq_decode/ckpt \
    --set serve.allow_reshard=true --output /tmp/chipq_decode/artifact
decode_ab continuous --set decode.scheduler=continuous
decode_ab static     --set decode.scheduler=static
decode_ab int8       --set decode.scheduler=continuous \
                     --set decode.kv_dtype=int8

# 17. Infeed A/B (ISSUE 19, docs/RESILIENCE.md "Exactly-once data"):
#     the sharded/packed input path's two dials on the BERT mlm
#     workload, behind the same §0b preflight (a wedged tunnel already
#     aborted the queue above; nothing here re-probes).
#     (a) sequence packing OFF vs ON (data.pack_factor 1 vs 4): the win
#         is goodput per PADDED token — the packing rollup
#         (KIND_DATA_PACKING: real/padded tokens, efficiency) in each
#         run's summary says how much of the step budget stopped being
#         spent on pad rows;
#     (b) shard_mode block vs stride at the same shapes: the refit-safe
#         block layout must price at parity — its per-batch host work is
#         the same permutation slice, just a different window — so any
#         step-time delta here is a regression, not a trade.
#     Telemetry (data_shard / data_packing / goodput rollups) read back
#     through analyze_trace per arm.
infeed_ab() {
  local label="$1"; shift
  rm -rf /tmp/chipq_infeed/"$label"
  run infeed-"$label" python train.py --config configs/bert_base_mlm.yaml \
      --set data.name=synthetic_mlm --set train.total_steps=100 \
      --set train.log_interval=25 --set train.eval_steps=0 \
      --set train.eval_interval=0 \
      --set model.hidden_size=256 --set model.num_layers=4 \
      --set model.num_heads=4 --set model.mlp_dim=1024 \
      --set model.max_seq_len=512 --set data.seq_len=512 \
      --set data.global_batch_size=32 \
      --set checkpoint.directory=/tmp/chipq_infeed/"$label" "$@"
  run infeed-"$label"-summary python scripts/analyze_trace.py \
      /tmp/chipq_infeed/"$label"
}
infeed_ab unpacked --set data.pack_factor=1
infeed_ab packed   --set data.pack_factor=4
infeed_ab block    --set data.pack_factor=4 --set data.shard_mode=block
infeed_ab stride   --set data.pack_factor=4 --set data.shard_mode=stride

echo "=== chip queue done $(date -u +%FT%TZ) ==="
