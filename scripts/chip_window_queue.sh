#!/bin/bash
# Round-5 chip-window measurement queue — now a thin wrapper over the
# autotuner's compiled plan (scripts/autotune.py --plan chip_window,
# tools/autotune/plan.py). The queue recipes themselves live in the plan
# compiler; this script only preserves the operator entry point:
#
#   setsid nohup bash scripts/chip_window_queue.sh > /tmp/chipq.log 2>&1 &
#
# Contract carried over from the shell queue (verify skill): serial runs,
# nothing else on the host, never killed mid-run. §0 (graftcheck) and
# §0b (chip probe) still run FIRST and still refuse the window — exec
# passes the autotuner's exit codes straight through: 0 done, 1 a
# preflight failed (window refused), 3 probe hang (chip access down,
# window aborted; the dtf-autotune-journal/1 journal keeps every settled
# trial, so re-landing this same command resumes where it stopped
# instead of re-spending the budget).
#
# The plan-manifest lines below are the machine-readable section→label
# map; tests/test_autotune.py asserts every label appears in
# `autotune.py --plan chip_window --dry-run`, so the wrapper and the
# compiler cannot drift apart silently.
#
# plan-manifest §0: graftcheck
# plan-manifest §0b: probe
# plan-manifest §1: resnet
# plan-manifest §13: prec-f32 prec-bf16 prec-bf16-fused prec-bf16-int8
# plan-manifest §7: wk-verify-2048 wk2048-fused wk2048-two wk-verify-4096 wk4096-fused wk4096-two
# plan-manifest §8: pp-sanity pp-gpipe pp-1f1b pp-interleaved
# plan-manifest §9: coll-f32 coll-bf16 coll-int8
# plan-manifest §10: serve-clean serve-train serve-export serve-batched serve-unbatched
# plan-manifest §11: zero-off zero-shard_map
# plan-manifest §12: mem-headline mem-summary
# plan-manifest §14: serve-fleet
# plan-manifest §15: gang-probe gang-clean gang-1p gang-2p gang-ab gang-ab-2p
# plan-manifest §16: decode-clean decode-train decode-export decode-continuous decode-static decode-int8
# plan-manifest §17: infeed-unpacked infeed-packed infeed-block infeed-stride
# plan-manifest §2: bert-base bert-fqkv
# plan-manifest §3: tile-512-1024 tile-1024-1024
# plan-manifest §4: crossover
# plan-manifest §4b: fused-bwd-verify fused-bwd
# plan-manifest §4c: bert-accum4
# plan-manifest §5: trace
# plan-manifest §6: inception
set -u
cd "$(dirname "$0")/.."
echo "=== chip queue start $(date -u +%FT%TZ) (autotune plan mode) ==="
exec python scripts/autotune.py --plan chip_window "$@"
