#!/usr/bin/env python
"""Entry point for the graftcheck static-analysis suite.

Pins the CPU runtime env BEFORE jax can initialize, so the jaxpr-layer
passes get the same 8-device CPU mesh the test suite uses (see
tests/conftest.py for the rationale), then hands off to
tools/graftcheck/cli.py. Usage: ``python scripts/graftcheck.py [--help]``.
"""

import os
import pathlib
import sys

_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()

from distributed_tensorflow_framework_tpu.core.platform import (  # noqa: E402
    with_cpu_collective_timeouts,
)

os.environ["XLA_FLAGS"] = with_cpu_collective_timeouts(_flags)

from tools.graftcheck import cli  # noqa: E402


def main() -> int:
    argv = sys.argv[1:]
    # Default the repo root to this checkout, not the caller's cwd.
    if not any(a == "--root" or a.startswith("--root=") for a in argv):
        argv = ["--root", str(_ROOT)] + argv
    return cli.main(argv)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `graftcheck.py --list-passes | head` closes stdout early; that
        # is not a failure. Re-point stdout at devnull so the interpreter
        # shutdown flush doesn't raise again.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
