#!/bin/bash
# Opt-in git hooks for this repo. Run once:
#
#   bash scripts/install_hooks.sh
#
# Installs a pre-commit hook that runs the fast graftcheck path —
# `scripts/graftcheck.py --changed` — AST passes over the files in your
# diff only (milliseconds; the jaxpr/hlo trace passes are skipped with a
# notice; see docs/STATIC_ANALYSIS.md). Bypass a single commit with
# `git commit --no-verify`; uninstall by deleting .git/hooks/pre-commit.
set -eu
cd "$(dirname "$0")/.."

HOOK=.git/hooks/pre-commit
if [ -e "$HOOK" ] && ! grep -q graftcheck "$HOOK" 2>/dev/null; then
  echo "install_hooks: $HOOK exists and is not ours — not overwriting" >&2
  exit 1
fi

cat > "$HOOK" <<'EOF'
#!/bin/sh
# Installed by scripts/install_hooks.sh — fast graftcheck over the diff.
exec env JAX_PLATFORMS=cpu python scripts/graftcheck.py --changed
EOF
chmod +x "$HOOK"
echo "install_hooks: wrote $HOOK (graftcheck --changed; --no-verify bypasses)"
