#!/usr/bin/env python
"""Launch an N-process fake cluster on localhost — the reference's
"multiple ports on one machine" development trick (SURVEY.md §4), rebuilt
for the SPMD runtime.

Where the reference had the user hand-write ``--ps_hosts/--worker_hosts``
host maps and start each role by hand, this spawns N identical worker
processes wired together through ``jax.distributed`` env vars
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID — the same
discovery path a real multi-host slice uses), each with
``--devices-per-proc`` virtual CPU devices. Exercises the full DCN-path
code (per-host data sharding, global-array assembly, cross-process
collectives, chief-only checkpointing) with zero hardware.

Usage:
    python scripts/launch_local_cluster.py --procs 2 -- \
        --config configs/lenet_mnist.yaml --set train.total_steps=20

Everything after ``--`` is passed to train.py verbatim. Exit status is
non-zero if any worker fails; worker logs stream to
``<workdir>/worker-<i>.log`` (default /tmp/dtf-local-cluster).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--procs", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=2)
    p.add_argument("--workdir", default="/tmp/dtf-local-cluster")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="arguments for train.py (prefix with --)")
    args = p.parse_args(argv)
    train_args = args.train_args
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]
    if not train_args:
        p.error("pass train.py arguments after --")

    os.makedirs(args.workdir, exist_ok=True)
    port = free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs, logs = [], []
    for i in range(args.procs):
        env = dict(os.environ)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(args.procs)
        env["JAX_PROCESS_ID"] = str(i)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
        ).strip()
        log = open(os.path.join(args.workdir, f"worker-{i}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(repo, "train.py"), *train_args],
            env=env, cwd=repo, stdout=log, stderr=subprocess.STDOUT))
    print(f"launched {args.procs} workers (coordinator 127.0.0.1:{port}); "
          f"logs in {args.workdir}/worker-*.log", file=sys.stderr)

    # Poll ALL workers: a crashed peer leaves the others blocked in a
    # collective forever, so on the first nonzero exit the rest are
    # terminated — the launcher must surface the failure, not hang on
    # procs[0].wait().
    rc = 0
    grace = 10.0  # seconds between SIGTERM and SIGKILL escalation
    try:
        live = dict(enumerate(procs))
        killed: dict[int, float] = {}  # worker → time SIGTERM was sent
        while live:
            now = time.monotonic()
            for i, proc in list(live.items()):
                r = proc.poll()
                if r is None:
                    # A worker blocked inside a native collective can
                    # ignore SIGTERM indefinitely — escalate to SIGKILL
                    # after the grace period so the launcher never hangs.
                    if i in killed and now - killed[i] > grace:
                        proc.kill()
                        killed[i] = float("inf")  # kill once
                    continue
                del live[i]
                if r != 0 and i not in killed:
                    # Peers terminated below exit nonzero too — only the
                    # first real failure is the root cause worth naming.
                    print(f"worker {i} exited {r} — see "
                          f"{args.workdir}/worker-{i}.log", file=sys.stderr)
                    rc = rc or r
                    for j, p in live.items():
                        killed[j] = now
                        p.terminate()
            if live:
                time.sleep(0.2)
    except KeyboardInterrupt:
        rc = 130
        for proc in procs:
            proc.terminate()
        try:
            deadline = time.monotonic() + grace
            for proc in procs:
                try:
                    proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
        except KeyboardInterrupt:
            # Second Ctrl-C: stop waiting politely, SIGKILL everything;
            # the finally block reaps.
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
    finally:
        # Reap everything — no orphaned children past this point.
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    proc.kill()
            proc.wait()
        for log in logs:
            log.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
