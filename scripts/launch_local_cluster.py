#!/usr/bin/env python
"""Launch an N-process fake cluster on localhost — the reference's
"multiple ports on one machine" development trick (SURVEY.md §4), rebuilt
for the SPMD runtime.

Where the reference had the user hand-write ``--ps_hosts/--worker_hosts``
host maps and start each role by hand, this spawns N identical worker
processes wired together through ``jax.distributed`` env vars
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID — the same
discovery path a real multi-host slice uses), each with
``--devices-per-proc`` virtual CPU devices. Exercises the full DCN-path
code (per-host data sharding, global-array assembly, cross-process
collectives, chief-only checkpointing) with zero hardware.

Usage:
    python scripts/launch_local_cluster.py --procs 2 -- \
        --config configs/lenet_mnist.yaml --set train.total_steps=20

Everything after ``--`` is passed to train.py verbatim. Exit status is
non-zero if any worker fails; worker logs stream to
``<workdir>/worker-<i>.log`` (default /tmp/dtf-local-cluster), and the
first failing worker's log tail is echoed to the launcher's stderr so CI
failures carry their own evidence. The free-port probe is inherently
racy (another process can grab the port between probe and coordinator
bind), so a gang whose chief dies at boot with a bind error is relaunched
on a fresh port up to ``--port-retries`` times.

The per-worker environment contract lives in ``core.cluster.worker_env``
(shared with scripts/train_cluster.py, the supervised flavor of this
launcher).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributed_tensorflow_framework_tpu.core import cluster  # noqa: E402

# SIGTERM → SIGKILL escalation budget, and the coordinator-bind failure
# signatures the port-retry path matches against a dead chief's log tail.
GRACE_S = 10.0
PORT_RETRIES = 3
BIND_FAILURE_SIGNS = (
    "address already in use",
    "failed to bind",
    "bind failed",
    "errno 98",
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--procs", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=2)
    p.add_argument("--workdir", default="/tmp/dtf-local-cluster")
    p.add_argument("--port-retries", type=int, default=PORT_RETRIES,
                   help="relaunch attempts when the coordinator loses the "
                        "free-port bind race (1 = no retry)")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="arguments for train.py (prefix with --)")
    args = p.parse_args(argv)
    if args.train_args and args.train_args[0] == "--":
        args.train_args = args.train_args[1:]
    if not args.train_args:
        p.error("pass train.py arguments after --")
    if args.procs < 1:
        p.error("--procs must be >= 1")
    return args


def log_path(workdir: str, worker: int) -> str:
    return os.path.join(workdir, f"worker-{worker}.log")


def log_tail(path: str, max_bytes: int = 4096) -> str:
    """Last ``max_bytes`` of a worker log ('' when unreadable)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - max_bytes))
            return fh.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def is_bind_failure(log_text: str) -> bool:
    """Does a worker's log tail look like the coordinator bind race?"""
    lowered = log_text.lower()
    return any(sign in lowered for sign in BIND_FAILURE_SIGNS)


def spawn_gang(
    train_args: list[str],
    *,
    procs: int,
    devices_per_proc: int,
    workdir: str,
    port: int,
    base_env: dict | None = None,
) -> tuple[list[subprocess.Popen], list]:
    """Spawn the N workers of one gang; returns (processes, log handles).

    ``base_env`` defaults to ``os.environ``; scripts/train_cluster.py
    passes its relaunch env (fast-fail XLA flags, elastic overrides)
    through here so the supervised gang uses the exact same discovery
    path as the bare launcher.
    """
    os.makedirs(workdir, exist_ok=True)
    children, logs = [], []
    for i in range(procs):
        env = cluster.worker_env(
            dict(os.environ if base_env is None else base_env),
            coordinator_port=port,
            num_processes=procs,
            process_id=i,
            devices_per_proc=devices_per_proc,
        )
        log = open(log_path(workdir, i), "w")
        logs.append(log)
        children.append(subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "train.py"), *train_args],
            env=env, cwd=_REPO, stdout=log, stderr=subprocess.STDOUT))
    return children, logs


def _report_failure(workdir: str, worker: int, rc: int) -> None:
    path = log_path(workdir, worker)
    print(f"worker {worker} exited {rc} — log tail ({path}):",
          file=sys.stderr)
    tail = log_tail(path)
    for line in tail.splitlines()[-25:]:
        print(f"    {line}", file=sys.stderr)


def _wait_gang(procs: list[subprocess.Popen],
               workdir: str) -> tuple[int, int | None]:
    """Poll ALL workers until exit; returns (rc, first failing worker).

    A crashed peer leaves the others blocked in a collective forever, so
    on the first nonzero exit the rest are terminated — the launcher must
    surface the failure, not hang on ``procs[0].wait()``. Workers that
    ignore SIGTERM (blocked inside a native collective) are SIGKILLed
    after the grace period.
    """
    rc, failed = 0, None
    live = dict(enumerate(procs))
    killed: dict[int, float] = {}  # worker → time SIGTERM was sent
    while live:
        now = time.monotonic()
        for i, proc in list(live.items()):
            r = proc.poll()
            if r is None:
                if i in killed and now - killed[i] > GRACE_S:
                    proc.kill()
                    killed[i] = float("inf")  # kill once
                continue
            del live[i]
            if r != 0 and i not in killed:
                # Peers terminated below exit nonzero too — only the
                # first real failure is the root cause worth naming.
                rc, failed = (rc or r), (failed if failed is not None else i)
                for j, p in live.items():
                    killed[j] = now
                    p.terminate()
        if live:
            time.sleep(0.2)
    return rc, failed


def _reap(procs: list[subprocess.Popen], logs: list) -> None:
    for proc in procs:
        if proc.poll() is None:
            try:
                proc.wait(timeout=GRACE_S)
            except subprocess.TimeoutExpired:
                proc.kill()
        proc.wait()
    for log in logs:
        log.close()


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    retries = max(1, args.port_retries)
    for attempt in range(1, retries + 1):
        port = free_port()
        procs, logs = spawn_gang(
            args.train_args, procs=args.procs,
            devices_per_proc=args.devices_per_proc,
            workdir=args.workdir, port=port)
        print(f"launched {args.procs} workers (coordinator 127.0.0.1:{port}); "
              f"logs in {args.workdir}/worker-*.log", file=sys.stderr)
        try:
            rc, failed = _wait_gang(procs, args.workdir)
        except KeyboardInterrupt:
            for proc in procs:
                proc.terminate()
            try:
                deadline = time.monotonic() + GRACE_S
                for proc in procs:
                    try:
                        proc.wait(
                            timeout=max(0.1, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        proc.kill()
            except KeyboardInterrupt:
                # Second Ctrl-C: stop waiting politely, SIGKILL everything;
                # _reap below collects the corpses.
                for proc in procs:
                    if proc.poll() is None:
                        proc.kill()
            return 130
        finally:
            _reap(procs, logs)
        if rc == 0 or failed is None:
            return rc
        if (attempt < retries
                and is_bind_failure(log_tail(log_path(args.workdir, failed)))):
            print(f"worker {failed} lost the port-bind race on "
                  f"127.0.0.1:{port} — relaunching the gang on a fresh "
                  f"port (attempt {attempt + 1}/{retries})", file=sys.stderr)
            continue
        _report_failure(args.workdir, failed, rc)
        return rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
