"""Load generator for the serving path (docs/SERVING.md) — stdlib only.

Drives a running ``cli/serve.py`` endpoint two ways and writes a
schema-versioned SERVE_BENCH.json:

  * closed loop — N workers, each holding one outstanding request
    (back-to-back). Measures the server's batching efficiency: the
    concurrency IS the offered batch, so throughput ~ how well the
    admission window coalesces it.
  * open loop — requests dispatched at a fixed rate regardless of
    completions (the SLO-honest mode: a slow server accumulates queue,
    it does not throttle the workload).

Payloads are synthesized from the endpoint's /healthz input spec, with
variable sequence lengths for MLM artifacts so the padding buckets
actually exercise. Client-side p50/p90/p99 come from the same bounded
reservoir the engine uses (core/metrics.PercentileReservoir); the
server-side queue-wait vs compute split is the delta of /healthz engine
counters across the run.

Usage:

    python scripts/load_gen.py --endpoint http://127.0.0.1:8000 \
        [--requests 256] [--concurrency 32] [--rows 1] [--rate 100] \
        [--out SERVE_BENCH.json] [--mode closed|open|both]

``--endpoint`` also accepts a path to the server's endpoint.json.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import pathlib
import random
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import tracing  # noqa: E402
from distributed_tensorflow_framework_tpu.core.metrics import (  # noqa: E402
    PercentileReservoir,
)

# /2 is additive over /1: per-run "by_replica" and a top-level "fleet"
# section (router counter deltas + replica distribution) appear when the
# endpoint is a fleet router; every /1 field is unchanged.  Per-run
# "trace_ids" (one fresh trace id per request, dispatch order) is a
# later additive field: join them against the server-side span events to
# reconstruct any request's causal story (docs/OBSERVABILITY.md).
# Still-additive later fields: per-run "shape" + "by_tenant" (per-tenant
# request attribution: requests/ok/errors/by_status, present when
# --tenants assigns X-DTF-Tenant classes) and the fleet section's
# "tenants" ledger snapshot from the router's healthz.
# --mode decode adds a run entry with mode "decode" (streamed /generate):
# per-stream TTFT + per-token TPOT percentiles, tokens/s, and a
# "decode_delta" of the server's decode healthz counters over the
# window. Every /1 and /2 field is unchanged — still schema-additive.
BENCH_SCHEMA = "dtf-serve-bench/2"

# Open-loop traffic shapes (--shape): per-request due times against the
# base --rate. "uniform" is the PR 14 fixed-rate schedule; the rest
# replay realistic load for the autoscale drill and chip A/Bs:
#   spike   — steady rate, then a middle-third burst at --spike-factor x,
#             then steady again (the scale-up/scale-down round trip).
#   ramp    — rate climbs linearly from 10% to 100% (slow-building rush).
#   diurnal — one sinusoidal day: rate swings between 25% and 100%.
SHAPES = ("uniform", "spike", "ramp", "diurnal")


def shape_offsets(n: int, rate: float, shape: str,
                  spike_factor: float = 4.0) -> list[float]:
    """Dispatch-time offsets (seconds) for n requests at base ``rate``
    under a traffic shape. Offsets are cumulative inter-arrival gaps of
    the instantaneous rate, so the area under the shape is preserved."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if shape not in SHAPES:
        raise ValueError(f"unknown shape {shape!r}; known: {SHAPES}")
    offsets: list[float] = []
    t = 0.0
    for i in range(n):
        frac = i / max(1, n - 1)
        if shape == "spike":
            r = rate * (spike_factor if 1 / 3 <= frac < 2 / 3 else 1.0)
        elif shape == "ramp":
            r = rate * (0.1 + 0.9 * frac)
        elif shape == "diurnal":
            r = rate * (0.625 + 0.375 * math.sin(2 * math.pi * frac))
        else:
            r = rate
        offsets.append(t)
        t += 1.0 / max(1e-6, r)
    return offsets


def parse_tenants(spec: str | None) -> list[tuple[str, float]]:
    """``"high=1,batch=3"`` -> [("high", 1.0), ("batch", 3.0)] — the
    weighted tenant mix each request's X-DTF-Tenant is drawn from."""
    if not spec:
        return []
    mix: list[tuple[str, float]] = []
    for part in spec.split(","):
        name, _, weight = part.strip().partition("=")
        if not name:
            raise ValueError(f"empty tenant name in {spec!r}")
        w = float(weight) if weight else 1.0
        if w <= 0:
            raise ValueError(f"tenant {name!r} needs weight > 0, got {w}")
        mix.append((name, w))
    return mix


def resolve_endpoint(endpoint: str) -> str:
    """A URL, or a path to (a directory holding) endpoint.json."""
    if endpoint.startswith("http://") or endpoint.startswith("https://"):
        return endpoint.rstrip("/")
    path = endpoint
    if os.path.isdir(path):
        path = os.path.join(path, "endpoint.json")
    with open(path) as fh:
        return json.load(fh)["url"].rstrip("/")


def fetch_healthz(url: str) -> dict:
    with urllib.request.urlopen(url + "/healthz", timeout=10) as resp:
        return json.load(resp)


def make_payload(spec: dict, rows: int, *, vocab_size: int,
                 rng: random.Random, seq_buckets: list[int]) -> dict:
    """One request body from the artifact's input spec. MLM rows draw a
    random length <= a random bucket so every bucket sees traffic."""
    inputs: dict = {}
    if "input_ids" in spec:
        max_len = int(spec["input_ids"]["shape"][0])
        cap = rng.choice(seq_buckets) if seq_buckets else max_len
        seq = rng.randint(max(1, cap // 2), min(cap, max_len))
        inputs["input_ids"] = [
            [rng.randrange(1, max(2, vocab_size)) for _ in range(seq)]
            for _ in range(rows)]
        inputs["attention_mask"] = [[1] * seq for _ in range(rows)]
    else:
        shape = spec["image"]["shape"]
        n = 1
        for d in shape:
            n *= int(d)
        flat = [rng.random() for _ in range(n)]

        def nest(vals, dims):
            if len(dims) == 1:
                return vals
            step = len(vals) // dims[0]
            return [nest(vals[i * step:(i + 1) * step], dims[1:])
                    for i in range(dims[0])]

        inputs["image"] = [nest(flat, [int(d) for d in shape])
                           for _ in range(rows)]
    return {"inputs": inputs}


def post_predict(url: str, payload: dict, timeout: float = 60.0,
                 trace: tracing.SpanContext | None = None,
                 tenant: str | None = None) -> tuple:
    """(status, latency_ms, rows_returned, replica). Network errors count
    as status 0 — a closed connection mid-drain must not crash the bench.
    ``replica`` is the fleet router's X-DTF-Replica attribution header
    (None against a single server). ``trace`` rides the X-DTF-Trace
    header so the router/server open spans under this client's trace;
    ``tenant`` rides X-DTF-Tenant for the router's QoS admission."""
    body = json.dumps(payload).encode()
    headers = {"Content-Type": "application/json"}
    if trace is not None:
        headers[tracing.TRACE_HEADER] = trace.encode()
    if tenant is not None:
        headers["X-DTF-Tenant"] = tenant
    req = urllib.request.Request(
        url + "/predict", data=body, headers=headers)
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.load(resp)
            return resp.status, (time.monotonic() - t0) * 1e3, \
                int(out.get("rows", 0)), resp.headers.get("X-DTF-Replica")
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, (time.monotonic() - t0) * 1e3, 0, \
            e.headers.get("X-DTF-Replica")
    except (urllib.error.URLError, OSError, TimeoutError):
        return 0, (time.monotonic() - t0) * 1e3, 0, None


def stream_generate(url: str, prompt: list[int], *, max_new: int,
                    session: str, timeout: float = 300.0) -> dict:
    """One streamed /generate exchange, timed per token frame.

    Returns {"status", "ttft_ms", "tpot_ms" (list), "tokens",
    "latency_ms", "replica", "retried_409"}. TTFT is dispatch → first
    token frame; each TPOT sample is the gap between consecutive token
    frames. A 409 from the fleet router (session pinned to a draining
    replica during a rolling reload) is retried after its Retry-After —
    the contract says the stream succeeds on the reloaded replica, so a
    bounded retry loop is part of the client protocol, not cheating."""
    body = json.dumps({"prompt": prompt,
                       "max_new_tokens": max_new}).encode()
    headers = {"Content-Type": "application/json",
               "X-DTF-Session": session}
    retried_409 = 0
    t0 = time.monotonic()
    for _ in range(20):
        req = urllib.request.Request(url + "/generate", data=body,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                replica = resp.headers.get("X-DTF-Replica")
                ttft = None
                tpot: list[float] = []
                tokens = 0
                t_prev = time.monotonic()
                for line in resp:
                    if not line.strip():
                        continue
                    event = json.loads(line)
                    now = time.monotonic()
                    if "token" in event:
                        if ttft is None:
                            ttft = (now - t0) * 1e3
                        else:
                            tpot.append((now - t_prev) * 1e3)
                        t_prev = now
                        tokens += 1
                    elif "error" in event:
                        return {"status": 0, "ttft_ms": ttft,
                                "tpot_ms": tpot, "tokens": tokens,
                                "latency_ms": (now - t0) * 1e3,
                                "replica": replica,
                                "retried_409": retried_409}
                return {"status": resp.status, "ttft_ms": ttft,
                        "tpot_ms": tpot, "tokens": tokens,
                        "latency_ms": (time.monotonic() - t0) * 1e3,
                        "replica": replica, "retried_409": retried_409}
        except urllib.error.HTTPError as e:
            e.read()
            if e.code == 409:
                retried_409 += 1
                time.sleep(float(e.headers.get("Retry-After") or 0.5))
                continue
            return {"status": e.code, "ttft_ms": None, "tpot_ms": [],
                    "tokens": 0,
                    "latency_ms": (time.monotonic() - t0) * 1e3,
                    "replica": e.headers.get("X-DTF-Replica"),
                    "retried_409": retried_409}
        except (urllib.error.URLError, OSError, TimeoutError,
                ValueError):
            return {"status": 0, "ttft_ms": None, "tpot_ms": [],
                    "tokens": 0,
                    "latency_ms": (time.monotonic() - t0) * 1e3,
                    "replica": None, "retried_409": retried_409}
    return {"status": 409, "ttft_ms": None, "tpot_ms": [], "tokens": 0,
            "latency_ms": (time.monotonic() - t0) * 1e3, "replica": None,
            "retried_409": retried_409}


def _drive_decode(url: str, prompts: list[list[int]], *, concurrency: int,
                  max_new: int, seed: int = 0) -> dict:
    """Closed-loop decode run: ``concurrency`` workers each hold one
    stream open — that concurrency IS the continuous batcher's offered
    occupancy. TTFT and TPOT reservoirs are the streaming SLO story;
    tokens/s is the aggregate the A/B drills compare."""
    ttft_r = PercentileReservoir()
    tpot_r = PercentileReservoir()
    latency = PercentileReservoir()
    lock = threading.Lock()
    counts = {"ok": 0, "errors": 0, "tokens": 0, "by_status": {},
              "by_replica": {}, "retried_409": 0}
    idx = {"next": 0}

    def worker():
        while True:
            with lock:
                i = idx["next"]
                if i >= len(prompts):
                    return
                idx["next"] = i + 1
            # Mixed stream lengths: every 8th stream runs the full token
            # budget, the rest an eighth. This is the churn continuous
            # batching exists for — a static batcher idles 7 finished
            # slots while the long stream runs out; uniform lengths
            # would finish in lockstep and hide the difference.
            mn = max_new if i % 8 == 0 else max(2, max_new // 8)
            out = stream_generate(url, prompts[i], max_new=mn,
                                  session=f"lg-{seed}-{i}")
            with lock:
                key = str(out["status"])
                counts["by_status"][key] = \
                    counts["by_status"].get(key, 0) + 1
                if out["replica"] is not None:
                    counts["by_replica"][out["replica"]] = \
                        counts["by_replica"].get(out["replica"], 0) + 1
                counts["retried_409"] += out["retried_409"]
                counts["tokens"] += out["tokens"]
                latency.add(out["latency_ms"])
                if out["ttft_ms"] is not None:
                    ttft_r.add(out["ttft_ms"])
                for ms in out["tpot_ms"]:
                    tpot_r.add(ms)
                if out["status"] == 200 and out["tokens"] > 0:
                    counts["ok"] += 1
                else:
                    counts["errors"] += 1

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(time.monotonic() - t_start, 1e-9)
    lat, ttft, tpot = latency.summary(), ttft_r.summary(), tpot_r.summary()
    return {
        "mode": "decode",
        "requests": len(prompts),
        "ok": counts["ok"],
        "errors": counts["errors"],
        "by_status": counts["by_status"],
        "rows": counts["tokens"],  # /1 uniformity: rows == tokens here
        "tokens": counts["tokens"],
        "retried_409": counts["retried_409"],
        "elapsed_s": elapsed,
        "requests_per_sec": counts["ok"] / elapsed,
        "rows_per_sec": counts["tokens"] / elapsed,
        "tokens_per_sec": counts["tokens"] / elapsed,
        "latency_ms": {"p50": lat["p50"], "p90": lat["p90"],
                       "p99": lat["p99"], "mean": lat["mean"],
                       "count": lat["count"]},
        "ttft_ms": {"p50": ttft["p50"], "p90": ttft["p90"],
                    "p99": ttft["p99"], "mean": ttft["mean"],
                    "count": ttft["count"]},
        "tpot_ms": {"p50": tpot["p50"], "p90": tpot["p90"],
                    "p99": tpot["p99"], "mean": tpot["mean"],
                    "count": tpot["count"]},
        **({"by_replica": dict(sorted(counts["by_replica"].items()))}
           if counts["by_replica"] else {}),
        "concurrency": concurrency,
    }


def make_prompts(n: int, *, vocab_size: int, max_len: int, max_new: int,
                 rng: random.Random) -> list[list[int]]:
    """Variable-length decode prompts: mostly short with a heavy tail,
    so the continuous batcher's join/leave churn actually exercises
    (uniform lengths would finish in lockstep like a static batch)."""
    cap = max(1, max_len - max_new)
    prompts = []
    for _ in range(n):
        if rng.random() < 0.25:  # heavy tail: near-cap prompts
            length = rng.randint(max(1, cap * 3 // 4), cap)
        else:
            length = rng.randint(1, max(1, cap // 4))
        prompts.append(
            [rng.randrange(1, max(2, vocab_size)) for _ in range(length)])
    return prompts


def _drive(url: str, payloads: list[dict], *, concurrency: int,
           rate: float | None, shape: str = "uniform",
           spike_factor: float = 4.0,
           tenants: list[str] | None = None) -> dict:
    """Run one mode over pre-built payloads; rate=None → closed loop.
    ``tenants`` is the per-request X-DTF-Tenant assignment (parallel to
    ``payloads``); ``shape`` bends the open-loop dispatch schedule."""
    latency = PercentileReservoir()
    lock = threading.Lock()
    counts = {"ok": 0, "errors": 0, "rows": 0, "by_status": {},
              "by_replica": {}, "by_tenant": {}}
    idx = {"next": 0}

    def record(status, ms, rows, replica=None, tenant=None):
        with lock:
            latency.add(ms)
            key = str(status)
            counts["by_status"][key] = counts["by_status"].get(key, 0) + 1
            if replica is not None:
                counts["by_replica"][replica] = \
                    counts["by_replica"].get(replica, 0) + 1
            if tenant is not None:
                led = counts["by_tenant"].setdefault(
                    tenant, {"requests": 0, "ok": 0, "errors": 0,
                             "by_status": {}})
                led["requests"] += 1
                led["by_status"][key] = led["by_status"].get(key, 0) + 1
                led["ok" if status == 200 else "errors"] += 1
            if status == 200:
                counts["ok"] += 1
                counts["rows"] += rows
            else:
                counts["errors"] += 1

    def one(i: int):
        tenant = tenants[i] if tenants else None
        record(*post_predict(url, payloads[i], trace=ctxs[i],
                             tenant=tenant), tenant=tenant)

    # One fresh trace per request: the client is the trace root, so a
    # request that fans out into router attempts / hedges / batches still
    # reads as ONE tree when the span events are stitched.
    ctxs = [tracing.fresh_context() for _ in payloads]

    t_start = time.monotonic()
    if rate is None:  # closed loop: each worker keeps one request in flight
        def worker():
            while True:
                with lock:
                    i = idx["next"]
                    if i >= len(payloads):
                        return
                    idx["next"] = i + 1
                one(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
    else:  # open loop: dispatch on schedule, completion be damned
        offsets = shape_offsets(len(payloads), rate, shape,
                                spike_factor=spike_factor)
        threads = []
        for i in range(len(payloads)):
            delay = (t_start + offsets[i]) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one, args=(i,), daemon=True)
            threads.append(t)
            t.start()
    if rate is None:
        for t in threads:
            t.start()
    for t in threads:
        t.join()
    elapsed = max(time.monotonic() - t_start, 1e-9)
    s = latency.summary()
    return {
        "mode": "closed" if rate is None else "open",
        "requests": len(payloads),
        "ok": counts["ok"],
        "errors": counts["errors"],
        "by_status": counts["by_status"],
        "rows": counts["rows"],
        "elapsed_s": elapsed,
        "requests_per_sec": counts["ok"] / elapsed,
        "rows_per_sec": counts["rows"] / elapsed,
        "latency_ms": {"p50": s["p50"], "p90": s["p90"], "p99": s["p99"],
                       "mean": s["mean"], "count": s["count"]},
        # Client-observed per-replica distribution (fleet endpoints only):
        # how evenly did the router actually spread THIS window's traffic.
        **({"by_replica": dict(sorted(counts["by_replica"].items()))}
           if counts["by_replica"] else {}),
        # Per-tenant attribution (present when --tenants assigned a mix):
        # which class absorbed the 429s/503s is the QoS story.
        **({"by_tenant": dict(sorted(counts["by_tenant"].items()))}
           if counts["by_tenant"] else {}),
        **({"offered_rate": rate, "shape": shape} if rate is not None else
           {"concurrency": concurrency}),
        "trace_ids": [c.trace_id for c in ctxs],
    }


def run_bench(endpoint: str, *, requests: int = 256, concurrency: int = 32,
              rows: int = 1, rate: float = 100.0, mode: str = "both",
              seed: int = 0, shape: str = "uniform",
              spike_factor: float = 4.0,
              tenant_mix: str | None = None,
              max_new_tokens: int = 32) -> dict:
    url = resolve_endpoint(endpoint)
    health = fetch_healthz(url)
    spec = health["input_spec"]
    engine0 = health.get("engine", {})
    rng = random.Random(seed)
    runs = []
    if mode == "decode":
        decode0 = health.get("decode") or {}
        max_len = int(decode0.get("max_len")
                      or (spec.get("input_ids") or {"shape": [128]}
                          )["shape"][0])
        prompts = make_prompts(
            requests, vocab_size=int(health.get("vocab_size", 2)),
            max_len=max_len, max_new=max_new_tokens, rng=rng)
        runs.append(_drive_decode(url, prompts, concurrency=concurrency,
                                  max_new=max_new_tokens, seed=seed))
    else:
        seq_buckets = [int(b) for b in engine0.get("seq_buckets", [])]
        payloads = [
            make_payload(spec, rows,
                         vocab_size=int(health.get("vocab_size", 2)),
                         rng=rng, seq_buckets=seq_buckets)
            for _ in range(requests)]
        mix = parse_tenants(tenant_mix)
        tenants = None
        if mix:
            names = [name for name, _ in mix]
            weights = [w for _, w in mix]
            tenants = rng.choices(names, weights=weights, k=requests)
        if mode in ("closed", "both"):
            runs.append(_drive(url, payloads, concurrency=concurrency,
                               rate=None, tenants=tenants))
        if mode in ("open", "both"):
            runs.append(_drive(url, payloads, concurrency=concurrency,
                               rate=rate, shape=shape,
                               spike_factor=spike_factor, tenants=tenants))
    health1 = fetch_healthz(url)
    engine1 = health1.get("engine", {})
    # Against a fleet router: the router-counter deltas over the bench
    # window (how many proxied requests needed a retry, how many were
    # shed) plus the server-side routed distribution and replica states.
    fleet = None
    if health1.get("role") == "fleet":
        router0 = (health.get("fleet") or {}).get("router") or {}
        router1 = (health1.get("fleet") or {}).get("router") or {}
        fleet = {
            "replicas": [
                {"replica": r.get("replica"), "state": r.get("state"),
                 "routed": r.get("routed"), "restarts": r.get("restarts")}
                for r in (health1.get("fleet") or {}).get("replicas", [])],
            "router_delta": {
                key: router1.get(key, 0) - router0.get(key, 0)
                for key in ("requests", "retries", "shed",
                            "deadline_exceeded", "scale_ups",
                            "scale_downs")},
            "admitted": (health1.get("fleet") or {}).get("admitted"),
            # Router-side per-tenant ledger + autoscaler view at bench
            # end (additive; absent against pre-QoS routers).
            "tenants": (health1.get("fleet") or {}).get("tenants"),
            "autoscale": (health1.get("fleet") or {}).get("autoscale"),
        }
    # Server-side split over the bench window: where did a request's
    # life go — waiting for the admission window, or under compute?
    split = {
        "queue_wait_ms": (engine1.get("queue_wait_ms_total", 0)
                          - engine0.get("queue_wait_ms_total", 0)),
        "compute_ms": (engine1.get("compute_ms_total", 0)
                       - engine0.get("compute_ms_total", 0)),
        "batches": (engine1.get("batches", 0) - engine0.get("batches", 0)),
        "batch_rows": (engine1.get("batch_rows", 0)
                       - engine0.get("batch_rows", 0)),
        "padded_rows": (engine1.get("padded_rows", 0)
                        - engine0.get("padded_rows", 0)),
    }
    if split["padded_rows"]:
        split["fill"] = split["batch_rows"] / split["padded_rows"]
    # Decode healthz deltas over the window (single server with
    # decode.enabled; absent against routers/pre-decode servers): the
    # server-side view of tokens/steps/evictions this traffic caused.
    decode_delta = None
    if (health1.get("decode") or {}) and mode == "decode":
        d0, d1 = health.get("decode") or {}, health1.get("decode") or {}
        decode_delta = {
            key: d1.get(key, 0) - d0.get(key, 0)
            for key in ("tokens", "steps", "streams_done", "evictions")}
        decode_delta["compiled_buckets"] = d1.get("compiled_buckets")
        decode_delta["avg_occupancy"] = d1.get("avg_occupancy")
        decode_delta["scheduler"] = d1.get("scheduler")
        decode_delta["kv_dtype"] = d1.get("kv_dtype")
    return {
        "schema": BENCH_SCHEMA,
        "endpoint": url,
        "model": health.get("model"),
        "task": health.get("task"),
        "step": health.get("step"),
        "rows_per_request": rows,
        "runs": runs,
        "fleet": fleet,
        "decode_delta": decode_delta,
        "server_split": split,
        "server_latency": engine1.get("latency"),
        # Healthz deltas across the window: serve-side HBM pressure (peak
        # growth attributable to this traffic) and the engine's
        # compute-fraction movement (serve/server.py /healthz).
        "server_memory": {
            "before": health.get("memory"),
            "after": health1.get("memory"),
            "peak_bytes_delta": (
                (health1.get("memory") or {}).get("peak_bytes_in_use", 0)
                - (health.get("memory") or {}).get("peak_bytes_in_use", 0)),
        },
        "server_goodput": {
            "before": health.get("goodput"),
            "after": health1.get("goodput"),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--endpoint", required=True,
                    help="server URL, or path to its endpoint.json")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop offered rate (req/s)")
    ap.add_argument("--mode", choices=("closed", "open", "both", "decode"),
                    default="both",
                    help="decode = streamed /generate against a "
                         "decode-enabled endpoint (TTFT/TPOT/tokens-per-"
                         "sec instead of request latency)")
    ap.add_argument("--max-new-tokens", type=int, default=32,
                    help="token budget in --mode decode: every 8th "
                         "stream decodes the full budget, the rest a "
                         "quarter (mixed-length churn)")
    ap.add_argument("--shape", choices=SHAPES, default="uniform",
                    help="open-loop traffic shape (spike/ramp/diurnal "
                         "replay realistic load against the base --rate)")
    ap.add_argument("--spike-factor", type=float, default=4.0,
                    help="burst multiplier for --shape spike")
    ap.add_argument("--tenants", default=None, metavar="NAME=W,...",
                    help="weighted tenant mix, e.g. 'high=1,batch=3' — "
                         "each request draws an X-DTF-Tenant class and "
                         "the bench JSON gains per-tenant attribution")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="SERVE_BENCH.json")
    args = ap.parse_args(argv)
    try:
        bench = run_bench(
            args.endpoint, requests=args.requests,
            concurrency=args.concurrency, rows=args.rows, rate=args.rate,
            mode=args.mode, seed=args.seed, shape=args.shape,
            spike_factor=args.spike_factor, tenant_mix=args.tenants,
            max_new_tokens=args.max_new_tokens)
    except (urllib.error.URLError, OSError, FileNotFoundError) as e:
        print(f"error: cannot reach {args.endpoint}: {e}", file=sys.stderr)
        return 1
    with open(args.out, "w") as fh:
        json.dump(bench, fh, indent=2, sort_keys=True)
        fh.write("\n")
    for run in bench["runs"]:
        lat = run["latency_ms"]
        print(f"{run['mode']:>6}: {run['ok']}/{run['requests']} ok, "
              f"{run['requests_per_sec']:.1f} req/s, "
              f"p50 {lat['p50']:.1f} ms, p99 {lat['p99']:.1f} ms")
        if run["mode"] == "decode":
            ttft, tpot = run["ttft_ms"], run["tpot_ms"]
            print(f"        {run['tokens']} tokens, "
                  f"{run['tokens_per_sec']:.1f} tok/s, "
                  f"ttft p50 {ttft['p50']:.1f}/p99 {ttft['p99']:.1f} ms, "
                  f"tpot p50 {tpot['p50']:.1f}/p99 {tpot['p99']:.1f} ms, "
                  f"{run['retried_409']} retried 409s")
        for tenant, led in (run.get("by_tenant") or {}).items():
            print(f"        tenant {tenant}: {led['ok']}/{led['requests']}"
                  f" ok ({led['by_status']})")
    if bench.get("fleet"):
        delta = bench["fleet"]["router_delta"]
        dist = ", ".join(
            f"{r['replica']}={r['routed']}"
            for r in bench["fleet"]["replicas"])
        print(f" fleet: {delta['requests']} proxied ({dist}), "
              f"{delta['retries']} retries, {delta['shed']} shed")
    print(f"wrote {args.out}")
    return 0 if all(r["ok"] for r in bench["runs"]) else 1


if __name__ == "__main__":
    sys.exit(main())
