#!/usr/bin/env python
"""Render sklearn's handwritten-digit scans as an ImageNet-style JPEG tree.

Companion to scripts/make_digits_npz.py (same 1,797 real scans, same
seeded 1500/297 split) but emitting the torchvision directory layout that
scripts/make_imagenet_tfrecords.py consumes:

    <out>/train/<digit>/<idx>.jpg
    <out>/validation/<digit>/<idx>.jpg

This closes the full north-star input loop with real image files: raw
JPEGs → TFRecord authoring → (native or tf.data) ImageNet pipeline →
train → exact eval (SURVEY.md §3.1/§3.4), in an environment where actual
ImageNet is unreachable.

Upsampling: 8x8 → nearest-neighbor x8 (64x64) RGB, JPEG quality 92. The
64x64 canvas leaves room for the Inception-style distorted crops of the
train transform.

Usage: python scripts/make_digits_jpeg_tree.py [out_dir]  (default
/tmp/digits_jpeg)
"""

from __future__ import annotations

import os
import sys

import numpy as np


def main() -> int:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/digits_jpeg"
    import tensorflow as tf
    from sklearn.datasets import load_digits

    digits = load_digits()
    images = digits.images.astype(np.float32)  # (1797, 8, 8), values 0..16
    labels = digits.target.astype(np.int64)

    up = np.kron(images, np.ones((8, 8), np.float32))       # (N, 64, 64)
    up = (up / 16.0 * 255.0).astype(np.uint8)
    rgb = np.repeat(up[..., None], 3, axis=-1)              # (N, 64, 64, 3)

    # Same split discipline as make_digits_npz.py: seeded shuffle so the
    # writer-ordered raw file doesn't become a distribution-shifted split.
    perm = np.random.default_rng(0).permutation(len(rgb))
    rgb, labels = rgb[perm], labels[perm]
    n_train = 1500

    counts = {"train": 0, "validation": 0}
    for i, (img, lab) in enumerate(zip(rgb, labels)):
        split = "train" if i < n_train else "validation"
        d = os.path.join(out_dir, split, f"digit_{lab}")
        os.makedirs(d, exist_ok=True)
        jpg = tf.io.encode_jpeg(img, quality=92).numpy()
        with open(os.path.join(d, f"{i:05d}.jpg"), "wb") as fh:
            fh.write(jpg)
        counts[split] += 1
    print(f"wrote {out_dir}: train {counts['train']}, "
          f"validation {counts['validation']} (10 classes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
