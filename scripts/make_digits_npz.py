#!/usr/bin/env python
"""Package sklearn's bundled handwritten-digit scans as an ``mnist.npz``.

The build environment has no network egress and no MNIST archive on disk
(RESULTS.md), but scikit-learn ships 1,797 REAL handwritten digit images
(UCI optical-recognition set, 8x8) inside the package. This converts them
to the keras mnist.npz layout the MNIST pipeline reads (data/mnist.py), so
the real-file path — load → standardize → shuffle-shard → exact eval —
runs on genuine handwriting end to end.

Upsampling: 8x8 → nearest-neighbor 3x (24x24) → 2px zero pad (28x28).
Split: seeded shuffle, 1500 train / 297 test (the raw file is ordered by
writer, so a sequential split would make the test set a writer-disjoint
distribution shift; the shuffle is fixed-seed and reproducible).

Usage: python scripts/make_digits_npz.py [out_dir]   (default /tmp/digits)
"""

from __future__ import annotations

import os
import sys

import numpy as np


def main() -> int:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/digits"
    from sklearn.datasets import load_digits

    digits = load_digits()
    images = digits.images.astype(np.float32)  # (1797, 8, 8), values 0..16
    labels = digits.target.astype(np.int64)

    up = np.kron(images, np.ones((3, 3), np.float32))      # (N, 24, 24)
    up = np.pad(up, ((0, 0), (2, 2), (2, 2)))              # (N, 28, 28)
    up = (up / 16.0 * 255.0).astype(np.uint8)              # mnist value range

    perm = np.random.default_rng(0).permutation(len(up))
    up, labels = up[perm], labels[perm]
    n_train = 1500
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "mnist.npz")
    np.savez(
        path,
        x_train=up[:n_train], y_train=labels[:n_train],
        x_test=up[n_train:], y_test=labels[n_train:],
    )
    print(f"wrote {path}: train {n_train}, test {len(up) - n_train}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
