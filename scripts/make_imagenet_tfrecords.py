#!/usr/bin/env python
"""Author ImageNet-style TFRecord shards from a directory tree of JPEGs.

The reference framework consumes the canonical ImageNet TFRecord layout
(SURVEY.md §2 row 5: ``image/encoded`` JPEG bytes + ``image/class/label``
in [1, 1000]); this is the companion authoring tool so a user switching
from the reference can produce that layout from raw images without the
legacy TF build scripts.

Input layout (torchvision/Keras convention):

    <src>/<split>/<class_name>/<anything>.{jpg,jpeg,JPEG,png}

Class names are sorted lexicographically and assigned labels 1..N (the
1-based convention the pipeline's ``label - 1`` shift expects —
data/imagenet.py). PNG inputs are transcoded to JPEG so the reader's
decode path stays uniform.

Usage:
    python scripts/make_imagenet_tfrecords.py <src> <out> \
        --split train --shards 128 [--quality 90] [--seed 0]

Outputs ``<out>/<split>-XXXXX-of-NNNNN`` shards plus one ``labels.txt``
mapping file (split-independent — classes are the union across splits),
shuffling examples across shards with a seeded RNG so each shard is
class-mixed (required for good shuffle behavior with small per-host
shuffle buffers).
"""

from __future__ import annotations

import argparse
import os
import sys


def class_list(src: str) -> list[str]:
    """Sorted union of class directories across ALL splits under src.

    Labels must be consistent across splits — deriving them per split
    would shift every id after a class that is missing from one split
    (silently mislabeling eval). The union keeps train/validation/test
    invocations agreeing on the same map.
    """
    classes: set[str] = set()
    for split in os.listdir(src):
        sdir = os.path.join(src, split)
        if os.path.isdir(sdir):
            classes.update(
                d for d in os.listdir(sdir)
                if os.path.isdir(os.path.join(sdir, d))
            )
    if not classes:
        raise SystemExit(f"no <split>/<class> directories under {src}")
    return sorted(classes)


def find_examples(src: str, split: str,
                  classes: list[str]) -> list[tuple[str, int]]:
    split_dir = os.path.join(src, split)
    if not os.path.isdir(split_dir):
        raise SystemExit(f"no such split directory: {split_dir}")
    exts = (".jpg", ".jpeg", ".png")
    examples: list[tuple[str, int]] = []
    for label0, cls in enumerate(classes):
        cdir = os.path.join(split_dir, cls)
        if not os.path.isdir(cdir):
            continue
        for fn in sorted(os.listdir(cdir)):
            if fn.lower().endswith(exts):
                # 1-based labels: the canonical ImageNet TFRecord convention.
                examples.append((os.path.join(cdir, fn), label0 + 1))
    if not examples:
        raise SystemExit(f"no images found under {split_dir}")
    return examples


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("src", help="root containing <split>/<class>/*.jpg")
    p.add_argument("out", help="output directory for TFRecord shards")
    p.add_argument("--split", default="train",
                   help="split name (subdir of src and shard prefix)")
    p.add_argument("--shards", type=int, default=128)
    p.add_argument("--quality", type=int, default=90,
                   help="JPEG quality when transcoding PNG inputs")
    p.add_argument("--seed", type=int, default=0,
                   help="shuffle seed for class-mixing across shards")
    args = p.parse_args(argv)

    import numpy as np
    import tensorflow as tf

    classes = class_list(args.src)
    examples = find_examples(args.src, args.split, classes)
    rng = np.random.default_rng(args.seed)
    order = rng.permutation(len(examples))
    os.makedirs(args.out, exist_ok=True)

    # One split-independent map (classes are the union across splits).
    with open(os.path.join(args.out, "labels.txt"), "w") as fh:
        for i, cls in enumerate(classes):
            fh.write(f"{i + 1} {cls}\n")

    n_shards = max(1, min(args.shards, len(examples)))
    writers = [
        tf.io.TFRecordWriter(os.path.join(
            args.out, f"{args.split}-{s:05d}-of-{n_shards:05d}"))
        for s in range(n_shards)
    ]
    try:
        for rank, idx in enumerate(order):
            path, label = examples[idx]
            with open(path, "rb") as fh:
                data = fh.read()
            if path.lower().endswith(".png"):
                img = tf.io.decode_png(data, channels=3)
                data = tf.io.encode_jpeg(img, quality=args.quality).numpy()
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[data])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[label])),
            }))
            writers[rank % n_shards].write(ex.SerializeToString())
    finally:
        for w in writers:
            w.close()
    print(f"wrote {len(examples)} examples / {len(classes)} classes "
          f"into {n_shards} shards under {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
