#!/usr/bin/env python
"""Author a synthetic-grammar MLM corpus as pre-tokenized TFRecords.

Completes the trained-to-metric story for BASELINE config 5 (BERT MLM)
in an environment with no real text corpus (RESULTS.md): sequences are
arithmetic progressions ``tok[i] = base + i*stride (mod band)`` over a
vocab band clear of the special ids, so every masked token is exactly
recoverable from its context (infer the stride from any two neighbors)
— a model that learns the grammar approaches 100% masked accuracy,
making the metric a sharp pass/fail signal for the WHOLE path:
TFRecord read (native C++ or tf.data) → dynamic masking → train →
exact full-set eval.

Layout: ``<out>/train/mlm-XXX.tfrecord`` and ``<out>/eval/...`` —
point ``data.data_dir`` at train/ and ``eval_data.data_dir`` at eval/
(the MLM reader globs every record file in its directory).

Usage: python scripts/make_progression_mlm.py [out_dir]
           [--seq-len 64] [--train-seqs 8192] [--eval-seqs 1024]
           [--shards 4] [--seed 0]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

BAND_LO, BAND = 1000, 499  # prime band width; clear of 0/CLS/SEP/MASK ids


def _write(path: str, seqs: np.ndarray) -> None:
    import tensorflow as tf

    with tf.io.TFRecordWriter(path) as w:
        for row in seqs:
            w.write(tf.train.Example(features=tf.train.Features(feature={
                "input_ids": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=row.tolist())),
            })).SerializeToString())


def make_split(rng: np.random.Generator, n: int, seq_len: int,
               *, parity: int, min_doc: int = 0, max_doc: int = 0
               ) -> np.ndarray:
    """Sequences whose (base mod 2) == parity. Train takes parity 0 and
    eval parity 1, so the splits are DISJOINT sequence sets: a model can
    only score on eval by generalizing the stride grammar, never by
    memorizing training sequences.

    With ``min_doc``/``max_doc`` set, each row is a VARIABLE-LENGTH
    document (trailing-zero padded to ``seq_len``) — the shape the
    sequence-packing pipeline (data.pack_factor) consumes."""
    base = rng.integers(0, BAND // 2, n) * 2 + parity
    stride = rng.integers(1, 4, n)
    idx = np.arange(seq_len)
    toks = (base[:, None] + idx[None, :] * stride[:, None]) % BAND + BAND_LO
    toks = toks.astype(np.int64)
    if max_doc:
        lengths = rng.integers(min_doc, max_doc + 1, n)
        toks *= (idx[None, :] < lengths[:, None])
    return toks


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("out", nargs="?", default="/tmp/progression_mlm")
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--train-seqs", type=int, default=8192)
    p.add_argument("--eval-seqs", type=int, default=1024)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--min-doc", type=int, default=0,
                   help="variable-length docs: min real tokens per row")
    p.add_argument("--max-doc", type=int, default=0,
                   help="variable-length docs: max real tokens per row "
                        "(0 = full-width rows, no padding)")
    a = p.parse_args()
    if a.min_doc and not a.max_doc:
        p.error("--min-doc needs --max-doc (0 disables variable-length "
                "docs entirely, silently ignoring the floor)")
    if a.max_doc and not 0 < a.min_doc <= a.max_doc <= a.seq_len:
        p.error(f"need 0 < min_doc <= max_doc <= seq_len, got "
                f"{a.min_doc}..{a.max_doc} vs {a.seq_len}")

    rng = np.random.default_rng(a.seed)
    for split, n, shards, parity in (
            ("train", a.train_seqs, a.shards, 0),
            ("eval", a.eval_seqs, max(1, a.shards // 2), 1)):
        d = os.path.join(a.out, split)
        os.makedirs(d, exist_ok=True)
        seqs = make_split(rng, n, a.seq_len, parity=parity,
                          min_doc=a.min_doc, max_doc=a.max_doc)
        for s, part in enumerate(np.array_split(seqs, shards)):
            _write(os.path.join(d, f"mlm-{s:03d}.tfrecord"), part)
        print(f"wrote {n} seqs (len {a.seq_len}) into {shards} shards "
              f"under {d}")
    return 0


if __name__ == "__main__":
    return_code = main()
    raise SystemExit(return_code)
