#!/bin/bash
# Tier-1 wrapper: the ROADMAP.md verify command plus the graftcheck
# static-analysis gate, with both artifacts archived side by side so CI
# keeps the dtf-lint-report/1 JSON next to the pytest log.
#
#   bash scripts/run_tier1.sh [ARTIFACT_DIR]     (default /tmp/tier1)
#
# Exit code: non-zero if EITHER pytest or graftcheck fails. graftcheck
# runs first — it is seconds, and a finding there (untallied collective,
# dead donation, busted thread contract) explains test failures better
# than the tests do.
set -u -o pipefail
cd "$(dirname "$0")/.."

ART="${1:-/tmp/tier1}"
# TIER1_MARKERS widens the run (e.g. TIER1_MARKERS='slow and serve'
# runs the fleet chaos drill, whose serve-bench JSON is archived below).
MARKERS="${TIER1_MARKERS:-not slow}"
mkdir -p "$ART"

echo "=== graftcheck (full run, JSON → $ART/graftcheck.json) ==="
env JAX_PLATFORMS=cpu python scripts/graftcheck.py \
    --json "$ART/graftcheck.json" | tee "$ART/graftcheck.log"
gc_rc=${PIPESTATUS[0]}

echo "=== tier-1 pytest (log → $ART/pytest.log) ==="
# DTF_SERVE_BENCH_DIR: when a slow run includes the fleet chaos drill
# (tests/test_fleet_drill.py), its dtf-serve-bench/2 JSON lands here
# next to the other artifacts instead of dying with pytest's tmpdir.
# DTF_GANG_DRILL_DIR: same contract for the gang chaos drills
# (tests/test_cluster_drill.py) — their supervisor_events.jsonl is the
# attempt-by-attempt record of the coordinated restart / gang refit.
# DTF_TRACE_DIR: the drills' Perfetto trace exports and any
# flight-recorder dumps land here too (docs/OBSERVABILITY.md "Tracing
# and flight recorder").
# DTF_DECODE_BENCH_DIR: the decode acceptance drill
# (tests/test_decode_drill.py) archives its continuous-vs-static A/B
# bench JSON (dtf-serve-bench/2 schema, mode "decode") the same way.
# DTF_DATA_DRILL_DIR: the exactly-once data drill
# (tests/test_data_drill.py) archives its per-attempt telemetry —
# supervisor events plus the worker event streams whose data_state /
# data_shard records prove the multiset claim.
# DTF_AUTOTUNE_DIR: the autotune smoke drill (tests/test_autotune.py)
# archives its fake-runner search journal + leaderboard — the
# dtf-autotune-journal/1 resume record and the dtf-leaderboard/1 pin.
timeout -k 10 870 env JAX_PLATFORMS=cpu DTF_SERVE_BENCH_DIR="$ART" \
    DTF_GANG_DRILL_DIR="$ART" DTF_TRACE_DIR="$ART" \
    DTF_DECODE_BENCH_DIR="$ART" DTF_DATA_DRILL_DIR="$ART" \
    DTF_AUTOTUNE_DIR="$ART" \
    python -m pytest tests/ -q \
    -m "$MARKERS" --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
    | tee "$ART/pytest.log"
py_rc=${PIPESTATUS[0]}

if [ -f "$ART/SERVE_BENCH_FLEET.json" ]; then
  echo "=== serve bench archived: $ART/SERVE_BENCH_FLEET.json ==="
fi
# The autoscale drill (tests/test_autoscale_drill.py) archives its
# shaped-load bench (per-tenant attribution) and the router's raw
# scaling-event telemetry for the same slow runs.
if [ -f "$ART/SERVE_BENCH_AUTOSCALE.json" ]; then
  echo "=== autoscale bench archived: $ART/SERVE_BENCH_AUTOSCALE.json ==="
fi
if [ -f "$ART/AUTOSCALE_EVENTS.jsonl" ]; then
  echo "=== autoscale events archived: $ART/AUTOSCALE_EVENTS.jsonl ==="
fi
if [ -f "$ART/GANG_DRILL_EVENTS.jsonl" ]; then
  echo "=== gang drill events archived: $ART/GANG_DRILL_EVENTS.jsonl ==="
fi
# The decode acceptance drill (tests/test_decode_drill.py) archives its
# continuous-vs-static A/B bench JSON for the same slow runs.
for bench in "$ART"/DECODE_BENCH_*.json; do
  [ -f "$bench" ] && echo "=== decode bench archived: $bench ==="
done
for trace in "$ART"/*TRACE*.json; do
  [ -f "$trace" ] && echo "=== perfetto trace archived: $trace ==="
done
for dump in "$ART"/flightrec-*.json; do
  [ -f "$dump" ] && echo "=== flight-recorder dump archived: $dump ==="
done
# The exactly-once data drill (tests/test_data_drill.py) archives the
# telemetry that backs its consumed-sample multiset comparison.
for ev in "$ART"/DATA_DRILL_*.jsonl; do
  [ -f "$ev" ] && echo "=== data drill events archived: $ev ==="
done
# The autotune smoke drill (tests/test_autotune.py) archives its search
# journal and winner pin so a tier-1 run leaves a worked example of the
# journal/leaderboard contracts next to the pytest log.
for art in "$ART"/AUTOTUNE_*.json "$ART"/AUTOTUNE_*.jsonl; do
  [ -f "$art" ] && echo "=== autotune artifact archived: $art ==="
done

echo "=== tier-1 summary: graftcheck rc=$gc_rc pytest rc=$py_rc ==="
[ "$gc_rc" -eq 0 ] && [ "$py_rc" -eq 0 ]
