#!/usr/bin/env python
"""Gang supervisor: cluster-level fault tolerance for the N-process runtime.

``jax.distributed`` gangs fail as a unit — one worker crash or hang wedges
every collective in the job — so scripts/train_resilient.py's per-process
ladder is not enough at pod scale. This supervisor owns the WHOLE gang:

    python scripts/train_cluster.py --procs 2 --devices-per-proc 2 \\
        --heartbeat-timeout 60 --workdir /tmp/dtf-gang -- \\
        --config configs/lenet_mnist.yaml \\
        --set checkpoint.directory=/tmp/dtf-gang/ck

Everything after ``--`` is passed to train.py verbatim; the N workers are
launched through the same ``launch_local_cluster`` discovery path
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) the bare
launcher uses. Behavior ladder (docs/RESILIENCE.md "Gang supervision"):

  * Coordinated gang restart: on ANY worker's crash or stale heartbeat
    (every worker beats its own ``heartbeat-p<i>.json``, pid-scoped), the
    survivors get SIGTERM — the chief finishes its in-flight step and
    force-saves through the graceful-preemption contract (rc 83) — then
    the whole gang is relaunched with shared exponential backoff.
  * Crash-loop breaker keyed on (worker, failure signature): worker 3
    segfaulting at the same step trips ITS breaker after
    ``--crash-loop-threshold`` identical no-progress repeats, while one
    flaky host's noise cannot burn the shared attempt budget
    (core/cluster.py GangBreaker).
  * Gang-level rc-84: a worker dropped permanently (``drop_worker``
    chaos, or no heartbeat within ``cluster.rejoin_timeout_s`` while its
    peers rejoined) shrinks the gang — the mesh is refit to the
    surviving process count (fit_axis_sizes), batch/grad-accum rescaled
    so the EFFECTIVE batch is preserved (rescale_for_devices), and the
    smaller gang relaunched WITHOUT consuming an attempt, bounded by
    ``--max-reshards``. The refit reaches the children via
    DTF_ELASTIC_OVERRIDES, exactly like the single-process ladder.
  * Graceful preemption (first exit rc 83 that the supervisor did not
    itself cause) and operator cancellation (130/143, or a signal sent
    to the supervisor and forwarded to the gang) keep their
    train_resilient.py semantics.
  * Cluster chaos (core/faults.py): ``kill_worker:W[:T]``,
    ``stall_worker:W:S`` (SIGSTOP/SIGCONT) and ``drop_worker:W[:T]``
    fire at the supervisor's ``gang_chaos`` point on a 1-based tick
    clock that starts once EVERY worker has heartbeated.
  * Every attempt lands in ``<ckpt_dir>/supervisor_events.jsonl`` tagged
    with the ``process_id`` the failure was attributed to, so
    stitch_attempts / analyze_trace.py classify gang restart gaps per
    host.

Single-threaded by design: one poll loop owns the children, the
heartbeat/rejoin watchdogs, the chaos tick clock and the SIGTERM→SIGKILL
escalation — no supervisor threads to leak or deadlock.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import (  # noqa: E402
    cluster,
    faults,
    supervision,
    telemetry,
    tracing,
)
from scripts import launch_local_cluster as llc  # noqa: E402
from scripts.train_resilient import (  # noqa: E402
    _fmt_axes,
    build_env,
    find_checkpoint_dir,
    latest_committed_step,
    parse_training_params,
)


def parse_rejoin_timeout(cmd: list[str]) -> float:
    """The child-visible ``cluster.rejoin_timeout_s`` knob, recovered the
    same way parse_training_params recovers mesh sizes: --config YAML
    first, then any ``--set cluster.rejoin_timeout_s=`` override in the
    raw command text (last occurrence wins)."""
    value = 0.0
    config_path = None
    for i, tok in enumerate(cmd):
        if tok == "--config" and i + 1 < len(cmd):
            config_path = cmd[i + 1]
        elif tok.startswith("--config="):
            config_path = tok.split("=", 1)[1]
    if config_path:
        try:
            import yaml

            with open(config_path) as fh:
                doc = yaml.safe_load(fh) or {}
            value = float((doc.get("cluster") or {}).get(
                "rejoin_timeout_s", value))
        except Exception:
            pass
    for m in re.finditer(r"cluster\.rejoin_timeout_s=([0-9.]+)",
                         " ".join(cmd)):
        value = float(m.group(1))
    return value


# -- cancellation forwarding ----------------------------------------------
_children: dict[int, subprocess.Popen] = {}
_cancelled = False


def _forward_signal(signum, frame):
    global _cancelled
    _cancelled = True
    for child in _children.values():
        if child.poll() is None:
            child.send_signal(signum)


@dataclasses.dataclass
class GangResult:
    """One gang attempt's post-mortem, as the poll loop observed it."""

    rcs: dict[int, int]             # worker → normalized exit code
    pids: dict[int, int]            # worker → child pid (heartbeat scoping)
    first_worker: int | None = None  # root-cause worker (first nonzero exit)
    first_rc: int = 0
    hung: set[int] = dataclasses.field(default_factory=set)
    dropped: set[int] = dataclasses.field(default_factory=set)

    @property
    def done(self) -> bool:
        return all(rc == 0 for rc in self.rcs.values())


def _run_gang_attempt(
    train_args: list[str],
    env: dict,
    *,
    procs: int,
    devices_per_proc: int,
    workdir: str,
    ckpt_dir: str | None,
    hb_timeout: float,
    hb_poll: float,
    startup_grace: float,
    rejoin_timeout_s: float,
    chaos_tick_s: float,
    grace: float = 10.0,
) -> GangResult:
    """Launch one gang and watch it to collective exit.

    The loop owns four clocks: per-worker heartbeat staleness (pid-scoped
    against THIS attempt's children), the pre-admission rejoin watchdog,
    the chaos tick (starting once every worker has beaten), and the
    SIGTERM→SIGKILL escalation once a shutdown begins. The first nonzero
    exit is the root cause; everything after it (peers SIGTERMed by us
    exiting 83, SIGKILL escalations) is fallout.
    """
    global _children
    port = llc.free_port()
    children, logs = llc.spawn_gang(
        train_args, procs=procs, devices_per_proc=devices_per_proc,
        workdir=workdir, port=port, base_env=env)
    live = dict(enumerate(children))
    _children = dict(live)
    result = GangResult(
        rcs={}, pids={w: p.pid for w, p in live.items()})
    hb_paths = {
        w: (cluster.heartbeat_path(ckpt_dir, w, procs) if ckpt_dir else None)
        for w in live
    }
    print(f"train_cluster: launched gang of {procs} "
          f"(coordinator 127.0.0.1:{port}); logs in {workdir}/worker-*.log",
          file=sys.stderr)

    start = time.monotonic()
    admitted: float | None = None
    tick = 0
    stalled: dict[int, float] = {}   # worker → monotonic SIGCONT deadline
    shutting_down = False
    term_at = 0.0
    killed: set[int] = set()

    def _begin_shutdown(now: float) -> None:
        nonlocal shutting_down, term_at
        if shutting_down:
            return
        shutting_down = True
        term_at = now
        for w, deadline in list(stalled.items()):
            # A SIGSTOPped worker cannot honor SIGTERM — wake it first.
            proc = live.get(w)
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGCONT)
            del stalled[w]
        for w, proc in live.items():
            if proc.poll() is None:
                proc.terminate()

    try:
        while live:
            now = time.monotonic()
            for w, proc in list(live.items()):
                r = proc.poll()
                if r is None:
                    if shutting_down and now - term_at > grace \
                            and w not in killed:
                        proc.kill()
                        killed.add(w)
                    continue
                del live[w]
                if r < 0:
                    r = 128 - r  # shell convention: 128 + signal
                result.rcs[w] = r
                if r != 0 and result.first_worker is None:
                    result.first_worker, result.first_rc = w, r
                if r != 0:
                    # One worker down kills every collective — SIGTERM
                    # the survivors so the chief force-saves (rc 83)
                    # instead of timing out inside a dead rendezvous.
                    _begin_shutdown(now)
            if not live:
                break
            if not shutting_down:
                ages = {
                    w: (supervision.heartbeat_age_s(hb_paths[w],
                                                    pid=proc.pid)
                        if hb_paths[w] else None)
                    for w, proc in live.items()
                }
                if hb_timeout > 0 or startup_grace > 0:
                    for w, proc in list(live.items()):
                        age = ages.get(w)
                        stale = (hb_timeout > 0 and age is not None
                                 and age > hb_timeout)
                        no_start = (startup_grace > 0 and age is None
                                    and now - start > startup_grace)
                        if stale or no_start:
                            why = (f"heartbeat stale ({age:.0f}s > "
                                   f"{hb_timeout:.0f}s budget)" if stale
                                   else f"no heartbeat within "
                                        f"{startup_grace:.0f}s startup grace")
                            print(f"train_cluster: worker {w} {why} — "
                                  f"killing pid={proc.pid}", file=sys.stderr)
                            result.hung.add(w)
                            if proc.poll() is None:
                                proc.send_signal(signal.SIGCONT)
                                proc.kill()
                            stalled.pop(w, None)
                if admitted is None:
                    overdue = cluster.decide_rejoin(
                        ages, elapsed_s=now - start,
                        rejoin_timeout_s=rejoin_timeout_s)
                    for w in overdue:
                        print(f"train_cluster: worker {w} failed to rejoin "
                              f"within {rejoin_timeout_s:.0f}s — dropping "
                              f"it from the gang", file=sys.stderr)
                        result.dropped.add(w)
                        proc = live.get(w)
                        if proc is not None and proc.poll() is None:
                            proc.kill()
                    if live and all(ages.get(w) is not None for w in live):
                        admitted = now  # chaos clock starts at readiness
                if admitted is not None and chaos_tick_s > 0:
                    while admitted + (tick + 1) * chaos_tick_s <= now:
                        tick += 1
                        for fault in faults.fire("gang_chaos", step=tick):
                            w = fault.worker
                            proc = live.get(w) if w is not None else None
                            if proc is None or proc.poll() is not None:
                                print(f"train_cluster: {fault.fault_id} "
                                      f"targets worker {w}, which is not "
                                      f"live — ignored", file=sys.stderr)
                                continue
                            if fault.kind == "kill_worker":
                                print(f"train_cluster: chaos SIGKILL worker "
                                      f"{w} (tick {tick})", file=sys.stderr)
                                proc.kill()
                            elif fault.kind == "drop_worker":
                                print(f"train_cluster: chaos DROP worker "
                                      f"{w} permanently (tick {tick})",
                                      file=sys.stderr)
                                result.dropped.add(w)
                                proc.kill()
                            elif fault.kind == "stall_worker":
                                print(f"train_cluster: chaos SIGSTOP worker "
                                      f"{w} for {fault.seconds:.0f}s "
                                      f"(tick {tick})", file=sys.stderr)
                                proc.send_signal(signal.SIGSTOP)
                                stalled[w] = now + (fault.seconds or 0.0)
                for w, resume_at in list(stalled.items()):
                    if now >= resume_at:
                        proc = live.get(w)
                        if proc is not None and proc.poll() is None:
                            print(f"train_cluster: chaos SIGCONT worker {w}",
                                  file=sys.stderr)
                            proc.send_signal(signal.SIGCONT)
                        del stalled[w]
            time.sleep(min(0.2, hb_poll))
    finally:
        for proc in children:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    proc.kill()
            proc.wait()
        for log in logs:
            log.close()
        _children = {}
    for w, proc in enumerate(children):
        result.rcs.setdefault(w, 0 if proc.returncode == 0
                              else abs(proc.returncode))
    return result


def main(argv=None) -> int:
    global _cancelled
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument("--devices-per-proc", type=int, default=2)
    parser.add_argument("--workdir", default="/tmp/dtf-gang",
                        help="worker log directory")
    parser.add_argument("--max-attempts", type=int, default=10)
    parser.add_argument("--retry-sleep", type=float, default=5.0,
                        help="backoff BASE seconds (doubles per consecutive "
                             "failure, jittered)")
    parser.add_argument("--backoff-max", type=float, default=120.0)
    parser.add_argument("--jitter", type=float, default=0.5)
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="kill a worker whose heartbeat-p<i>.json is "
                             "older than this many seconds and restart the "
                             "gang (0 disables)")
    parser.add_argument("--heartbeat-poll", type=float, default=2.0)
    parser.add_argument("--startup-grace", type=float, default=0.0,
                        help="kill a worker with NO heartbeat this many "
                             "seconds after launch (0 disables; compile "
                             "time counts against it)")
    parser.add_argument("--rejoin-timeout", type=float, default=None,
                        help="drop a worker that fails to rejoin within "
                             "this many seconds while peers did, and refit "
                             "the gang (default: the command's "
                             "cluster.rejoin_timeout_s knob; 0 disables)")
    parser.add_argument("--chaos-tick", type=float, default=1.0,
                        help="gang_chaos fault-point tick period in "
                             "seconds (0 disables the chaos clock)")
    parser.add_argument("--crash-loop-threshold", type=int, default=3)
    parser.add_argument("--max-preemptions", type=int, default=50)
    parser.add_argument("--max-reshards", type=int, default=8,
                        help="safety bound on gang refits + child-led "
                             "elastic reshards (they never consume "
                             "attempts)")
    parser.add_argument("--events", default=None,
                        help="supervisor telemetry JSONL (default: "
                             "<checkpoint.directory>/supervisor_events"
                             ".jsonl; '-' disables)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="train.py arguments after --")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no train.py arguments given (put them after `--`)")
    if args.procs < 1:
        parser.error("--procs must be >= 1")
    if args.max_attempts < 1:
        parser.error("--max-attempts must be >= 1")

    ckpt_dir, ckpt_enabled = find_checkpoint_dir(cmd)
    if not ckpt_enabled:
        print("train_cluster: WARNING — no checkpoint.directory in the "
              "command; every gang restart will lose all progress AND the "
              "per-worker heartbeat/rejoin watchdogs are blind",
              file=sys.stderr)
    rejoin_timeout = (args.rejoin_timeout if args.rejoin_timeout is not None
                      else parse_rejoin_timeout(cmd))

    events_path = args.events
    if events_path is None and ckpt_dir:
        events_path = os.path.join(ckpt_dir, "supervisor_events.jsonl")
    writer = telemetry.TelemetryWriter(
        None if events_path in (None, "-") else events_path)
    writer.emit_run_meta(
        argv=[sys.argv[0]], supervisor=True, gang=True,
        command=" ".join(cmd), procs=args.procs,
        devices_per_proc=args.devices_per_proc,
        max_attempts=args.max_attempts,
        heartbeat_timeout_s=args.heartbeat_timeout,
        rejoin_timeout_s=rejoin_timeout,
        checkpoint_dir=ckpt_dir or "",
    )

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _forward_signal)
        except (ValueError, OSError):  # non-main thread (tests importing us)
            pass

    # The gang's ONE causal root: supervisor.run → supervisor.attempt per
    # attempt (its context rides DTF_TRACE_CTX into every worker, whose
    # worker.run spans parent on it) → supervisor.restart_gap spans for
    # the dead time between attempts — the coordinated-restart cost on
    # the trace's critical path.
    tracer = tracing.Tracer(writer, service="supervisor")
    flightrec = tracing.FlightRecorder(
        512, dump_dir=ckpt_dir or args.workdir, tracer=tracer).attach(writer)
    flightrec.install_sigusr1()
    root = tracer.start("supervisor.run", None, procs=args.procs,
                        command=" ".join(cmd)[:200])

    env = build_env()
    breaker = cluster.GangBreaker(args.crash_loop_threshold)
    cur_sizes, cur_batch, cur_accum = parse_training_params(cmd)
    active = args.procs
    rc = 1
    attempt = failures = preemptions = reshards = 0
    prev_end_mono: float | None = None
    try:
        while attempt < args.max_attempts:
            attempt += 1
            if prev_end_mono is not None:
                # The dead time since the previous attempt ended (backoff +
                # relaunch): retroactive, so it lands between the attempts'
                # spans and the restart cost is ON the reconstructed
                # critical path, not an invisible gap.
                tracer.emit_span(
                    "supervisor.restart_gap", root,
                    start_mono=prev_end_mono, end_mono=time.monotonic(),
                    before_attempt=attempt)
            attempt_span = tracer.start(
                "supervisor.attempt", root, attempt=attempt, gang=active)
            env[tracing.TRACE_CTX_ENV] = attempt_span.context().encode()
            print(f"train_cluster: attempt {attempt}/{args.max_attempts} "
                  f"(gang of {active})", file=sys.stderr)
            res = _run_gang_attempt(
                cmd, env, procs=active,
                devices_per_proc=args.devices_per_proc,
                workdir=args.workdir, ckpt_dir=ckpt_dir,
                hb_timeout=args.heartbeat_timeout,
                hb_poll=args.heartbeat_poll,
                startup_grace=args.startup_grace,
                rejoin_timeout_s=rejoin_timeout,
                chaos_tick_s=args.chaos_tick)
            attempt_span.end(
                status="ok" if res.done else f"rc_{res.first_rc}",
                rc=res.first_rc, worker=res.first_worker,
                hung=sorted(res.hung), dropped=sorted(res.dropped))
            prev_end_mono = time.monotonic()
            rc = res.first_rc or 0
            worker = res.first_worker
            # Progress accounting: the failing worker's own heartbeat,
            # pid-scoped to THIS attempt's child so a predecessor's record
            # cannot fake forward progress.
            last_step = None
            if worker is not None and ckpt_dir:
                hb = supervision.read_heartbeat(
                    cluster.heartbeat_path(ckpt_dir, worker, active))
                if hb and hb.get("pid") in (None, res.pids.get(worker)):
                    last_step = hb.get("last_completed_step", hb.get("step"))
            ckpt_step = latest_committed_step(ckpt_dir) if ckpt_dir else None

            if res.done:
                print(f"train_cluster: done (attempt {attempt})", file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=0, classification="done",
                            process_id=0, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                return 0
            hung = worker in res.hung
            if _cancelled or rc in (130, 143):
                print(f"train_cluster: gang cancelled (rc={rc}) — not retrying",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=rc, classification="cancelled",
                            process_id=worker, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                return rc

            if res.dropped:
                # Permanent worker loss (drop_worker chaos or rejoin timeout):
                # the gang-level rc-84 path. Refit the mesh to the survivors
                # and relaunch smaller — topology change, not failure, so no
                # attempt is consumed and the breaker streak never feeds.
                survivors = active - len(res.dropped)
                reshards += 1
                attempt -= 1
                for w in res.dropped:
                    breaker.record(w, rc=rc, last_step=last_step,
                                   ckpt_step=ckpt_step, transient=True)
                if survivors < 1:
                    print("train_cluster: every worker dropped — giving up",
                          file=sys.stderr)
                    return rc or 1
                try:
                    refit = cluster.decide_refit(
                        cur_sizes, cur_batch, cur_accum,
                        process_count=survivors,
                        devices_per_proc=args.devices_per_proc)
                except cluster.ClusterSpecError as e:
                    print(f"train_cluster: {e} — giving up", file=sys.stderr)
                    return rc or 1
                if not refit.batch_preserved:
                    print("train_cluster: WARNING — could not preserve the "
                          f"effective batch across {_fmt_axes(cur_sizes)} -> "
                          f"{_fmt_axes(refit.sizes)}", file=sys.stderr)
                env[supervision.ELASTIC_OVERRIDES_ENV] = ",".join(refit.overrides)
                print(f"train_cluster: gang refit #{reshards} — workers "
                      f"{sorted(res.dropped)} lost, {active} -> {survivors} "
                      f"processes ({refit.n_devices} devices), mesh "
                      f"{_fmt_axes(cur_sizes)} -> {_fmt_axes(refit.sizes)}, "
                      f"global_batch {cur_batch} -> {refit.global_batch}, "
                      f"grad_accum {cur_accum} -> {refit.grad_accum} — "
                      "relaunching immediately", file=sys.stderr)
                writer.emit(telemetry.KIND_MESH_RESIZED,
                            attempt=attempt + 1, rc=rc, reshards=reshards,
                            from_axes=dict(cur_sizes), to_axes=dict(refit.sizes),
                            visible_devices=refit.n_devices,
                            process_count=survivors,
                            dropped_workers=sorted(res.dropped),
                            global_batch=refit.global_batch,
                            grad_accum=refit.grad_accum,
                            effective_batch_preserved=refit.batch_preserved,
                            overrides=" ".join(refit.overrides),
                            last_step=last_step, ckpt_step=ckpt_step)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="gang_refit", reshards=reshards,
                            process_id=worker, process_count=survivors,
                            last_step=last_step, ckpt_step=ckpt_step)
                cur_sizes, cur_batch, cur_accum = (
                    refit.sizes, refit.global_batch, refit.grad_accum)
                active = survivors
                if reshards >= args.max_reshards:
                    print("train_cluster: topology churn exceeded "
                          f"--max-reshards={args.max_reshards} — giving up",
                          file=sys.stderr)
                    return rc
                continue

            if rc == supervision.GRACEFUL_PREEMPT_RC:
                # The FIRST exit was already rc 83 — the whole gang was
                # preempted externally (our own coordinated shutdown only
                # SIGTERMs peers AFTER a nonzero root cause, so it cannot
                # produce an 83-first gang).
                preemptions += 1
                attempt -= 1
                print(f"train_cluster: gang preempted (rc={rc}, "
                      f"#{preemptions}) — relaunching immediately",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="preempted", preemptions=preemptions,
                            process_id=worker, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                if preemptions >= args.max_preemptions:
                    print("train_cluster: preemption churn exceeded "
                          f"--max-preemptions={args.max_preemptions} — giving "
                          "up", file=sys.stderr)
                    return rc
                continue

            if rc == supervision.ELASTIC_RESHARD_RC:
                # A child could not build its mesh on the devices it saw
                # (child-led elastic, e.g. a drop_devices drill inside the
                # gang). Refit over the reported device set at the SAME
                # process count; the gang-shrink path above handles lost
                # workers.
                report = supervision.read_device_report(ckpt_dir) \
                    if ckpt_dir else None
                visible = (report or {}).get("visible_devices")
                if not visible:
                    failures += 1
                    print(f"train_cluster: attempt {attempt} exited rc={rc} "
                          "(elastic) but left no device report — treating as "
                          "a plain failure", file=sys.stderr)
                    writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                                attempt=attempt, rc=rc,
                                classification="elastic_no_report",
                                process_id=worker, process_count=active,
                                last_step=last_step, ckpt_step=ckpt_step)
                    if worker is not None and breaker.record(
                            worker, rc=rc, last_step=last_step,
                            ckpt_step=ckpt_step):
                        print("train_cluster: CRASH LOOP — not retrying",
                              file=sys.stderr)
                        return rc
                    continue
                reshards += 1
                attempt -= 1
                try:
                    fitted = supervision.fit_axis_sizes(cur_sizes, int(visible))
                except ValueError as e:
                    print(f"train_cluster: no mesh fits {visible} devices "
                          f"({e}) — giving up", file=sys.stderr)
                    return rc
                old_dp = cur_sizes.get("data", 1)
                new_batch, new_accum, preserved = (cur_batch, cur_accum, False)
                if old_dp > 0:
                    new_batch, new_accum, preserved = \
                        supervision.rescale_for_devices(
                            cur_batch, cur_accum, old_dp, fitted.get("data", 1))
                if not preserved:
                    new_batch, new_accum = cur_batch, cur_accum
                overrides = [f"mesh.{a}={v}" for a, v in fitted.items()]
                overrides.append("checkpoint.allow_reshard=true")
                if preserved:
                    overrides += [f"data.global_batch_size={new_batch}",
                                  f"train.grad_accum_steps={new_accum}"]
                env[supervision.ELASTIC_OVERRIDES_ENV] = ",".join(overrides)
                print(f"train_cluster: elastic reshard #{reshards} (rc={rc}) — "
                      f"mesh {_fmt_axes(cur_sizes)} -> {_fmt_axes(fitted)} on "
                      f"{visible} devices — relaunching immediately",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_MESH_RESIZED,
                            attempt=attempt + 1, rc=rc, reshards=reshards,
                            from_axes=dict(cur_sizes), to_axes=dict(fitted),
                            visible_devices=int(visible), process_count=active,
                            global_batch=new_batch, grad_accum=new_accum,
                            effective_batch_preserved=preserved,
                            overrides=" ".join(overrides),
                            last_step=last_step, ckpt_step=ckpt_step)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="elastic_reshard", reshards=reshards,
                            process_id=worker, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                cur_sizes, cur_batch, cur_accum = fitted, new_batch, new_accum
                if reshards >= args.max_reshards:
                    print("train_cluster: topology churn exceeded "
                          f"--max-reshards={args.max_reshards} — giving up",
                          file=sys.stderr)
                    return rc
                continue

            if rc == supervision.ANOMALY_ESCALATION_RC:
                failures += 1
                print(f"train_cluster: attempt {attempt} exited rc={rc} "
                      f"(persistent_anomaly on worker {worker}; "
                      f"last_step={last_step}, ckpt_step={ckpt_step})",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=rc,
                            classification="persistent_anomaly",
                            process_id=worker, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                if worker is not None:
                    breaker.record(worker, rc=rc, last_step=last_step,
                                   ckpt_step=ckpt_step, transient=True)
                if attempt < args.max_attempts:
                    delay = supervision.backoff_seconds(
                        failures, base=args.retry_sleep, cap=args.backoff_max,
                        jitter=args.jitter)
                    print(f"train_cluster: backing off {delay:.1f}s",
                          file=sys.stderr)
                    time.sleep(delay)
                continue

            if worker is not None and not hung and llc.is_bind_failure(
                    llc.log_tail(llc.log_path(args.workdir, worker))):
                # The coordinator lost the free-port bind race at boot: pure
                # launch-infrastructure noise, not a training failure —
                # relaunch on a fresh port (chosen per attempt) for free.
                attempt -= 1
                print(f"train_cluster: worker {worker} lost the port-bind "
                      "race — relaunching the gang on a fresh port",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="port_race",
                            process_id=worker, process_count=active,
                            last_step=last_step, ckpt_step=ckpt_step)
                continue

            failures += 1
            classification = "hung" if hung else "crashed"
            print(f"train_cluster: attempt {attempt} exited rc={rc} "
                  f"({classification} on worker {worker}, "
                  f"last_step={last_step}, ckpt_step={ckpt_step})",
                  file=sys.stderr)
            writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                        attempt=attempt, rc=rc, classification=classification,
                        hung=hung, process_id=worker, process_count=active,
                        last_step=last_step, ckpt_step=ckpt_step)
            # Supervisor-observed crash/hang: dump the flight recorder — the
            # ring holds the attempt/restart-gap spans and the attempt
            # events leading to this fault, plus the open supervisor.run.
            flightrec.dump(f"worker {worker} {classification} (rc={rc})")
            if worker is not None and breaker.record(
                    worker, rc=rc, last_step=last_step, ckpt_step=ckpt_step,
                    hung=hung):
                report = breaker.report(worker)
                print(f"train_cluster: CRASH LOOP on worker {worker} — "
                      "deterministic failure, not retrying:\n"
                      + json.dumps(report, indent=2), file=sys.stderr)
                writer.emit(telemetry.KIND_CRASH_LOOP, **report)
                return rc
            if attempt < args.max_attempts:
                delay = supervision.backoff_seconds(
                    failures, base=args.retry_sleep, cap=args.backoff_max,
                    jitter=args.jitter)
                print(f"train_cluster: backing off {delay:.1f}s",
                      file=sys.stderr)
                time.sleep(delay)
        return rc
    finally:
        # Every exit path (done, cancelled, crash loop, churn caps)
        # closes the gang's root span; a SIGKILLed supervisor leaves
        # it open for the flight recorder's open-span snapshot.
        root.end(status="ok" if rc == 0 else f"rc_{rc}",
                 attempts=attempt, failures=failures,
                 reshards=reshards, preemptions=preemptions)


if __name__ == "__main__":
    sys.exit(main())
