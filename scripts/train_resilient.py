#!/usr/bin/env python
"""Relaunch-on-failure wrapper: bounded restarts around a training command.

The framework's checkpoint contract (auto-restore latest on start, exact
iterator/RNG resume) makes relaunching the whole process a correct — and
on some hosts the only — recovery from infrastructure failures:
preemptions, killed workers, and the intermittent XLA:CPU
collective-rendezvous freeze on oversubscribed virtual-device hosts
(core/platform.py). This wrapper turns that contract into a one-liner:

    python scripts/train_resilient.py --max-attempts 25 -- \\
        python train.py --config configs/bert_base_mlm.yaml \\
        --set checkpoint.directory=/tmp/run_ck \\
        --set checkpoint.save_interval_steps=500

Behavior:
  * Runs the command after ``--``; exit 0 stops the loop (done).
  * Any non-zero exit relaunches after ``--retry-sleep`` seconds, up to
    ``--max-attempts`` total attempts; the final rc is propagated.
  * For CPU-mesh runs (JAX_PLATFORMS=cpu) it lowers the XLA:CPU
    collective terminate timeout so a frozen collective dies in minutes
    instead of hanging a round — the relaunch + auto-restore then makes
    the freeze a bounded restart. User-provided XLA_FLAGS values win.
  * Warns when the command line carries no checkpoint.directory: without
    checkpoints every relaunch restarts from step 0.

The MoE trained-to-metric artifact (RESULTS.md round 4) is the
reference run for this recovery shape: a freeze mid-run cost one
bounded restart and the resumed trajectory was bit-exact.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core.platform import (  # noqa: E402
    FAST_FAIL_COLLECTIVE_FLAGS,
    with_cpu_collective_timeouts,
)


def build_env(base: dict | None = None) -> dict:
    """Fast-fail rendezvous tuning for CPU-mesh runs — the shared flag
    table from core/platform.py with the relaunch-loop values; user-set
    XLA_FLAGS values win."""
    env = dict(os.environ if base is None else base)
    if env.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        env["XLA_FLAGS"] = with_cpu_collective_timeouts(
            env.get("XLA_FLAGS", ""), table=FAST_FAIL_COLLECTIVE_FLAGS)
    return env


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--max-attempts", type=int, default=10)
    parser.add_argument("--retry-sleep", type=float, default=5.0)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command after --")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (put it after `--`)")
    if args.max_attempts < 1:
        parser.error("--max-attempts must be >= 1")
    explicit_off = any(a.rstrip().endswith("checkpoint.directory=")
                       for a in cmd)
    has_dir = any("checkpoint.directory=" in a
                  and not a.rstrip().endswith("checkpoint.directory=")
                  for a in cmd)
    # A --config YAML may enable checkpointing itself (all shipped
    # configs do) — but a user YAML may also leave it disabled, so parse
    # the YAML instead of assuming (ADVICE r4). Unreadable/odd YAMLs get
    # the benefit of the doubt (the trainer will fail loudly on them).
    config_path = None
    for i, a in enumerate(cmd):
        if a == "--config" and i + 1 < len(cmd):
            config_path = cmd[i + 1]
        elif a.startswith("--config="):
            config_path = a.split("=", 1)[1]
    config_has_dir = False
    if config_path is not None:
        config_has_dir = True  # assume-on unless we can prove otherwise
        try:
            import yaml
            with open(config_path) as f:
                doc = yaml.safe_load(f) or {}
            config_has_dir = bool(
                (doc.get("checkpoint") or {}).get("directory"))
        except Exception:
            pass
    if explicit_off or (not has_dir and not config_has_dir):
        print("train_resilient: WARNING — no checkpoint.directory in the "
              "command; every relaunch will restart from step 0",
              file=sys.stderr)
    env = build_env()
    rc = 1
    for attempt in range(1, args.max_attempts + 1):
        print(f"train_resilient: attempt {attempt}/{args.max_attempts}",
              file=sys.stderr)
        rc = subprocess.run(cmd, env=env).returncode
        if rc < 0:
            # Child died to a signal (e.g. the XLA terminate timeout's
            # SIGABRT → -6): report the shell's 128+signal convention so
            # outer automation can classify the failure (134 = SIGABRT).
            rc = 128 - rc
        if rc in (130, 143):
            # SIGINT/SIGTERM are CANCELLATION, not infrastructure
            # failure — honor the operator instead of relaunching.
            print(f"train_resilient: child cancelled (rc={rc}) — "
                  "not retrying", file=sys.stderr)
            return rc
        if rc == 0:
            print(f"train_resilient: done (attempt {attempt})",
                  file=sys.stderr)
            return 0
        print(f"train_resilient: attempt {attempt} exited rc={rc}",
              file=sys.stderr)
        if attempt < args.max_attempts:
            time.sleep(args.retry_sleep)
    return rc


if __name__ == "__main__":
    sys.exit(main())
