#!/usr/bin/env python
"""Self-healing supervisor: watchdog + bounded relaunch around training.

The framework's checkpoint contract (auto-restore latest on start, exact
iterator/RNG resume, integrity-manifested saves) makes relaunching the
whole process a correct recovery from infrastructure failures: preemptions,
killed workers, wedged infeed threads, and the intermittent XLA:CPU
collective-rendezvous freeze on oversubscribed virtual-device hosts
(core/platform.py). This wrapper turns that contract into supervision:

    python scripts/train_resilient.py --max-attempts 25 \\
        --heartbeat-timeout 120 -- \\
        python train.py --config configs/bert_base_mlm.yaml \\
        --set checkpoint.directory=/tmp/run_ck \\
        --set checkpoint.save_interval_steps=500

Behavior (exit-code contract in docs/RESILIENCE.md):
  * exit 0 stops the loop (done); any other rc is classified first.
  * 130/143 (SIGINT/SIGTERM death) is operator CANCELLATION — never
    relaunched. SIGTERM/SIGINT sent to the supervisor itself is forwarded
    to the child and also treated as cancellation.
  * GRACEFUL_PREEMPT_RC (83) means the child honored a SIGTERM: step
    finished, checkpoint saved — relaunched immediately WITHOUT consuming
    an attempt (preemption is scheduling, not failure).
  * ELASTIC_RESHARD_RC (84) means the child's configured mesh no longer
    fits the visible device set (a slice was lost — or came back). The
    supervisor reads the child's device report, fits the largest valid
    mesh onto what remains (supervision.fit_axis_sizes), rescales
    batch/grad-accum so the EFFECTIVE batch and LR schedule are
    preserved (supervision.rescale_for_devices), and relaunches with
    ``checkpoint.allow_reshard=true`` — the restore resharding the
    checkpoint onto the new mesh (ckpt/reshard.py). Like preemption this
    consumes NO attempt and never feeds the crash-loop breaker; it is
    bounded separately by ``--max-reshards``. The refit reaches the
    child via the DTF_ELASTIC_OVERRIDES env var (cli/train.py applies it
    after its own --set overrides). A ``mesh_resized`` telemetry event
    records each transition.
  * Heartbeat watchdog: when the run's heartbeat file (written by
    train/hooks.HeartbeatHook under checkpoint.directory) goes stale past
    ``--heartbeat-timeout``, the child is SIGKILLed instead of waiting for
    an XLA collective timeout; the kill counts as a transient hang.
  * Other failures relaunch after exponential backoff with jitter, up to
    ``--max-attempts``; the final rc is propagated.
  * Crash-loop breaker: the same rc at the same step with no checkpoint
    progress, ``--crash-loop-threshold`` attempts in a row, is a
    deterministic bug — the loop stops early with a structured report
    instead of burning the budget (core/supervision.py).
  * Every attempt is emitted as a ``dtf-telemetry/1`` JSONL event
    (``supervisor_attempt``) to ``<ckpt_dir>/supervisor_events.jsonl`` so
    recovery activity joins the run's telemetry
    (scripts/analyze_trace.py prints it in run summaries).
  * For CPU-mesh runs (JAX_PLATFORMS=cpu) the XLA:CPU collective terminate
    timeout is lowered so a frozen collective dies in minutes; user-set
    XLA_FLAGS win.
  * Warns when neither the command line nor its --config YAML carries a
    checkpoint.directory: without checkpoints every relaunch restarts from
    step 0.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distributed_tensorflow_framework_tpu.core import (  # noqa: E402
    faults,
    supervision,
    telemetry,
    tracing,
)
from distributed_tensorflow_framework_tpu.core.platform import (  # noqa: E402
    FAST_FAIL_COLLECTIVE_FLAGS,
    with_cpu_collective_timeouts,
)


def _load_manifest_module():
    """ckpt/manifest.py loaded directly from its file: importing it through
    the ckpt package would pull in jax + orbax (checkpoint.py), a
    multi-second tax on every supervisor start that the stdlib-only
    manifest layer exists to avoid."""
    import importlib.util

    path = (pathlib.Path(__file__).resolve().parent.parent
            / "distributed_tensorflow_framework_tpu" / "ckpt" / "manifest.py")
    spec = importlib.util.spec_from_file_location("_dtf_ckpt_manifest", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


latest_committed_step = _load_manifest_module().latest_committed_step


def build_env(base: dict | None = None) -> dict:
    """Fast-fail rendezvous tuning for CPU-mesh runs — the shared flag
    table from core/platform.py with the relaunch-loop values; user-set
    XLA_FLAGS values win."""
    env = dict(os.environ if base is None else base)
    if env.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        env["XLA_FLAGS"] = with_cpu_collective_timeouts(
            env.get("XLA_FLAGS", ""), table=FAST_FAIL_COLLECTIVE_FLAGS)
    return env


def find_checkpoint_dir(cmd: list[str]) -> tuple[str | None, bool]:
    """(checkpoint directory, checkpointing-enabled) for a training command.

    Command-line ``checkpoint.directory=`` values win (last occurrence, the
    --set override semantics); otherwise the ``--config`` YAML is parsed —
    not assumed — since a user YAML may leave checkpointing disabled
    (ADVICE r4). An unreadable/odd YAML gets the benefit of the doubt
    (enabled=True, directory unknown): the trainer will fail loudly on it,
    and crying wolf here trains operators to ignore the warning.
    """
    import re

    directory: str | None = None
    explicit = False
    for arg in cmd:
        if "checkpoint.directory=" in arg:
            explicit = True
            raw = arg.split("checkpoint.directory=", 1)[1]
            # The override may ride inside a larger token (a `python -c`
            # program, a shell-quoted --set) — take the value up to the
            # first quote/whitespace/comma.
            directory = re.split(r"[\s'\",]", raw, 1)[0]
    if explicit:
        return (directory or None), bool(directory)
    config_path = None
    for i, arg in enumerate(cmd):
        if arg == "--config" and i + 1 < len(cmd):
            config_path = cmd[i + 1]
        elif arg.startswith("--config="):
            config_path = arg.split("=", 1)[1]
    if config_path is None:
        return None, False
    try:
        import yaml

        with open(config_path) as fh:
            doc = yaml.safe_load(fh) or {}
        directory = (doc.get("checkpoint") or {}).get("directory") or None
        return directory, bool(directory)
    except Exception:
        return None, True  # benefit of the doubt


def parse_training_params(cmd: list[str]) -> tuple[dict, int, int]:
    """(mesh axis sizes, global batch, grad accum) as the child sees them.

    Same philosophy as ``find_checkpoint_dir``: regex over the raw command
    tokens (an override may ride inside a ``python -c`` program string),
    with the ``--config`` YAML as fallback and the config-dataclass
    defaults (``data=-1``, batch 64, accum 1) underneath. Command-line
    values win over YAML; the LAST occurrence of an override wins, like
    --set semantics.
    """
    import re

    sizes = {a: (-1 if a == "data" else 1)
             for a in supervision.MESH_AXIS_ORDER}
    batch, accum = 64, 1
    config_path = None
    for i, tok in enumerate(cmd):
        if tok == "--config" and i + 1 < len(cmd):
            config_path = cmd[i + 1]
        elif tok.startswith("--config="):
            config_path = tok.split("=", 1)[1]
    if config_path:
        try:
            import yaml

            with open(config_path) as fh:
                doc = yaml.safe_load(fh) or {}
            for a, v in (doc.get("mesh") or {}).items():
                if a in sizes:
                    sizes[a] = int(v)
            batch = int((doc.get("data") or {}).get(
                "global_batch_size", batch))
            accum = int((doc.get("train") or {}).get(
                "grad_accum_steps", accum))
        except Exception:
            pass
    text = " ".join(cmd)
    for a in sizes:
        for m in re.finditer(rf"mesh\.{a}=(-?\d+)", text):
            sizes[a] = int(m.group(1))
    for m in re.finditer(r"data\.global_batch_size=(\d+)", text):
        batch = int(m.group(1))
    for m in re.finditer(r"train\.grad_accum_steps=(\d+)", text):
        accum = int(m.group(1))
    return sizes, batch, accum


def _fmt_axes(axes: dict) -> str:
    parts = [f"{a}:{v}" for a, v in axes.items() if int(v) != 1]
    return "{" + ", ".join(parts) + "}" if parts else "{1 device}"


# -- cancellation forwarding ----------------------------------------------
_child: subprocess.Popen | None = None
_cancelled = False


def _forward_signal(signum, frame):
    global _cancelled
    _cancelled = True
    if _child is not None and _child.poll() is None:
        _child.send_signal(signum)


def _run_attempt(cmd, env, *, hb_path: str | None, hb_timeout: float,
                 hb_poll: float, startup_grace: float) -> tuple[int, bool, int]:
    """Run the child under the heartbeat watchdog; (rc, hung, child pid)."""
    global _child
    _child = child = subprocess.Popen(cmd, env=env)
    start = time.monotonic()
    hung = False
    watch = hb_path is not None and hb_timeout > 0
    while True:
        try:
            rc = child.wait(timeout=hb_poll if watch else None)
            break
        except subprocess.TimeoutExpired:
            pass
        age = supervision.heartbeat_age_s(hb_path, pid=child.pid)
        stale = age is not None and age > hb_timeout
        no_start = (age is None and startup_grace > 0
                    and time.monotonic() - start > startup_grace)
        if stale or no_start:
            why = (f"heartbeat stale ({age:.0f}s > {hb_timeout:.0f}s budget)"
                   if stale else
                   f"no heartbeat within {startup_grace:.0f}s startup grace")
            print(f"train_resilient: {why} — killing hung child "
                  f"pid={child.pid}", file=sys.stderr)
            child.kill()
            rc = child.wait()
            hung = True
            break
    _child = None
    return rc, hung, child.pid


def main(argv=None) -> int:
    global _cancelled
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--max-attempts", type=int, default=10)
    parser.add_argument("--retry-sleep", type=float, default=5.0,
                        help="backoff BASE seconds (doubles per consecutive "
                             "failure, jittered)")
    parser.add_argument("--backoff-max", type=float, default=120.0,
                        help="backoff ceiling in seconds")
    parser.add_argument("--jitter", type=float, default=0.5,
                        help="fractional backoff jitter (0 disables)")
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="kill the child when its heartbeat file is "
                             "older than this many seconds (0 disables the "
                             "watchdog)")
    parser.add_argument("--heartbeat-poll", type=float, default=2.0,
                        help="watchdog poll interval in seconds")
    parser.add_argument("--heartbeat-file", default=None,
                        help="heartbeat path (default: "
                             "<checkpoint.directory>/heartbeat.json)")
    parser.add_argument("--startup-grace", type=float, default=0.0,
                        help="kill the child when NO heartbeat appears "
                             "within this many seconds of launch (0 "
                             "disables; compile time counts against it)")
    parser.add_argument("--crash-loop-threshold", type=int, default=3,
                        help="stop after this many consecutive identical "
                             "no-progress failures (0 disables the breaker)")
    parser.add_argument("--max-preemptions", type=int, default=50,
                        help="safety bound on graceful-preemption "
                             "relaunches (they never consume attempts)")
    parser.add_argument("--max-reshards", type=int, default=8,
                        help="safety bound on elastic mesh-refit "
                             "relaunches, rc 84 (they never consume "
                             "attempts)")
    parser.add_argument("--events", default=None,
                        help="supervisor telemetry JSONL (default: "
                             "<checkpoint.directory>/supervisor_events"
                             ".jsonl; '-' disables)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command after --")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (put it after `--`)")
    if args.max_attempts < 1:
        parser.error("--max-attempts must be >= 1")

    ckpt_dir, ckpt_enabled = find_checkpoint_dir(cmd)
    if not ckpt_enabled:
        print("train_resilient: WARNING — no checkpoint.directory in the "
              "command; every relaunch will restart from step 0",
              file=sys.stderr)
    hb_path = args.heartbeat_file or (
        os.path.join(ckpt_dir, "heartbeat.json") if ckpt_dir else None)
    if args.heartbeat_timeout > 0 and hb_path is None:
        print("train_resilient: WARNING — --heartbeat-timeout set but no "
              "heartbeat path is known (need checkpoint.directory or "
              "--heartbeat-file); watchdog disabled", file=sys.stderr)

    events_path = args.events
    if events_path is None and ckpt_dir:
        events_path = os.path.join(ckpt_dir, "supervisor_events.jsonl")
    writer = telemetry.TelemetryWriter(
        None if events_path in (None, "-") else events_path)
    writer.emit_run_meta(
        argv=[sys.argv[0]], supervisor=True, command=" ".join(cmd),
        max_attempts=args.max_attempts,
        heartbeat_timeout_s=args.heartbeat_timeout,
        checkpoint_dir=ckpt_dir or "",
    )

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _forward_signal)
        except (ValueError, OSError):  # non-main thread (tests importing us)
            pass

    env = build_env()
    # Same trace shape as the gang supervisor (scripts/train_cluster.py):
    # supervisor.run root → supervisor.attempt per attempt, the attempt's
    # context handed to the child via DTF_TRACE_CTX so its worker.run
    # span (train/loop.py) parents on it; restart gaps are retroactive
    # spans between attempts.
    tracer = tracing.Tracer(writer, service="supervisor")
    flightrec = tracing.FlightRecorder(
        512, dump_dir=ckpt_dir or None, tracer=tracer).attach(writer)
    flightrec.install_sigusr1()
    root = tracer.start("supervisor.run", None,
                        command=" ".join(cmd)[:200])
    breaker = supervision.CrashLoopBreaker(args.crash_loop_threshold)
    rc = 1
    attempt = failures = preemptions = reshards = 0
    prev_end_mono: float | None = None
    # Elastic state: what the child's mesh/batch currently are (command
    # line + any refit overrides already applied), and the device count
    # a drop_devices drill has masked the child to (None = unmasked).
    cur_sizes, cur_batch, cur_accum = parse_training_params(cmd)
    masked_devices: int | None = None
    try:
        while attempt < args.max_attempts:
            attempt += 1
            if prev_end_mono is not None:
                # Retroactive span for the dead time between attempts (backoff
                # + relaunch latency) so the restart gap lands on the trace's
                # critical path instead of vanishing between siblings.
                tracer.emit_span("supervisor.restart_gap", root,
                                 start_mono=prev_end_mono,
                                 end_mono=time.monotonic(),
                                 before_attempt=attempt)
            attempt_span = tracer.start("supervisor.attempt", root,
                                        attempt=attempt)
            env[tracing.TRACE_CTX_ENV] = attempt_span.context().encode()
            # The supervisor-side fault point: drop_devices drills fire here,
            # keyed on the 1-based attempt ordinal, and shrink/grow the
            # child's visible device set (CPU stand-in for losing a slice —
            # on real TPUs the devices disappear by themselves).
            for fault in faults.fire("relaunch", step=attempt):
                if fault.kind != "drop_devices":
                    continue
                masked_devices = fault.devices
                if env.get("JAX_PLATFORMS", "").split(",")[0] != "cpu":
                    print("train_resilient: WARNING — drop_devices masks the "
                          "virtual-CPU host device count; JAX_PLATFORMS is "
                          "not cpu, the mask may have no effect",
                          file=sys.stderr)
                env["XLA_FLAGS"] = supervision.mask_host_device_count(
                    env.get("XLA_FLAGS", ""), masked_devices)
                print(f"train_resilient: drop_devices drill — child device "
                      f"set masked to {masked_devices}", file=sys.stderr)
            print(f"train_resilient: attempt {attempt}/{args.max_attempts}",
                  file=sys.stderr)
            rc, hung, child_pid = _run_attempt(
                cmd, env, hb_path=hb_path, hb_timeout=args.heartbeat_timeout,
                hb_poll=args.heartbeat_poll, startup_grace=args.startup_grace)
            if rc < 0:
                # Child died to a signal (e.g. the XLA terminate timeout's
                # SIGABRT → -6): report the shell's 128+signal convention so
                # outer automation can classify the failure (134 = SIGABRT).
                rc = 128 - rc
            attempt_span.end(status="ok" if rc == 0 else f"rc_{rc}",
                             rc=rc, hung=hung)
            prev_end_mono = time.monotonic()
            # Progress accounting for the crash-loop breaker: the heartbeat
            # record only counts when the just-dead child wrote it (pid match);
            # a predecessor's stale record would fake forward progress.
            hb = supervision.read_heartbeat(hb_path) if hb_path else None
            last_step = None
            if hb and hb.get("pid") in (None, child_pid):
                last_step = hb.get("last_completed_step", hb.get("step"))
            ckpt_step = latest_committed_step(ckpt_dir) if ckpt_dir else None

            if rc == 0:
                print(f"train_resilient: done (attempt {attempt})",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=0, classification="done",
                            last_step=last_step, ckpt_step=ckpt_step)
                return 0
            if _cancelled or rc in (130, 143):
                # SIGINT/SIGTERM death — or a signal we forwarded ourselves —
                # is CANCELLATION, not infrastructure failure: honor the
                # operator instead of relaunching. (A supervisor-level SIGTERM
                # also ends the loop when the child preempted gracefully: the
                # whole tree is being evicted, relaunching would fight the
                # scheduler.)
                print(f"train_resilient: child cancelled (rc={rc}) — "
                      "not retrying", file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=rc, classification="cancelled",
                            last_step=last_step, ckpt_step=ckpt_step)
                return rc
            if rc == supervision.GRACEFUL_PREEMPT_RC:
                preemptions += 1
                attempt -= 1  # graceful preemption never consumes the budget
                print(f"train_resilient: graceful preemption (rc={rc}, "
                      f"#{preemptions}) — relaunching immediately",
                      file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="preempted", preemptions=preemptions,
                            last_step=last_step, ckpt_step=ckpt_step)
                if preemptions >= args.max_preemptions:
                    print("train_resilient: preemption churn exceeded "
                          f"--max-preemptions={args.max_preemptions} — giving "
                          "up", file=sys.stderr)
                    return rc
                continue

            if rc == supervision.ELASTIC_RESHARD_RC:
                # The child could not build its mesh on the devices it saw —
                # a topology change, not a failure. Refit and relaunch
                # without consuming an attempt or feeding the breaker.
                report = supervision.read_device_report(ckpt_dir) if ckpt_dir \
                    else None
                visible = (report or {}).get("visible_devices") or masked_devices
                if not visible:
                    failures += 1
                    print(f"train_resilient: attempt {attempt} exited rc={rc} "
                          "(elastic) but left no device report — treating as a "
                          "plain failure", file=sys.stderr)
                    writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                                attempt=attempt, rc=rc,
                                classification="elastic_no_report",
                                last_step=last_step, ckpt_step=ckpt_step)
                    if breaker.record(rc=rc, last_step=last_step,
                                      ckpt_step=ckpt_step):
                        print("train_resilient: CRASH LOOP — not retrying",
                              file=sys.stderr)
                        return rc
                    continue
                reshards += 1
                attempt -= 1  # topology changes never consume the budget
                breaker.record(rc=rc, last_step=last_step, ckpt_step=ckpt_step,
                               transient=True)
                try:
                    fitted = supervision.fit_axis_sizes(cur_sizes, int(visible))
                except ValueError as e:
                    print(f"train_resilient: no mesh fits {visible} devices "
                          f"({e}) — giving up", file=sys.stderr)
                    return rc
                old_dp = cur_sizes.get("data", 1)
                new_batch, new_accum, preserved = (cur_batch, cur_accum, False)
                if old_dp > 0:
                    new_batch, new_accum, preserved = \
                        supervision.rescale_for_devices(
                            cur_batch, cur_accum, old_dp, fitted.get("data", 1))
                if not preserved:
                    print("train_resilient: WARNING — could not preserve the "
                          f"effective batch across {_fmt_axes(cur_sizes)} -> "
                          f"{_fmt_axes(fitted)}; keeping "
                          f"global_batch={cur_batch}, accum={cur_accum}",
                          file=sys.stderr)
                    new_batch, new_accum = cur_batch, cur_accum
                overrides = [f"mesh.{a}={v}" for a, v in fitted.items()]
                overrides.append("checkpoint.allow_reshard=true")
                if preserved:
                    overrides += [f"data.global_batch_size={new_batch}",
                                  f"train.grad_accum_steps={new_accum}"]
                env[supervision.ELASTIC_OVERRIDES_ENV] = ",".join(overrides)
                print(f"train_resilient: elastic reshard #{reshards} (rc={rc}) "
                      f"— mesh {_fmt_axes(cur_sizes)} -> {_fmt_axes(fitted)} on "
                      f"{visible} devices, global_batch {cur_batch} -> "
                      f"{new_batch}, grad_accum {cur_accum} -> {new_accum} — "
                      "relaunching immediately", file=sys.stderr)
                writer.emit(telemetry.KIND_MESH_RESIZED,
                            attempt=attempt + 1, rc=rc, reshards=reshards,
                            from_axes=dict(cur_sizes), to_axes=dict(fitted),
                            visible_devices=int(visible),
                            global_batch=new_batch, grad_accum=new_accum,
                            effective_batch_preserved=preserved,
                            overrides=" ".join(overrides),
                            last_step=last_step, ckpt_step=ckpt_step)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt + 1, rc=rc,
                            classification="elastic_reshard", reshards=reshards,
                            last_step=last_step, ckpt_step=ckpt_step)
                cur_sizes, cur_batch, cur_accum = fitted, new_batch, new_accum
                if reshards >= args.max_reshards:
                    print("train_resilient: topology churn exceeded "
                          f"--max-reshards={args.max_reshards} — giving up",
                          file=sys.stderr)
                    return rc
                continue

            if rc == supervision.ANOMALY_ESCALATION_RC:
                # The child's IN-PROCESS recovery ladder (train/anomaly.py)
                # exhausted max_rollbacks on one incident: a poisoned data
                # region or deterministic numeric bug, already diagnosed and
                # telemetered by the child. Relaunching from the checkpoint is
                # still the right move (the restored iterator has advanced past
                # part of the region), but this is NOT a crash signature — the
                # breaker's streak must not accumulate toward "deterministic
                # bug, stop retrying" on a failure mode the child already
                # classified. Attempts are still consumed (bounded retries).
                failures += 1
                print(f"train_resilient: attempt {attempt} exited rc={rc} "
                      f"(persistent_anomaly — the child exhausted its in-process "
                      f"rollback ladder; last_step={last_step}, "
                      f"ckpt_step={ckpt_step})", file=sys.stderr)
                writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                            attempt=attempt, rc=rc,
                            classification="persistent_anomaly",
                            last_step=last_step, ckpt_step=ckpt_step)
                breaker.record(rc=rc, last_step=last_step, ckpt_step=ckpt_step,
                               transient=True)
                if attempt < args.max_attempts:
                    delay = supervision.backoff_seconds(
                        failures, base=args.retry_sleep, cap=args.backoff_max,
                        jitter=args.jitter)
                    print(f"train_resilient: backing off {delay:.1f}s",
                          file=sys.stderr)
                    time.sleep(delay)
                continue

            failures += 1
            classification = "hung" if hung else "crashed"
            print(f"train_resilient: attempt {attempt} exited rc={rc} "
                  f"({classification}, last_step={last_step}, "
                  f"ckpt_step={ckpt_step})", file=sys.stderr)
            writer.emit(telemetry.KIND_SUPERVISOR_ATTEMPT,
                        attempt=attempt, rc=rc, classification=classification,
                        hung=hung, last_step=last_step, ckpt_step=ckpt_step)
            flightrec.dump(f"child {classification} (rc={rc})",
                           open_spans=tracer.open_spans())
            if breaker.record(rc=rc, last_step=last_step, ckpt_step=ckpt_step,
                              hung=hung):
                report = breaker.report()
                print("train_resilient: CRASH LOOP — deterministic failure, "
                      "not retrying:\n" + json.dumps(report, indent=2),
                      file=sys.stderr)
                writer.emit(telemetry.KIND_CRASH_LOOP, **report)
                return rc
            if attempt < args.max_attempts:
                delay = supervision.backoff_seconds(
                    failures, base=args.retry_sleep, cap=args.backoff_max,
                    jitter=args.jitter)
                print(f"train_resilient: backing off {delay:.1f}s",
                      file=sys.stderr)
                time.sleep(delay)
        return rc
    finally:
        root.end(status="ok" if rc == 0 else f"rc_{rc}",
                 attempts=attempt, failures=failures,
                 reshards=reshards, preemptions=preemptions)


if __name__ == "__main__":
    sys.exit(main())
