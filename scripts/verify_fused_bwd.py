"""On-DEVICE numerics check for the fused streaming backward.

The fused kernel's dk/dv/dbias correctness rests on in-order HBM
flushes of revisited output blocks — a Mosaic behavior CPU interpret
mode cannot exercise (it executes the grid sequentially by
construction). Run THIS before trusting a fused-backward number on a new backend:
it compares fused vs two-pass gradients on the real chip at a streaming
shape and fails loudly on divergence.

Usage (serial, backgrounded per the verify skill):

    python scripts/verify_fused_bwd.py [seq]
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

if os.environ.get("VFB_CPU", "0") not in ("", "0"):
    # CPU dry-run gate: the image's sitecustomize force-selects the axon
    # TPU platform regardless of JAX_PLATFORMS, so validating this
    # script's plumbing without a chip needs the in-process override
    # (and interpret-mode kernels follow automatically).
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

SEQ = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
# Env-tunable so the script itself can be dry-run on CPU interpret mode
# (tiny dims) before a chip window burns time on a plumbing bug.
B = int(os.environ.get("VFB_B", "2"))
H = int(os.environ.get("VFB_H", "4"))
D = int(os.environ.get("VFB_D", "64"))


def main() -> int:
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, SEQ, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, SEQ, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, SEQ, H, D), jnp.bfloat16)
    wk_min = fa.fused_whole_k_min(jnp.bfloat16)
    if SEQ < wk_min and SEQ <= fa.MAX_SEQ_VMEM:
        print(f"seq {SEQ} < fused_whole_k_min(bf16)={wk_min}: "
              f"whole-K two-pass territory, no fused path to verify")
        return 2
    if SEQ <= fa.MAX_SEQ_VMEM:
        # Whole-K takeover band (FUSED_WHOLE_K_MIN ≤ seq ≤ MAX_SEQ_VMEM):
        # the two arms are the fused STREAMING backward vs the WHOLE-K
        # two-pass, whose K-dots accumulate in a different order — expect
        # bf16 reassociation noise (1e-2 class), not the bit-exactness the
        # pure-streaming comparison shows; the 5e-2 gate still separates
        # that from a flush-ordering defect (which is >1e0 when it bites).
        print(f"seq {SEQ}: whole-K takeover band — comparing fused "
              f"streaming vs whole-K two-pass (different accumulation "
              f"order; bf16 reassociation noise expected)")

    def loss(q, k, v):
        out = fa.flash_attention(q, k, v)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    grads = {}
    for fused in (False, True):
        fa.FUSED_BWD = fused
        # Fresh outer trace each arm (the fused decision is read at the
        # custom_vjp layer, outside the inner jit's cache).
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        grads[fused] = [np.asarray(t, np.float32) for t in g]
        # Sync by VALUE (axon rule: never block_until_ready).
        _ = float(grads[fused][0].sum())

    whole_k_band = SEQ <= fa.MAX_SEQ_VMEM
    worst = 0.0
    for name, a, b in zip("qkv", grads[True], grads[False]):
        denom = np.maximum(np.abs(b), 1e-3)
        rel = np.abs(a - b) / denom
        if whole_k_band:
            # The element-wise max over ~B·S·H·D values is an order
            # statistic of the bf16 reassociation-noise tail: it grows
            # with problem size and one unlucky element can fail (or,
            # worse, two cancelling elements can pass) a pair the
            # aggregate numerics contradict. Gate on noise-robust
            # statistics instead — the 99.9th-percentile rel diff and the
            # relative L2 error. A flush-ordering defect moves BOTH by
            # orders of magnitude (>1e0 when it bites); reassociation
            # noise keeps p99.9 in the 1e-2 class and rel L2 well below.
            rel_l2 = float(np.linalg.norm(a - b)
                           / max(float(np.linalg.norm(b)), 1e-30))
            p999 = float(np.percentile(rel, 99.9))
            stat = max(p999, rel_l2)
            print(f"d{name}: fused-vs-two-pass p99.9 rel {p999:.3e}, "
                  f"rel L2 {rel_l2:.3e} (max rel {float(np.max(rel)):.3e} "
                  f"reported, not gated)")
        else:
            # Pure-streaming band: both arms stream with the same
            # accumulation order — bit-exactness is the expectation, so
            # the element-wise max stays the gate.
            stat = float(np.max(rel))
            print(f"d{name}: max rel diff fused-vs-two-pass = {stat:.3e}")
        worst = max(worst, stat)
    if worst > 5e-2:
        print(f"FUSED BWD NUMERICS MISMATCH (worst {worst:.3e}) — do NOT "
              f"use the fused backward (set FLASH_FUSED_BWD=0); flush ordering is "
              f"suspect on this backend/toolchain")
        return 1
    print(f"fused backward matches two-pass on this device "
          f"(worst rel diff {worst:.3e}, seq {SEQ})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
