"""Test harness: 8 virtual CPU devices.

SURVEY.md §4 "Multi-replica without hardware": the TPU analogue of the
reference's fake-cluster-on-localhost trick is
``--xla_force_host_platform_device_count=8`` — real psum/shard_map/pjit
semantics, no TPU required. Env vars MUST be set before jax initializes,
hence this module-level block.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The environment's TPU-tunnel sitecustomize force-sets
# jax_platforms="axon,cpu" via jax.config at interpreter start, which beats
# the env var; override it back to CPU-only before any backend initializes
# so tests never occupy the real chip.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
