"""Test harness: 8 virtual CPU devices.

SURVEY.md §4 "Multi-replica without hardware": the TPU analogue of the
reference's fake-cluster-on-localhost trick is
``--xla_force_host_platform_device_count=8`` — real psum/shard_map/pjit
semantics, no TPU required. Env vars MUST be set before jax initializes,
hence this module-level block.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Rendezvous-timeout defaults: on a 1-core box a scheduling stall would
# otherwise abort multi-device collectives — see core/platform.py.
from distributed_tensorflow_framework_tpu.core.platform import (  # noqa: E402
    with_cpu_collective_timeouts,
)

os.environ["XLA_FLAGS"] = with_cpu_collective_timeouts(_flags)

import jax  # noqa: E402
import pytest  # noqa: E402

# The environment's TPU-tunnel sitecustomize force-sets
# jax_platforms="axon,cpu" via jax.config at interpreter start, which beats
# the env var; override it back to CPU-only before any backend initializes
# so tests never occupy the real chip.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def gang_capability():
    """Gate for tests that need a REAL multi-process jax.distributed gang.

    Stock CPU jaxlib forms the gang (coordinator handshake + global
    device discovery succeed) but rejects any computation spanning
    processes at compile time ("Multiprocess computations aren't
    implemented on the CPU backend"), so every end-to-end gang test
    would fail identically.  Probe once per session and SKIP those
    tests with the probe's evidence — the supervisor/launcher decision
    logic stays covered by the stubbed fast tiers (tests/test_cluster.py,
    tests/test_local_cluster_launcher.py).
    """
    from distributed_tensorflow_framework_tpu.core import cluster

    ok, detail = cluster.probe_gang(procs=2, devices_per_proc=2)
    if not ok:
        reason = ("backend cannot run real multi-process gangs"
                  if cluster.is_gang_unsupported(detail)
                  else "gang probe failed")
        pytest.skip(f"{reason}:\n{detail[-800:]}")


def write_imagenet_records(root, *, split="train", counts=(8, 8),
                           size=(64, 48), label_fn=None):
    """The ONE fabricated ImageNet-layout TFRecord writer for the suite
    (JPEG bytes + 1-based labels; shard naming `<split>-NNNNN-of-NNNNN`).
    ``counts`` gives records per shard file; ``label_fn`` maps the global
    1-based record counter to a label (default: identity-ish n%1000+1).
    Previously three near-identical writers had drifted across test
    files — record-format changes now have a single home."""
    import os

    import numpy as np
    import tensorflow as tf

    label_fn = label_fn or (lambda n: (n % 1000) + 1)
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    n = 0
    files = len(counts)
    for f, per_file in enumerate(counts):
        path = os.path.join(str(root), f"{split}-{f:05d}-of-{files:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                img = rng.integers(0, 255, (*size, 3), dtype=np.uint8)
                encoded = tf.io.encode_jpeg(img).numpy()
                n += 1
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[encoded])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label_fn(n)])),
                }))
                w.write(ex.SerializeToString())
