"""Subprocess worker for the multi-process (DCN-path) test.

SURVEY.md §4 "Multi-process without a cluster": N local processes with
jax.distributed.initialize exercise the cross-host code paths (env-var
topology discovery, per-host data sharding, global-array assembly, psum
across processes) without a real multi-host slice.

Usage: python distributed_worker.py <port> <num_procs> <proc_id> [eval_dir]

With ``eval_dir`` (a directory of ``validation-*`` TFRecords), the worker
additionally runs the EXACT multi-host eval path: hosts hold uneven file
shards, agree on the padded batch count via the process_allgather in
``eval_batches_all_hosts``, and must produce identical full-set metrics
without deadlocking.
"""

import os
import sys


def main() -> int:
    port, num_procs, proc_id = sys.argv[1:4]
    eval_dir = sys.argv[4] if len(sys.argv) > 4 else None
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = num_procs
    os.environ["JAX_PROCESS_ID"] = proc_id
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import initialize_runtime
    from distributed_tensorflow_framework_tpu.train import Trainer

    cfg = load_config(base={
        "name": "mp-lenet",
        "mesh": {"data": -1},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        # 32x32x3 when exercising the TFRecord eval path so the trained
        # params match the eval pipeline's image shape.
        "data": {"name": "synthetic_images", "global_batch_size": 32,
                 "image_size": 32 if eval_dir else 28,
                 "channels": 3 if eval_dir else 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 5, "log_interval": 5, "seed": 0},
    })
    runtime = initialize_runtime(cfg.mesh)
    assert runtime.process_count == int(num_procs), runtime.process_count
    assert runtime.global_device_count == 2 * int(num_procs)

    trainer = Trainer(cfg, runtime)
    metrics = trainer.train()
    # Every process must agree on the (replicated) loss.
    print(f"RESULT process={proc_id} loss={metrics['loss']:.6f}", flush=True)

    if eval_dir:
        from distributed_tensorflow_framework_tpu.core.config import DataConfig

        trainer.config.eval_data = DataConfig(
            name="imagenet", data_dir=eval_dir, global_batch_size=8,
            image_size=32, num_classes=10,
        )
        results = trainer.evaluate()
        print(
            f"EVAL process={proc_id} "
            f"examples={results['eval_examples']:.0f} "
            f"loss={results['eval_loss']:.6f}",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
