"""Fixture config: ``dead_knob`` is neither read nor documented."""


def config_dataclass(cls):
    return cls


@config_dataclass
class TrainConfig:
    alpha: float = 0.1       # read by pkg/train.py and documented
    dead_knob: int = 7       # read nowhere, documented nowhere
