def step(cfg, x):
    return x * cfg.alpha
