"""Fixture config: every knob read and documented."""


def config_dataclass(cls):
    return cls


@config_dataclass
class TrainConfig:
    alpha: float = 0.1
    axis_name: str = "data"   # consumed as a string constant in train.py
