def step(cfg, x):
    del cfg
    return x.sum("axis_name") * x.alpha
