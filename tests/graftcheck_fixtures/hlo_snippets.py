"""Traceable functions for the compiled-HLO audits
(tests/test_graftcheck_hlo.py).

``reshard_bad`` contracts a matmul over a dimension the test shards —
GSPMD must insert an all-reduce to produce the replicated result, and
that compiler-inserted collective (against ZERO jaxpr-declared ones) is
exactly what hlo-reshard-census flags. ``reshard_clean`` is elementwise
over identically-sharded operands: no communication needed, none
inserted.
"""

import jax.numpy as jnp


def reshard_bad(x, w):
    """dot over a sharded contracting dimension → GSPMD all-reduce."""
    return jnp.dot(x, w)


def reshard_clean(x, y):
    """Elementwise over aligned shardings → zero collectives."""
    return x + y
