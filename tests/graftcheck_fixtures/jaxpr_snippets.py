"""Traceable functions for the jaxpr-layer audits (tests/test_graftcheck_jaxpr.py).

Imported lazily by the jaxpr tests (never collected by pytest, never
scanned by the AST passes — this tree is fixture territory). The raw
``lax.psum`` in ``census_bad`` is the point of the fixture: a collective
with no CollectiveTally row, exactly what the census pass must catch.
"""

import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_framework_tpu.parallel import collectives as coll


# --- jaxpr-f32-upcast -----------------------------------------------------
def upcast_bad(x, w):
    """bf16 operands widened to f32 right before the matmul — the silent
    full-precision GEMM the pass exists to flag."""
    return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))


def upcast_clean(x, w):
    """The matmul runs at the operands' bf16 dtype."""
    return jnp.dot(x, w)


def policy_upcast_bad(x, w_hidden, w_logits):
    """A two-matmul 'model' under the bf16 precision policy that widens
    the HIDDEN matmul to f32 — exactly the defeat the policy-probe
    variant of the pass exists to catch (the widening is mid-network, not
    the justified logits head)."""
    h = jnp.dot(x.astype(jnp.float32), w_hidden.astype(jnp.float32))
    return jnp.dot(h.astype(jnp.bfloat16), w_logits)


def policy_upcast_clean(x, w_hidden, w_logits):
    """The policy-honoring twin: both matmuls take bf16 operands, the
    hidden one with f32 MXU accumulation via preferred_element_type —
    range safety WITHOUT a convert op, so the pass has nothing to flag."""
    h = lax.dot_general(x, w_hidden, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return jnp.dot(h.astype(jnp.bfloat16), w_logits)


# --- jaxpr-collective-census ----------------------------------------------
def census_bad(x):
    """Raw lax.psum: the jaxpr gets a psum op, the tally gets nothing."""
    return lax.psum(x, "data")


def census_clean(x):
    """Tallied wrapper: one tally row per psum op in the jaxpr."""
    return coll.psum(x, "data")
