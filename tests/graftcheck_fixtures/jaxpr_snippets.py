"""Traceable functions for the jaxpr-layer audits (tests/test_graftcheck_jaxpr.py).

Imported lazily by the jaxpr tests (never collected by pytest, never
scanned by the AST passes — this tree is fixture territory). The raw
``lax.psum`` in ``census_bad`` is the point of the fixture: a collective
with no CollectiveTally row, exactly what the census pass must catch.
"""

import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_framework_tpu.parallel import collectives as coll


# --- jaxpr-f32-upcast -----------------------------------------------------
def upcast_bad(x, w):
    """bf16 operands widened to f32 right before the matmul — the silent
    full-precision GEMM the pass exists to flag."""
    return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))


def upcast_clean(x, w):
    """The matmul runs at the operands' bf16 dtype."""
    return jnp.dot(x, w)


# --- jaxpr-collective-census ----------------------------------------------
def census_bad(x):
    """Raw lax.psum: the jaxpr gets a psum op, the tally gets nothing."""
    return lax.psum(x, "data")


def census_clean(x):
    """Tallied wrapper: one tally row per psum op in the jaxpr."""
    return coll.psum(x, "data")
