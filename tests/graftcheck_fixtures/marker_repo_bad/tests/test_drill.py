"""Fixture: a DRIVER drill module whose test lacks @pytest.mark.slow."""

import pytest  # noqa: F401

DRIVER = "import sys; sys.exit(0)"


def test_crash_drill_without_mark(tmp_path):
    assert DRIVER
