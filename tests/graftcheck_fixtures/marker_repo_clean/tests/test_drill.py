"""Fixture: the same drill, correctly marked slow."""

import pytest

DRIVER = "import sys; sys.exit(0)"


@pytest.mark.slow
def test_crash_drill_with_mark(tmp_path):
    assert DRIVER
