"""Fixture: host-synchronization patterns banned from step-builder code."""

import numpy as np

import jax


def collect_metrics(loss, metrics, x):
    scalar = loss.item()                    # device→host sync
    as_float = float(metrics["accuracy"])   # implicit device_get
    host = np.asarray(x)                    # numpy materializes on host
    fetched = jax.device_get(metrics)       # explicit fetch
    x.block_until_ready()                   # queue drain
    return scalar, as_float, host, fetched
