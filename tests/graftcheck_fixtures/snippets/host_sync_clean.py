"""Fixture: on-device metric handling — what the pass must NOT flag."""

import jax.numpy as jnp


def collect_metrics(loss, logits, labels):
    accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
    scaled = loss * float(4)  # literal float() is not a sync
    return {"loss": scaled, "accuracy": accuracy}
