"""Fixture: fields racing between the main/API group and a background
thread's group — written bare in a lock-owning class (per-site findings)
and in a lockless class (class-level finding)."""

import threading


class Racy:
    """Owns a lock but writes the shared field outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="dtf-racy")
        self._t.start()

    def _run(self):
        try:
            while True:
                self.count += 1          # bg write, no lock
        except BaseException as e:
            self.fail(e)

    def fail(self, e):
        pass

    def bump(self):
        self.count += 1                  # main write, no lock


class Lockless:
    """No lock at all — the class-level finding."""

    def __init__(self):
        self.total = 0
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="dtf-lockless")
        self._t.start()

    def _run(self):
        try:
            self.total += 1
        except BaseException as e:
            self.fail(e)

    def fail(self, e):
        pass

    def add(self, n):
        self.total += n
