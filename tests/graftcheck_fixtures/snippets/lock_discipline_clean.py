"""Fixture: the same shapes as lock_discipline_bad, made clean the three
accepted ways — writes under ``with self.<lock>``, writes inside
``*_locked`` methods, thread-safe handoff types, and single-writer
fields (which never need a lock)."""

import queue
import threading


class Disciplined:
    def __init__(self):
        self._cond = threading.Condition()
        self._q = queue.Queue()
        self._stop = threading.Event()
        self.count = 0
        self.bg_only = 0
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="dtf-disciplined")
        self._t.start()

    def _run(self):
        try:
            while not self._stop.is_set():
                with self._cond:
                    self.count += 1       # bg write under the lock
                self.bg_only += 1         # single-writer: only this thread
        except BaseException as e:
            self._q.put(e)                # Queue handoff is exempt

    def bump(self):
        self._bump_locked()

    def _bump_locked(self):
        self.count += 1                   # *_locked naming convention

    def close(self):
        self._stop.set()                  # Event is exempt
