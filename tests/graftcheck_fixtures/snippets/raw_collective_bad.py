"""Fixture: every spelling of a raw collective the pass must catch."""

import jax
from jax import lax
from jax.lax import psum  # banned import spelling


def mean_grads(g):
    return lax.pmean(g, "data")  # banned: bypasses CollectiveTally


def gather_params(p):
    return jax.lax.all_gather(p, "fsdp", tiled=True)  # banned: jax.lax attr


def reduce_direct(x):
    return psum(x, "data")  # call through the banned import
