"""Fixture: the tallied wrappers — what the pass must NOT flag."""

from distributed_tensorflow_framework_tpu.parallel import collectives as coll


def mean_grads(g):
    return coll.pmean(g, "data")


def gather_params(p):
    return coll.all_gather(p, "fsdp", tiled=True)


def shift(x):
    return coll.ppermute_shift(x, "pipe", shift=1)
