"""Fixture: every way a background thread can break the lifecycle
contract (name, daemon/join, exception funnel) — one violation per
thread so the test can count findings per rule."""

import threading
from concurrent.futures import ThreadPoolExecutor


def _quiet_worker():
    try:
        do_work()
    except Exception:  # log-and-vanish: the bound exception never escapes
        print("oops")


def _busy_worker():
    while True:
        do_work()


def do_work():
    pass


class Owner:
    def __init__(self, label):
        # 1. No name= at all.
        threading.Thread(target=_quiet_worker, daemon=True).start()
        # 2. Name present but not statically resolvable (runtime f-string).
        threading.Thread(target=_quiet_worker, daemon=True,
                         name=f"dtf-{label}").start()
        # 3. Resolvable name without the dtf- prefix.
        threading.Thread(target=_quiet_worker, daemon=True,
                         name="helper").start()
        # 4. Neither daemon=True nor joined anywhere in this module.
        self._t = threading.Thread(target=_quiet_worker, name="dtf-leaky")
        self._t.start()
        # 5. Target has no broad except handler whose exception escapes
        #    (_quiet_worker above also trips this: it only logs).
        threading.Thread(target=_busy_worker, daemon=True,
                         name="dtf-nofunnel").start()
        # 6. Executor workers without a dtf- thread_name_prefix.
        self._pool = ThreadPoolExecutor(max_workers=1)
