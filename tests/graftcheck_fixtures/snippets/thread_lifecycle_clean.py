"""Fixture: the async-saver lifecycle contract, satisfied every way the
pass accepts — literal names, module-constant names, __init__-default
names, daemon and joined threads, and a funneled target."""

import threading
from concurrent.futures import ThreadPoolExecutor

WORKER_NAME = "dtf-fixture-worker"


class WorkerError(RuntimeError):
    """Typed wrapper re-raised on the owning thread."""

    def __init__(self, cause):
        super().__init__(f"worker failed: {cause!r}")
        self.__cause__ = cause


class Owner:
    def __init__(self, *, name: str = WORKER_NAME):
        self._name = name
        self._lock = threading.Lock()
        self._error = None
        # Joined non-daemon thread, name via __init__ parameter default.
        self._t = threading.Thread(target=self._run, name=self._name)
        self._t.start()
        # Daemon thread, name via module constant.
        threading.Thread(target=self._run, daemon=True,
                         name=WORKER_NAME).start()
        # Daemon thread, literal name.
        threading.Thread(target=self._run, daemon=True,
                         name="dtf-fixture-aux").start()
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dtf-fixture-pool")

    def _run(self):
        try:
            pass
        except BaseException as e:  # funneled: stored, surfaced on join
            with self._lock:
                self._error = WorkerError(e)

    def close(self):
        self._t.join()
        with self._lock:
            error, self._error = self._error, None
        if error is not None:
            raise error
