"""Fixture: every typed-error-convention violation."""


class BadFailure(ValueError):  # not *Error-named, no docstring
    pass


def check(n):
    if n < 0:
        raise Exception("negative")  # anonymous raise
    try:
        return 1 / n
    except:  # noqa: E722 — bare except, swallows SystemExit
        return 0
