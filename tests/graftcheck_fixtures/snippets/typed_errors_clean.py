"""Fixture: the typed-error conventions done right."""


class NegativeInputError(ValueError):
    """Raised on negative input; callers map it to a usage exit code."""


def check(n):
    if n < 0:
        raise NegativeInputError(f"n must be >= 0, got {n}")
    try:
        return 1 / n
    except ZeroDivisionError:
        return 0
