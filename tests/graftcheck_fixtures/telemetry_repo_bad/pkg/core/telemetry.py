"""Fixture telemetry: an orphan kind and a duplicate kind value."""

KIND_GOOD = "good"
KIND_ORPHAN = "orphan"   # in no rollup, in no test
KIND_DUP_A = "dup"       # same value as KIND_DUP_B — rollups can't
KIND_DUP_B = "dup"       # tell the two apart


def summarize_events(events):
    return {KIND_GOOD: len(events), KIND_DUP_A: 0, KIND_DUP_B: 0}


def format_run_summary(summary):
    # KIND_GOOD rollup; the KIND_DUP_A / KIND_DUP_B pair rolls up too
    # (their shared value is the separate duplicate-kind finding).
    return f"good={summary[KIND_GOOD]} dup={summary[KIND_DUP_A]}"
