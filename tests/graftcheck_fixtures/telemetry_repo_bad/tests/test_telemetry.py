def test_kinds():
    assert "KIND_GOOD" and "KIND_DUP_A" and "KIND_DUP_B"
