"""Fixture telemetry: every kind summarized and test-referenced."""

KIND_GOOD = "good"
KIND_OTHER = "other"


def summarize_events(events):
    return {KIND_GOOD: len(events), KIND_OTHER: 0}


def format_run_summary(summary):
    return str(summary)
