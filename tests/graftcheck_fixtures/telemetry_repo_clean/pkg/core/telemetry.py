"""Fixture telemetry: every kind summarized, formatted and test-referenced."""

KIND_GOOD = "good"
KIND_OTHER = "other"


def summarize_events(events):
    return {KIND_GOOD: len(events), KIND_OTHER: 0}


def format_run_summary(summary):
    # KIND_GOOD rollup line; KIND_OTHER rollup line.
    return f"good={summary[KIND_GOOD]} other={summary[KIND_OTHER]}"
