def test_kinds():
    assert "KIND_GOOD" and "KIND_OTHER"
