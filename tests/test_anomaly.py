"""In-process recovery ladder (train/anomaly.py, docs/RESILIENCE.md).

Fast tier-1 coverage of every rung in isolation plus one in-process
end-to-end rollback on the LeNet slice: detector thresholds (non-finite /
grad-norm ceiling / EWMA loss-spike with warmup), the snapshot ring's
bit-exact device→host→device round trip, RecoveryManager policy
(snapshot cadence, rollback budget, escalation provenance, telemetry
emissions), and the ResilienceConfig validation seams. The subprocess
drills that prove the ladder under real fault injection live in
tests/test_recovery_drills.py (tier-2 by their slow marks).
"""

import math

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.core.config import (
    ResilienceConfig,
    load_config,
)
from distributed_tensorflow_framework_tpu.train import Trainer
from distributed_tensorflow_framework_tpu.train import anomaly

from tests.test_train_lenet import lenet_config


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.install(faults.FaultPlan())  # empty plan; no env re-read


# ----------------------------------------------------------- detector ----


def _warm(det, losses):
    for x in losses:
        det.observe({"loss": x})


def test_detector_flags_non_finite_any_metric():
    det = anomaly.AnomalyDetector(ResilienceConfig())
    assert det.classify(3, {"loss": 1.0, "grad_norm": 2.0}) is None
    v = det.classify(4, {"loss": float("nan"), "grad_norm": 2.0})
    assert v is not None and v.anomaly == "non_finite_metric"
    assert v.metric == "loss" and v.step == 4
    v = det.classify(5, {"loss": 1.0, "grad_norm": float("inf")})
    assert v is not None and v.metric == "grad_norm"
    # non-numeric metrics are skipped, not classified
    assert det.classify(6, {"loss": 1.0, "note": "fine"}) is None


def test_detector_grad_norm_ceiling():
    cfg = ResilienceConfig(grad_norm_max=100.0)
    det = anomaly.AnomalyDetector(cfg)
    assert det.classify(1, {"loss": 1.0, "grad_norm": 99.0}) is None
    v = det.classify(2, {"loss": 1.0, "grad_norm": 150.0})
    assert v is not None and v.anomaly == "grad_norm_explosion"
    assert v.detail["grad_norm_max"] == 100.0
    # 0 disables the ceiling entirely
    det0 = anomaly.AnomalyDetector(ResilienceConfig(grad_norm_max=0.0))
    assert det0.classify(2, {"loss": 1.0, "grad_norm": 1e12}) is None


def test_loss_spike_needs_warmup_then_fires():
    cfg = ResilienceConfig(loss_spike_zscore=5.0, min_observations=5,
                           loss_ewma_beta=0.9)
    det = anomaly.AnomalyDetector(cfg)
    # Cold EWMA: even an absurd loss cannot fire before min_observations.
    assert det.classify(1, {"loss": 1e9}) is None
    _warm(det, [1.0, 1.01, 0.99, 1.02, 0.98])
    assert det.observations == 5
    # Normal jitter around the baseline stays clean...
    assert det.classify(10, {"loss": 1.03}) is None
    # ...while a genuine spike classifies with z-score provenance.
    v = det.classify(11, {"loss": 50.0})
    assert v is not None and v.anomaly == "loss_spike"
    assert v.detail["zscore"] > 5.0
    assert v.detail["ewma_mean"] == pytest.approx(1.0, abs=0.1)


def test_loss_spike_std_floor_tolerates_constant_loss():
    """A perfectly flat loss history has ~zero EWMA variance; the relative
    std floor must keep numeric jitter from reading as an infinite-z
    spike."""
    cfg = ResilienceConfig(loss_spike_zscore=10.0, min_observations=3)
    det = anomaly.AnomalyDetector(cfg)
    _warm(det, [2.0] * 10)
    assert det.std >= 1e-3 * 2.0
    assert det.classify(20, {"loss": 2.0 + 1e-4}) is None


def test_loss_spike_zero_disables():
    det = anomaly.AnomalyDetector(ResilienceConfig(loss_spike_zscore=0.0,
                                                   min_observations=1))
    _warm(det, [1.0] * 10)
    assert det.classify(11, {"loss": 1e9}) is None


# --------------------------------------------------------- validation ----


@pytest.mark.parametrize("key,bad,msg", [
    ("resilience.snapshot_depth", 0, "snapshot_depth"),
    ("resilience.max_rollbacks", 0, "max_rollbacks"),
    ("resilience.loss_ewma_beta", 1.5, "loss_ewma_beta"),
    ("resilience.loss_ewma_beta", 0.0, "loss_ewma_beta"),
])
def test_resilience_config_validation(key, bad, msg):
    with pytest.raises(ValueError, match=msg):
        load_config(overrides=[f"{key}={bad}"])


def test_resilience_defaults_armed():
    cfg = load_config()
    assert cfg.resilience.rollback is True
    assert cfg.resilience.snapshot_depth >= 1
    assert cfg.resilience.max_rollbacks >= 1


# ------------------------------------------------------ snapshot ring ----


def test_snapshot_ring_depth_evicts_oldest():
    ring = anomaly.SnapshotRing(depth=2)
    for step in (10, 20, 30):
        ring.push(anomaly.Snapshot(step=step, host=None, shardings=None))
    assert len(ring) == 2
    assert ring.steps == [20, 30]
    assert ring.latest().step == 30


def test_snapshot_restore_bit_exact(devices):
    """The rollback contract: restore must land the EXACT bytes of the
    snapshotted state — params, opt state, step counter, and the typed
    PRNG key — on the original shardings, after training has moved the
    live state arbitrarily far away."""
    cfg = lenet_config(**{"train.total_steps": 6, "train.log_interval": 3})
    trainer = Trainer(cfg)
    trainer.build()

    ref = jax.device_get(
        trainer.state.replace(rng=jax.random.key_data(trainer.state.rng)))
    host, shardings = anomaly.snapshot_state(trainer.state)
    trainer.train()  # move the live state well away from the snapshot

    restored = anomaly.restore_state(host, shardings, like=trainer.state)
    got = jax.device_get(
        restored.replace(rng=jax.random.key_data(restored.rng)))
    ref_leaves = jax.tree.leaves(ref)
    got_leaves = jax.tree.leaves(got)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # placements survive the round trip: every restored leaf sits on the
    # same mesh sharding as its live counterpart, not a default device.
    for lr, ll in zip(jax.tree.leaves(restored),
                      jax.tree.leaves(trainer.state)):
        assert lr.sharding == ll.sharding


# --------------------------------------------------- recovery manager ----


def _manager(tmp_path=None, **over):
    cfg = ResilienceConfig(**over)
    writer = None
    path = None
    if tmp_path is not None:
        path = str(tmp_path / "events.jsonl")
        writer = telemetry.TelemetryWriter(path, run_id="anomaly-test")
    return anomaly.RecoveryManager(cfg, telemetry_writer=writer), path


def test_manager_snapshot_cadence_and_force():
    rec, _ = _manager(snapshot_interval_steps=10)
    # Bypass the device round trip: stub the snapshot at the module seam.
    orig = anomaly.snapshot_state
    anomaly.snapshot_state = lambda s: ("host", None)
    try:
        state = object()
        assert rec.take_snapshot(0, state, force=True)
        assert not rec.take_snapshot(5, state)       # below the interval
        assert rec.take_snapshot(10, state)          # at the interval
        assert rec.ring.steps == [0, 10]
    finally:
        anomaly.snapshot_state = orig


def test_manager_classify_emits_and_resets_streak(tmp_path):
    rec, path = _manager(tmp_path, min_observations=1)
    rec.consecutive_rollbacks = 2
    assert rec.classify(10, {"loss": 1.0}) is None   # clean: streak resets
    assert rec.consecutive_rollbacks == 0
    assert rec.detector.observations == 1
    v = rec.classify(20, {"loss": float("nan")})
    assert v is not None
    # anomalous metrics must NOT feed the EWMA baseline
    assert rec.detector.observations == 1
    assert rec.anomalies_detected == 1
    rec._telemetry.close()
    evs = list(telemetry.read_events(path, kind=telemetry.KIND_ANOMALY))
    assert len(evs) == 1
    assert evs[0]["step"] == 20
    assert evs[0]["health"]["anomaly"] == "non_finite_metric"


def test_manager_rollback_budget_and_exhaustion():
    rec, _ = _manager(max_rollbacks=2)
    assert not rec.can_rollback()                    # no snapshot yet
    rec.ring.push(anomaly.Snapshot(step=10, host=None, shardings=None))
    orig = anomaly.restore_state
    anomaly.restore_state = lambda h, s, like: like
    try:
        assert rec.can_rollback()
        rec.rollback("state", from_step=30)
        assert rec.consecutive_rollbacks == 1 and rec.total_rollbacks == 1
        rec.rollback("state", from_step=30)
        assert not rec.can_rollback()                # budget exhausted
        # ...until a clean fetch resets the streak
        rec.classify(40, {"loss": 1.0})
        assert rec.can_rollback()
    finally:
        anomaly.restore_state = orig


def test_manager_rollback_telemetry_and_skip_accounting(tmp_path):
    rec, path = _manager(tmp_path)
    rec.ring.push(anomaly.Snapshot(step=20, host=None, shardings=None))
    orig = anomaly.restore_state
    anomaly.restore_state = lambda h, s, like: like
    try:
        _, snap = rec.rollback("state", from_step=30)
    finally:
        anomaly.restore_state = orig
    assert snap.step == 20
    rec._telemetry.close()
    rb = list(telemetry.read_events(path, kind=telemetry.KIND_ROLLBACK))
    sk = list(telemetry.read_events(path, kind=telemetry.KIND_BATCH_SKIPPED))
    assert rb[0]["health"] == {"from_step": 30, "to_step": 20,
                               "consecutive_rollbacks": 1}
    # skip-batch semantics: steps 21..30 replay with FRESH data
    assert sk[0]["health"]["batches"] == 10


def test_manager_disable_escalates_with_reason():
    rec, _ = _manager()
    rec.disable("train state is not fully addressable on this host")
    assert not rec.armed
    assert not rec.take_snapshot(0, None, force=True)
    assert not rec.can_rollback()
    assert "disabled" in rec.escalation_message()
    assert rec.provenance()["disabled_reason"]


def test_escalation_provenance_names_the_verdict():
    rec, _ = _manager(max_rollbacks=2)
    rec.classify(30, {"loss": float("nan")})
    rec.consecutive_rollbacks = 2
    prov = rec.provenance()
    assert prov["anomaly"] == "non_finite_metric"
    assert prov["step"] == 30
    assert prov["max_rollbacks"] == 2
    msg = rec.escalation_message()
    assert "non_finite_metric" in msg and "poisoned data region" in msg


def test_persistent_anomaly_error_is_a_floating_point_error():
    """The escalation tail must stay catchable by pre-ladder NaNGuardHook
    consumers (except FloatingPointError) while carrying provenance."""
    err = anomaly.PersistentAnomalyError("boom", provenance={"step": 3})
    assert isinstance(err, FloatingPointError)
    assert err.provenance == {"step": 3}


# ------------------------------------------- in-process end-to-end ----


def test_nan_batch_rolls_back_and_finishes(devices):
    """The ladder's happy path, in process and in one pytest worker: a
    single poisoned batch (nan_grads fault) is detected at the next metric
    fetch, the state rolls back to the last clean snapshot, the poisoned
    region is skipped, and the run finishes with finite metrics — no
    relaunch, no checkpoint, no supervisor."""
    faults.install("nan_grads:15")
    cfg = lenet_config(**{
        "train.total_steps": 30,
        "train.log_interval": 5,
        "resilience.snapshot_interval_steps": 5,
        "resilience.snapshot_depth": 2,
    })
    trainer = Trainer(cfg)
    metrics = trainer.train()
    assert trainer.recovery is not None
    assert trainer.recovery.total_rollbacks == 1
    assert trainer.recovery.anomalies_detected == 1
    assert not trainer.recovery.exhausted
    assert trainer.host_step == 30
    assert math.isfinite(float(metrics["loss"]))


def test_rollback_disabled_falls_back_to_nan_guard(devices):
    """resilience.rollback=false restores the PR 2 contract exactly: the
    NaN reaches NaNGuardHook and aborts the run as a FloatingPointError
    (not the escalation subclass — the ladder never armed)."""
    faults.install("nan_grads:15")
    cfg = lenet_config(**{
        "train.total_steps": 30,
        "train.log_interval": 5,
        "resilience.rollback": False,
    })
    trainer = Trainer(cfg)
    with pytest.raises(FloatingPointError) as ei:
        trainer.train()
    assert not isinstance(ei.value, anomaly.PersistentAnomalyError)
    assert trainer.recovery is None
