"""Async checkpoint pipeline: serialization, barriers, telemetry, crashes.

The perf contract (docs/PERFORMANCE.md): with ``checkpoint.async_save``
on, a save step costs the training thread only a device→host snapshot —
the orbax write + manifest commit happen on the background saver thread
— while the integrity contract of docs/RESILIENCE.md (manifest = commit
record; no manifest = uncommitted = quarantined) is preserved bit-for-bit.

Layered: pure AsyncSaver threading tests (tier-1, no jax), the tier-1
telemetry guard (``ckpt_save_blocked_ms`` emitted and < total under async
mode), and the slow end-to-end drills (bit-exact async resume; SIGKILL
injected ON the saver thread via the supervised crash_in_save drill).
"""

import json
import os
import threading
import time

import pytest

from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.ckpt.async_saver import (
    AsyncSaver,
    AsyncSaverError,
)
from distributed_tensorflow_framework_tpu.core import telemetry


# ----------------------------------------------------- AsyncSaver (pure) --

def test_overlapping_saves_serialize():
    """submit() must block until the previous commit landed: at most one
    job queued-or-running, executed in submission order."""
    saver = AsyncSaver()
    running = threading.Event()
    release = threading.Event()
    order = []

    def slow_job():
        running.set()
        assert release.wait(timeout=10)
        order.append("first")

    blocked_1 = saver.submit(slow_job, step=1)
    assert blocked_1 < 1.0  # pipe was idle — no wait
    assert running.wait(timeout=10)

    t0 = time.perf_counter()
    release_timer = threading.Timer(0.2, release.set)
    release_timer.start()
    try:
        blocked_2 = saver.submit(lambda: order.append("second"), step=2)
    finally:
        release_timer.cancel()
    # The second submit waited for the first commit to finish.
    assert time.perf_counter() - t0 >= 0.15
    assert blocked_2 >= 0.15
    assert order[0] == "first"
    saver.wait()
    assert order == ["first", "second"]
    assert saver.submitted == 2 and saver.completed == 2
    assert saver.idle
    saver.close()


def test_wait_is_a_barrier():
    saver = AsyncSaver()
    done = []
    saver.submit(lambda: (time.sleep(0.1), done.append(1)))
    saver.wait()
    assert done == [1]
    saver.close()


def test_background_error_surfaces_on_training_thread():
    """A failed background commit must re-raise at the next submit/wait,
    carrying the step and the original cause — never vanish into the
    daemon thread's stderr."""
    saver = AsyncSaver()

    def boom():
        raise OSError("disk full")

    saver.submit(boom, step=7)
    with pytest.raises(AsyncSaverError) as exc:
        saver.wait()
    assert exc.value.step == 7
    assert isinstance(exc.value.__cause__, OSError)
    assert "disk full" in str(exc.value)
    # The error was consumed: the pipe is usable again.
    saver.submit(lambda: None, step=8)
    saver.wait()
    saver.close()


def test_close_drains_and_raises_pending_error():
    saver = AsyncSaver()
    done = []
    saver.submit(lambda: done.append(1))
    saver.close()
    assert done == [1]
    with pytest.raises(RuntimeError, match="closed"):
        saver.submit(lambda: None)

    saver2 = AsyncSaver()
    saver2.submit(lambda: (_ for _ in ()).throw(ValueError("late")), step=3)
    # Give the worker a moment so the error is pending (not in-flight)
    # when close() runs its drain.
    deadline = time.monotonic() + 10
    while not saver2.idle and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(AsyncSaverError):
        saver2.close()


# ------------------------------------------- tier-1 telemetry guard (e2e) --

def _train_async(ckpt_dir, total_steps=6, save_interval=3, **overrides):
    from distributed_tensorflow_framework_tpu.train import Trainer
    from tests.test_train_lenet import lenet_config

    cfg = lenet_config(**{"train.total_steps": total_steps,
                          "train.log_interval": 3, **overrides})
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.save_interval_steps = save_interval
    cfg.checkpoint.async_save = True
    t = Trainer(cfg)
    t.train()
    return t


def test_async_save_emits_blocked_below_total(devices, tmp_path):
    """The acceptance guard: under async_save the run's telemetry carries
    a ``ckpt_save`` event per save whose loop-blocked time is strictly
    below the total save time (blocked is a proper prefix of total by
    construction: total is measured from save() entry through the
    background commit, blocked stops at submit)."""
    ckpt_dir = str(tmp_path / "ckpt")
    t = _train_async(ckpt_dir)
    assert sorted(t._ckpt_manager.all_steps()) == [3, 6]

    events = list(telemetry.read_events(
        os.path.join(ckpt_dir, "events.jsonl"),
        kind=telemetry.KIND_CKPT_SAVE, strict=True))
    assert {e["step"] for e in events} == {3, 6}
    for e in events:
        assert e["extra"]["async_save"] is True
        blocked = e["metrics"]["ckpt_save_blocked_ms"]
        total = e["metrics"]["ckpt_save_total_ms"]
        assert blocked < total, (blocked, total)
        assert blocked >= 0.0

    # ...and the run summary surfaces the save-stall accounting.
    summary = telemetry.summarize_events(os.path.join(ckpt_dir, "events.jsonl"))
    saves = summary["ckpt_saves"]
    assert saves["count"] == 2 and saves["async_count"] == 2
    assert saves["blocked_ms_total"] < saves["total_ms_total"]
    text = telemetry.format_run_summary(summary)
    assert "checkpoint saves: 2 (2 async)" in text
    # startup telemetry (restart → first step) rides the same stream
    assert summary["startups"] and \
        summary["startups"][0]["time_to_first_step_s"] > 0


def test_exit_barrier_flushes_inflight_commit(devices, tmp_path):
    """train() must not return with a commit still in flight: every saved
    step carries its manifest by the time the loop hands back control —
    the property the rc-83 graceful-preemption exit relies on."""
    ckpt_dir = str(tmp_path / "ckpt")
    t = _train_async(ckpt_dir)
    for step in (3, 6):
        step_dir = os.path.join(ckpt_dir, str(step))
        manifest = mf.read_manifest(step_dir)
        assert manifest is not None, f"step {step} uncommitted after train()"
        assert mf.verify_step_dir(step_dir, manifest) == []
    assert t._ckpt_manager._saver is not None and t._ckpt_manager._saver.idle

    # An explicit follow-up save + barrier also lands durably.
    t._ckpt_manager.save(99, t.state, dataset_state=t.data_ckpt_state,
                         force=True)
    t._ckpt_manager.wait_until_finished()
    assert mf.read_manifest(os.path.join(ckpt_dir, "99")) is not None
    t._ckpt_manager.close()


def test_queued_dataset_state_is_snapshotted(devices, tmp_path):
    """Mutating the live iterator-state dict after save() returns must not
    tear the queued snapshot (the async path deep-copies it)."""
    ckpt_dir = str(tmp_path / "ckpt")
    t = _train_async(ckpt_dir)
    ds_state = dict(t.data_ckpt_state)
    t._ckpt_manager.save(50, t.state, dataset_state=ds_state, force=True)
    ds_state.clear()  # trainer reuses/mutates its dict freely
    t._ckpt_manager.wait_until_finished()
    saved = json.load(open(os.path.join(
        ckpt_dir, "50", "data_iter", "metadata")))
    assert saved, "queued dataset snapshot was torn by the mutation"
    t._ckpt_manager.close()


# ------------------------------------------------------- slow e2e drills --

@pytest.mark.slow
def test_async_resume_exactness(devices, tmp_path):
    """Bit-exact resume with async_save on: params after restore + K more
    steps equal an uninterrupted run's — the PR 2 contract must survive
    moving the commit to the saver thread."""
    import jax
    import numpy as np

    from distributed_tensorflow_framework_tpu.train import Trainer
    from tests.test_train_lenet import lenet_config

    cfg = lenet_config(**{"train.total_steps": 8, "train.log_interval": 4})
    t_full = Trainer(cfg)
    t_full.train()
    full_params = jax.device_get(t_full.state.params)

    ckpt_dir = str(tmp_path / "ckpt")
    _train_async(ckpt_dir, total_steps=4, save_interval=4,
                 **{"train.log_interval": 4})

    cfg_b = lenet_config(**{"train.total_steps": 8, "train.log_interval": 4})
    cfg_b.checkpoint.directory = ckpt_dir
    cfg_b.checkpoint.save_interval_steps = 100
    cfg_b.checkpoint.async_save = True
    t_b = Trainer(cfg_b)
    t_b.build()
    assert t_b.host_step == 4, "restore did not pick up the async-saved step"
    t_b.train()
    resumed = jax.device_get(t_b.state.params)
    for a, b in zip(jax.tree.leaves(full_params), jax.tree.leaves(resumed)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
@pytest.mark.slowest
def test_supervised_crash_in_save_drill_async(tmp_path):
    """The sync drill's acceptance twin with async_save=true: the SIGKILL
    fires ON the background saver thread (between orbax data and manifest
    commit), takes the whole process, the relaunch quarantines the
    uncommitted step-40 directory, and the final loss is BIT-EXACT
    against an uninterrupted async run of the same seed."""
    from tests.test_fault_tolerance import DRIVER, _child_env, _final_loss
    import subprocess
    import sys

    driver_async = DRIVER.replace("checkpoint.async_save=false",
                                  "checkpoint.async_save=true")
    assert "async_save=true" in driver_async  # template still has the knob

    ckpt_dir = str(tmp_path / "ckpt")
    ref_dir = str(tmp_path / "ref")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    ref = subprocess.run(
        [sys.executable, "-c", driver_async.format(ckpt=ref_dir, steps=60)],
        env=_child_env(), cwd=repo_root, capture_output=True, text=True,
        timeout=420)
    assert ref.returncode == 0, ref.stdout[-3000:] + ref.stderr[-2000:]

    cmd = [sys.executable, "scripts/train_resilient.py",
           "--max-attempts", "3", "--retry-sleep", "0.2", "--jitter", "0",
           "--", sys.executable, "-c",
           driver_async.format(ckpt=ckpt_dir, steps=60)]
    r = subprocess.run(
        cmd, cwd=repo_root, capture_output=True, text=True, timeout=560,
        env=_child_env({
            "DTF_FAULTS": "crash_in_save:40",
            "DTF_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        }))
    assert r.returncode == 0, r.stderr[-3000:]
    assert "firing crash_in_save:40" in r.stderr, r.stderr[-3000:]
    # The kill fired on the background saver thread, not the train loop.
    assert "thread=dtf-ckpt-saver" in r.stderr, r.stderr[-3000:]
    assert "exited rc=137" in r.stderr
    assert "done (attempt 2)" in r.stderr
    quarantined = [d for d in os.listdir(ckpt_dir)
                   if d.startswith("40" + mf.CORRUPT_SUFFIX)]
    assert quarantined, os.listdir(ckpt_dir)
    assert os.path.isdir(os.path.join(ckpt_dir, "40"))  # the re-save
    assert _final_loss(ckpt_dir, 60) == _final_loss(ref_dir, 60)
