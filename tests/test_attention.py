"""Attention implementation parity: pallas and ring vs the XLA reference
(SURVEY.md §4 numerics-parity strategy applied to the attention kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.models.bert import dot_product_attention


def _rand_qkv(key, b=2, s=256, h=4, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def test_flash_attention_matches_xla(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_with_mask(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(1), s=128)
    mask = jnp.ones((2, 1, 1, 128), bool).at[:, :, :, 100:].set(False)
    ref = dot_product_attention(q, k, v, mask=mask)
    out = flash_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_backward_matches_xla(devices):
    """The Pallas backward kernels (dq + dkv, online recompute) must match
    XLA autodiff through the reference attention — for q, k AND v."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(3), s=256)
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 200:].set(False)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_flash_attention_backward_no_quadratic_residual(devices):
    """Structural check on the VJP residuals: nothing score-matrix-shaped
    (S×S) is saved between forward and backward."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    b, s, h, d = 2, 512, 4, 64
    q, k, v = _rand_qkv(jax.random.key(4), b=b, s=s, h=h, d=d)
    _, vjp = jax.vjp(lambda q, k, v: flash_attention(q, k, v), q, k, v)
    # Residuals captured by the VJP closure: all must be O(S·D)/O(S) —
    # a score-shaped residual would have TWO sequence-length axes.
    leaves = [x for x in jax.tree.leaves(vjp) if hasattr(x, "shape")]
    assert leaves, "vjp closure has no residuals?"
    for leaf in leaves:
        seq_axes = sum(1 for dim in leaf.shape if dim == s)
        assert seq_axes <= 1, f"score-matrix-shaped residual: {leaf.shape}"
        assert leaf.size <= b * h * s * d, (
            f"residual {leaf.shape} larger than any O(S·D) tensor"
        )


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_attention_matches_xla(devices, monkeypatch, chunk_impl):
    """Ring attention over a seq=8 mesh axis reproduces full attention —
    through BOTH per-chunk implementations (the FLASH_CHUNK_MIN dispatch
    picks by chunk length in production; tests force each path)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(2), b=2, s=256, h=2, d=32)
    ref = dot_product_attention(q, k, v)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_attention_mask_and_gradients(devices, monkeypatch, chunk_impl):
    """Ring attention under a key mask must match XLA attention for the
    output AND the q/k/v gradients (the training path differentiates
    through the ppermute ring; the flash variant additionally exercises
    the lse-cotangent path of the Pallas backward)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(5), b=2, s=256, h=2, d=32)
    # Mask out the last 40 keys (cuts across the final ring shard).
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 216:].set(False)

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    out_ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
    )(q, k, v)
    out_ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_flash_chunk_guards(devices):
    """flash_attention_chunk must refuse shapes its grid would silently
    truncate: non-multiple-of-BLOCK_Q chunk lengths (e.g. seq/ring_shards
    = 192), oversized K/V chunks, and unequal shard lengths."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention_chunk,
    )

    def qkv(s, sk=None):
        sk = s if sk is None else sk
        q = jnp.zeros((1, s, 2, 8), jnp.float32)
        k = jnp.zeros((1, sk, 2, 8), jnp.float32)
        bias = jnp.zeros((1, sk), jnp.float32)
        return q, k, k, bias

    q, k, v, bias = qkv(192)  # > BLOCK_Q but not a multiple
    with pytest.raises(ValueError, match="multiple of"):
        flash_attention_chunk(q, k, v, bias)
    q, k, v, bias = qkv(8192)  # past the VMEM budget
    with pytest.raises(ValueError, match="VMEM"):
        flash_attention_chunk(q, k, v, bias)
    q, k, v, bias = qkv(128, sk=256)  # unequal shards
    with pytest.raises(ValueError, match="equal-length"):
        flash_attention_chunk(q, k, v, bias)
    # A legal sub-block chunk still runs (block_q clamps to s).
    q, k, v, bias = qkv(32)
    o, lse = flash_attention_chunk(q, k, v, bias)
    assert o.shape == (1, 32, 2, 8) and lse.shape == (1, 32, 2, 1)


def test_ring_chunk_dispatch_falls_back_for_incompatible_shapes(devices):
    """Chunks the Pallas kernel can't take (non-128-multiples above the
    crossover, or beyond its VMEM budget) must silently use the XLA chain
    — every chunk length the old pure-XLA ring handled still works."""
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        _chunk_attention,
    )

    for c in (2112, 8192):  # non-multiple above crossover; > MAX_SEQ_VMEM
        q = jnp.zeros((1, c, 1, 8), jnp.float32)
        bias = jnp.zeros((1, c), jnp.float32)
        o, lse = _chunk_attention(q, q, q, bias)  # must not raise
        assert o.shape == (1, c, 1, 8) and lse.shape == (1, c, 1, 1)
