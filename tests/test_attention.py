"""Attention implementation parity: pallas and ring vs the XLA reference
(SURVEY.md §4 numerics-parity strategy applied to the attention kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.models.bert import dot_product_attention


def _rand_qkv(key, b=2, s=256, h=4, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def test_flash_attention_matches_xla(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_with_mask(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(1), s=128)
    mask = jnp.ones((2, 1, 1, 128), bool).at[:, :, :, 100:].set(False)
    ref = dot_product_attention(q, k, v, mask=mask)
    out = flash_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_backward_matches_xla(devices):
    """The Pallas backward kernels (dq + dkv, online recompute) must match
    XLA autodiff through the reference attention — for q, k AND v."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(3), s=256)
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 200:].set(False)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_flash_attention_backward_no_quadratic_residual(devices):
    """Structural check on the VJP residuals: nothing score-matrix-shaped
    (S×S) is saved between forward and backward."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    b, s, h, d = 2, 512, 4, 64
    q, k, v = _rand_qkv(jax.random.key(4), b=b, s=s, h=h, d=d)
    _, vjp = jax.vjp(lambda q, k, v: flash_attention(q, k, v), q, k, v)
    # Residuals captured by the VJP closure: all must be O(S·D)/O(S) —
    # a score-shaped residual would have TWO sequence-length axes.
    leaves = [x for x in jax.tree.leaves(vjp) if hasattr(x, "shape")]
    assert leaves, "vjp closure has no residuals?"
    for leaf in leaves:
        seq_axes = sum(1 for dim in leaf.shape if dim == s)
        assert seq_axes <= 1, f"score-matrix-shaped residual: {leaf.shape}"
        assert leaf.size <= b * h * s * d, (
            f"residual {leaf.shape} larger than any O(S·D) tensor"
        )


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_attention_matches_xla(devices, monkeypatch, chunk_impl):
    """Ring attention over a seq=8 mesh axis reproduces full attention —
    through BOTH per-chunk implementations (the FLASH_CHUNK_MIN dispatch
    picks by chunk length in production; tests force each path)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(2), b=2, s=256, h=2, d=32)
    ref = dot_product_attention(q, k, v)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_attention_mask_and_gradients(devices, monkeypatch, chunk_impl):
    """Ring attention under a key mask must match XLA attention for the
    output AND the q/k/v gradients (the training path differentiates
    through the ppermute ring; the flash variant additionally exercises
    the lse-cotangent path of the Pallas backward)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(5), b=2, s=256, h=2, d=32)
    # Mask out the last 40 keys (cuts across the final ring shard).
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 216:].set(False)

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    out_ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
    )(q, k, v)
    out_ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_attention_data_seq_mesh_trailing_padding(devices, monkeypatch,
                                                       chunk_impl):
    """Ring on a COMBINED data×seq mesh (4×2) with document-style
    trailing padding — half the rows have their entire second KV chunk
    padded — through BOTH per-chunk implementations (an all-f32-min
    bias chunk must stay finite in the flash kernels too). Pinned by the
    round-5 dp+sp+ep forensics: this exact shape was suspected when a
    composed ring+MoE run went flat, and the probe that exonerated the
    op (fwd + all grads ≤1.1e-6 vs reference) is kept here so the
    composition's attention substrate stays provably exact. Loss weights
    valid positions only, like the MLM objective."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=4, seq=2))
    B, S = 8, 256
    q, k, v = _rand_qkv(jax.random.key(23), b=B, s=S, h=2, d=32)
    valid = np.ones((B, S), bool)
    valid[:4, 80:] = False          # rows 0-3: 80-token docs → chunk 2 all pad
    mask = jnp.asarray(valid)[:, None, None, :]
    w = jnp.asarray(valid, jnp.float32)[:, :, None, None]

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)) * w)

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)) * w)

    out_ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
    )(q, k, v)
    out_ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(out_ring) * np.asarray(w), np.asarray(out_ref) * np.asarray(w),
        rtol=2e-5, atol=2e-5)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_flash_chunk_guards(devices):
    """flash_attention_chunk must refuse shapes its grid would silently
    truncate: non-multiple-of-BLOCK_Q chunk lengths (e.g. seq/ring_shards
    = 192) and unequal shard lengths."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention_chunk,
    )

    def qkv(s, sk=None):
        sk = s if sk is None else sk
        q = jnp.zeros((1, s, 2, 8), jnp.float32)
        k = jnp.zeros((1, sk, 2, 8), jnp.float32)
        bias = jnp.zeros((1, sk), jnp.float32)
        return q, k, k, bias

    q, k, v, bias = qkv(192)  # > BLOCK_Q but not a multiple
    with pytest.raises(ValueError, match="multiple of"):
        flash_attention_chunk(q, k, v, bias)
    q, k, v, bias = qkv(128, sk=256)  # unequal shards
    with pytest.raises(ValueError, match="equal-length"):
        flash_attention_chunk(q, k, v, bias)
    # A legal sub-block chunk still runs (block_q clamps to s).
    q, k, v, bias = qkv(32)
    o, lse = flash_attention_chunk(q, k, v, bias)
    assert o.shape == (1, 32, 2, 8) and lse.shape == (1, 32, 2, 1)


def test_ring_chunk_dispatch_policy(devices):
    """The >MAX_SEQ_VMEM silent-fallback hole is closed (VERDICT r3 weak
    #2): small odd chunks still take the XLA chain; 128-multiple chunks
    above MAX_SEQ_VMEM take the K-blocked flash kernels; chunks above
    MAX_SEQ_VMEM the kernel can't take fail LOUDLY instead of
    materializing an O(chunk²) score block."""
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        _chunk_attention,
    )

    # Non-multiple above the crossover but within VMEM: XLA chain, works.
    c = 2112
    q = jnp.zeros((1, c, 1, 8), jnp.float32)
    bias = jnp.zeros((1, c), jnp.float32)
    o, lse = _chunk_attention(q, q, q, bias)
    assert o.shape == (1, c, 1, 8) and lse.shape == (1, c, 1, 1)
    # Non-multiple above MAX_SEQ_VMEM: loud failure with mesh guidance.
    c = 8200
    q = jnp.zeros((1, c, 1, 8), jnp.float32)
    bias = jnp.zeros((1, c), jnp.float32)
    with pytest.raises(ValueError, match="mesh.seq"):
        _chunk_attention(q, q, q, bias)


def _streaming_reference(q, k, v, bias=None, segment_ids=None, block=128):
    """O(S·block)-memory full-attention reference (f32, logsumexp-stable):
    independent of both kernel families, cheap enough for S≫4096 where
    the (S,S)-materializing dot_product_attention reference would OOM."""
    b, s, h, d = q.shape
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)   # (B,H,S,D)
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    scale = 1.0 / (d ** 0.5)

    def one_block(qb_seg):
        qb, sb = qb_seg                                 # (B,H,block,D)
        sc = jnp.einsum("bhqd,bhkd->bhqk", qb, kf) * scale
        if bias is not None:
            sc = sc + bias[:, None, None, :]
        if segment_ids is not None:
            sc = jnp.where(
                sb[:, None, :, None] == segment_ids[:, None, None, :],
                sc, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(sc, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vf)

    qs = qf.reshape(b, h, s // block, block, d).transpose(2, 0, 1, 3, 4)
    if segment_ids is not None:
        segs = segment_ids.reshape(b, s // block, block).transpose(1, 0, 2)
    else:
        segs = jnp.zeros((s // block, b, block), jnp.int32)
    out = jax.lax.map(one_block, (qs, segs))            # (nb,B,H,block,D)
    out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)    # (B,S,H,D)


def test_kblocked_kernels_match_whole_k(devices, monkeypatch):
    """Forcing the K-blocked streaming kernels (MAX_SEQ_VMEM→128) on a
    shape the whole-K kernels handle must reproduce the XLA reference for
    output AND q/k/v grads — with a key mask in play."""
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "MAX_SEQ_VMEM", 128)
    # Pin the streaming tiles to 128 so s=384 gives a REAL 3-step
    # k-grid; the production 512/1024 targets would degenerate this
    # shape to one block and never exercise the running-softmax
    # cross-block math (init / corr rescale / finalize).
    monkeypatch.setattr(fa, "BLOCK_Q_KB", 128)
    monkeypatch.setattr(fa, "BLOCK_K_KB", 128)
    q, k, v = _rand_qkv(jax.random.key(7), b=2, s=384, h=2, d=32)
    mask = jnp.ones((2, 1, 1, 384), bool).at[:, :, :, 300:].set(False)

    def loss_flash(q, k, v):
        out = fa.flash_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    out = fa.flash_attention(q, k, v, mask=mask)
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_fused_streaming_backward_matches_two_pass(devices, monkeypatch):
    """FLASH_FUSED_BWD one-pass backward (round 5): on a forced
    streaming shape (MAX_SEQ_VMEM→128, 128-tiles, s=384 → real 3×3
    (q,k) block grid) the fused kernel's q/k/v grads must match BOTH the
    two-pass streaming kernels and the XLA reference — with a key mask,
    in bf16, and segmented."""
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "MAX_SEQ_VMEM", 128)
    monkeypatch.setattr(fa, "BLOCK_Q_KB", 128)
    monkeypatch.setattr(fa, "BLOCK_K_KB", 128)
    q, k, v = _rand_qkv(jax.random.key(11), b=2, s=384, h=2, d=32)
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    mask = jnp.ones((2, 1, 1, 384), bool).at[:, :, :, 320:].set(False)
    seg = jnp.concatenate(
        [jnp.zeros((2, 200), jnp.int32), jnp.ones((2, 184), jnp.int32)],
        axis=1)

    def loss(q, k, v, segment_ids=None):
        out = fa.flash_attention(q, k, v, mask=mask,
                                 segment_ids=segment_ids)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v, segment_ids=None):
        attn_mask = mask
        if segment_ids is not None:
            same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
            attn_mask = mask & same
        out = dot_product_attention(q, k, v, mask=attn_mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    for seg_ids in (None, seg):
        monkeypatch.setattr(fa, "FUSED_BWD", False)
        g_two = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, seg_ids)
        monkeypatch.setattr(fa, "FUSED_BWD", True)
        g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, seg_ids)
        for name, a, b in zip("qkv", g_fused, g_two):
            # Identical block math, identical accumulation order → the
            # two backward paths should agree to bf16 round-off.
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-2, atol=2e-2,
                err_msg=f"d{name} seg={seg_ids is not None}")
        # And DIRECTLY against the XLA reference — agreement with the
        # two-pass path alone would not catch a defect shared by both
        # streaming backwards (delta/bias plumbing upstream of the
        # kernels).
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v, seg_ids)
        for name, a, b in zip("qkv", g_fused, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=4e-2, atol=4e-2,
                err_msg=f"d{name} vs ref, seg={seg_ids is not None}")


def test_fused_streaming_backward_gate(devices, monkeypatch):
    """The fused path only engages below FUSED_BWD_MAX; above it the
    two-pass kernels run even with the flag armed (VMEM accumulators
    would not fit) — pinned by checking grads still match the XLA
    reference with an absurdly low gate."""
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "MAX_SEQ_VMEM", 128)
    monkeypatch.setattr(fa, "BLOCK_Q_KB", 128)
    monkeypatch.setattr(fa, "BLOCK_K_KB", 128)
    monkeypatch.setattr(fa, "FUSED_BWD", True)
    monkeypatch.setattr(fa, "FUSED_BWD_MAX", 256)  # s=384 exceeds it
    q, k, v = _rand_qkv(jax.random.key(13), b=1, s=384, h=2, d=32)

    # Spy on the fused builder: correctness alone cannot distinguish the
    # paths (both produce right grads at this shape) — pin the DISPATCH.
    calls = []
    orig = fa._flash_bwd_fused_kb
    monkeypatch.setattr(
        fa, "_flash_bwd_fused_kb",
        lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1])

    def loss_flash(q, k, v):
        out = fa.flash_attention(q, k, v)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    assert not calls, "fused kernel ran above FUSED_BWD_MAX"
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")
    # Raising the gate back over s flips the dispatch to the fused path.
    monkeypatch.setattr(fa, "FUSED_BWD_MAX", 8192)
    jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    assert calls, "fused kernel did not run below FUSED_BWD_MAX"


def test_fused_backward_takes_over_whole_k_regime(devices, monkeypatch):
    """FUSED_WHOLE_K_MIN routing (round 5): for mid-length sequences the
    fused one-pass streaming backward REPLACES the whole-K two-pass even
    though the sequence fits VMEM (s ≤ MAX_SEQ_VMEM) — it pays one fewer
    S² exp. Scaled-down constants stand in for the real ones
    (MIN 256 / VMEM 1024 ≈ 2048 / 4096): s=384 sits in the whole-K
    regime but above the fused takeover. Pins the DISPATCH via a spy and
    the numerics against both the whole-K two-pass and the XLA
    reference, masked and segmented."""
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "MAX_SEQ_VMEM", 1024)
    monkeypatch.setattr(fa, "BLOCK_Q_KB", 128)
    monkeypatch.setattr(fa, "BLOCK_K_KB", 128)
    monkeypatch.setattr(fa, "FUSED_BWD", True)
    q, k, v = _rand_qkv(jax.random.key(17), b=2, s=384, h=2, d=32)
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    mask = jnp.ones((2, 1, 1, 384), bool).at[:, :, :, 320:].set(False)
    seg = jnp.concatenate(
        [jnp.zeros((2, 200), jnp.int32), jnp.ones((2, 184), jnp.int32)],
        axis=1)

    calls = []
    orig = fa._flash_bwd_fused_kb
    monkeypatch.setattr(
        fa, "_flash_bwd_fused_kb",
        lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1])

    def loss(q, k, v, segment_ids=None):
        out = fa.flash_attention(q, k, v, mask=mask,
                                 segment_ids=segment_ids)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v, segment_ids=None):
        attn_mask = mask
        if segment_ids is not None:
            same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
            attn_mask = mask & same
        out = dot_product_attention(q, k, v, mask=attn_mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    # Below the takeover threshold the whole-K two-pass still runs.
    monkeypatch.setattr(fa, "FUSED_WHOLE_K_MIN", 512)  # s=384 below it
    g_whole = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, None)
    assert not calls, "fused kernel ran below FUSED_WHOLE_K_MIN"

    # At/above it the fused streaming backward takes over — in the
    # whole-K regime (384 ≤ MAX_SEQ_VMEM=1024).
    monkeypatch.setattr(fa, "FUSED_WHOLE_K_MIN", 256)
    for seg_ids in (None, seg):
        calls.clear()
        g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, seg_ids)
        assert calls, "fused kernel did not take over the whole-K regime"
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v, seg_ids)
        # vs the XLA reference always; vs the whole-K two-pass where one
        # was computed (unsegmented arm) — the distinct comparison.
        pairs = [("ref", g_ref)] + ([("whole-k", g_whole)]
                                    if seg_ids is None else [])
        for tag, ref in pairs:
            for name, a, b in zip("qkv", g_fused, ref):
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    rtol=4e-2, atol=4e-2,
                    err_msg=f"d{name} vs {tag}, seg={seg_ids is not None}")


def test_pick_block_divisor_policy():
    """Streaming-tile picker: largest 128-multiple ≤ target dividing s;
    sub-128 env targets clamp to 128 instead of dividing by zero; short
    sequences pass through whole."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        _pick_block,
    )

    assert _pick_block(8192, 1024) == 1024
    assert _pick_block(8192, 512) == 512
    assert _pick_block(4224, 1024) == 384      # 33·128: divisor fallback
    assert _pick_block(4352, 1024) == 256      # 34·128: 2·128 divides
    assert _pick_block(256, 64) == 128         # sub-128 target clamps
    assert _pick_block(96, 1024) == 96         # short chunk passes through


def test_bf16_inputs_match_f32_reference(devices, monkeypatch):
    """Production dtype through BOTH kernel regimes: the round-4 kernels
    dot in the INPUT dtype (bf16 on TPU) and downcast the p/ds softmax
    intermediates — paths every f32 test reduces to a no-op. Pin bf16
    fwd+grads against the f32 reference of the same bf16 values at
    bf16-resolution tolerance."""
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa

    # Distinct seq per regime: identical shapes would let the second
    # regime hit the first's jit cache and silently re-test whole-K.
    # s=384 under MAX_SEQ_VMEM=128 also makes the k-blocked arm a real
    # 3-step streaming grid.
    for regime, seq_vmem, s in (("whole-K", 4096, 256),
                                ("k-blocked", 128, 384)):
        q, k, v = _rand_qkv(jax.random.key(11), b=2, s=s, h=2, d=32,
                            dtype=jnp.bfloat16)
        mask = jnp.ones((2, 1, 1, s), bool).at[:, :, :, s - 56:].set(False)
        qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))

        def loss_flash(q, k, v):
            out = fa.flash_attention(q, k, v, mask=mask)
            return jnp.sum(jnp.sin(out.astype(jnp.float32)))

        def loss_ref(q, k, v):
            out = dot_product_attention(q, k, v, mask=mask)
            return jnp.sum(jnp.sin(out.astype(jnp.float32)))

        ref = dot_product_attention(qf, kf, vf, mask=mask)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
        with monkeypatch.context() as mp:
            mp.setattr(fa, "MAX_SEQ_VMEM", seq_vmem)
            mp.setattr(fa, "BLOCK_Q_KB", 128)
            mp.setattr(fa, "BLOCK_K_KB", 128)
            out = fa.flash_attention(q, k, v, mask=mask)
            np.testing.assert_allclose(
                np.asarray(out, np.float32), np.asarray(ref),
                rtol=2e-2, atol=2e-2, err_msg=regime)
            g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            for name, a, b in zip("qkv", g_fl, g_ref):
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b),
                    rtol=6e-2, atol=6e-2, err_msg=f"{regime} d{name}")


@pytest.mark.parametrize("fused", [False, True])
def test_kblocked_segmented_ring_matches_reference(devices, monkeypatch,
                                                   fused):
    """Packed segments + ring + K-blocked chunk kernels: force every ring
    chunk through the streaming kernels (MAX_SEQ_VMEM→64, FLASH_CHUNK_MIN
    →0) and pin output + grads against the segment-aware reference.
    ``fused=True`` repeats the composition through the one-pass backward —
    covering the ring-merge dlse→delta folding + segments on that path."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.ops import flash_attention as fa
    from distributed_tensorflow_framework_tpu.parallel import ring

    monkeypatch.setattr(fa, "FUSED_BWD", fused)
    # chunk = 256/4 = 64 > MAX_SEQ_VMEM(32) → K-blocked kernels with a
    # 16-wide block grid (nq = nk = 4), segments riding along.
    monkeypatch.setattr(fa, "MAX_SEQ_VMEM", 32)
    monkeypatch.setattr(fa, "BLOCK_Q", 16)
    monkeypatch.setattr(fa, "BLOCK_K", 16)
    monkeypatch.setattr(fa, "BLOCK_Q_KB", 16)
    monkeypatch.setattr(fa, "BLOCK_K_KB", 16)
    monkeypatch.setattr(ring, "FLASH_CHUNK_MIN", 0)
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    b, s = 2, 256
    q, k, v = _rand_qkv(jax.random.key(8), b=b, s=s, h=2, d=16)
    # Packed segments crossing the shard boundary at s/2.
    seg = jnp.concatenate([
        jnp.zeros((b, 96), jnp.int32),
        jnp.ones((b, 96), jnp.int32),
        jnp.full((b, 64), 2, jnp.int32),
    ], axis=1)

    def loss_ring(q, k, v):
        out = ring.ring_attention_sharded(q, k, v, mesh=mesh,
                                          segment_ids=seg)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = _streaming_reference(q, k, v, segment_ids=seg, block=64)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    out = jax.jit(lambda q, k, v: ring.ring_attention_sharded(
        q, k, v, mesh=mesh, segment_ids=seg))(q, k, v)
    ref = _streaming_reference(q, k, v, segment_ids=seg, block=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


@pytest.mark.slow
def test_ring_chunk_8192_kblocked(devices):
    """The closed fallback, at the size that motivated it (VERDICT r3
    item 4): a ring whose per-shard chunk is 8192 (> MAX_SEQ_VMEM) runs
    the K-blocked flash kernels — fwd AND bwd — and matches the streaming
    reference. Interpret mode on the CPU mesh."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring

    mesh = create_mesh(MeshConfig(data=4, seq=2))
    b, s, h, d = 4, 16384, 1, 8                   # chunk = 8192 per shard
    q, k, v = _rand_qkv(jax.random.key(9), b=b, s=s, h=h, d=d)

    out = jax.jit(lambda q, k, v: ring.ring_attention_sharded(
        q, k, v, mesh=mesh))(q, k, v)
    ref = _streaming_reference(q, k, v, block=512)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(q):
        out = ring.ring_attention_sharded(q, k, v, mesh=mesh)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q):
        out = _streaming_reference(q, k, v, block=512)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    gq = jax.jit(jax.grad(loss))(q)
    gq_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(gq_ref),
                               rtol=2e-4, atol=2e-4)
