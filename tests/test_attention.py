"""Attention implementation parity: pallas and ring vs the XLA reference
(SURVEY.md §4 numerics-parity strategy applied to the attention kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.models.bert import dot_product_attention


def _rand_qkv(key, b=2, s=256, h=4, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def test_flash_attention_matches_xla(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_with_mask(devices):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(1), s=128)
    mask = jnp.ones((2, 1, 1, 128), bool).at[:, :, :, 100:].set(False)
    ref = dot_product_attention(q, k, v, mask=mask)
    out = flash_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_backward_matches_xla(devices):
    """The Pallas backward kernels (dq + dkv, online recompute) must match
    XLA autodiff through the reference attention — for q, k AND v."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v = _rand_qkv(jax.random.key(3), s=256)
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 200:].set(False)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_flash_attention_backward_no_quadratic_residual(devices):
    """Structural check on the VJP residuals: nothing score-matrix-shaped
    (S×S) is saved between forward and backward."""
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    b, s, h, d = 2, 512, 4, 64
    q, k, v = _rand_qkv(jax.random.key(4), b=b, s=s, h=h, d=d)
    _, vjp = jax.vjp(lambda q, k, v: flash_attention(q, k, v), q, k, v)
    # Residuals captured by the VJP closure: all must be O(S·D)/O(S) —
    # a score-shaped residual would have TWO sequence-length axes.
    leaves = [x for x in jax.tree.leaves(vjp) if hasattr(x, "shape")]
    assert leaves, "vjp closure has no residuals?"
    for leaf in leaves:
        seq_axes = sum(1 for dim in leaf.shape if dim == s)
        assert seq_axes <= 1, f"score-matrix-shaped residual: {leaf.shape}"
        assert leaf.size <= b * h * s * d, (
            f"residual {leaf.shape} larger than any O(S·D) tensor"
        )


def test_ring_attention_matches_xla(devices):
    """Ring attention over a seq=8 mesh axis reproduces full attention."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(2), b=2, s=256, h=2, d=32)
    ref = dot_product_attention(q, k, v)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_mask_and_gradients(devices):
    """Ring attention under a key mask must match XLA attention for the
    output AND the q/k/v gradients (the training path differentiates
    through the ppermute ring — previously only the forward was pinned)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _rand_qkv(jax.random.key(5), b=2, s=256, h=2, d=32)
    # Mask out the last 40 keys (cuts across the final ring shard).
    mask = jnp.ones((2, 1, 1, 256), bool).at[:, :, :, 216:].set(False)

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    out_ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh=mesh, mask=mask)
    )(q, k, v)
    out_ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")
