"""Fleet autoscaling + multi-tenant QoS (serve/autoscale.py and the
router's actuation of it) — tier-1 coverage with stub replicas.

Three layers, mirroring how the feature is built:

  * the PURE policy: Autoscaler hysteresis/cooldown/bounds and the
    TenantQuotas token buckets, driven with explicit clocks so every
    decision is deterministic (including the one-remaining-token race);
  * the router's QoS front door over in-process stub replicas: 429 +
    Retry-After on quota breach, priority-ordered 503 shedding at exact
    capacity, X-DTF-Model pinned routing, scoped rolling reloads;
  * the router's actuation of scale decisions through a fake launcher
    (spawn one / drain one / never fight the restart supervisor) and
    the KIND_SCALE / KIND_ADMISSION telemetry rollups.

The real thing — subprocess replicas scaling under a shaped load spike
with a mid-scale kill — is the slow drill in test_autoscale_drill.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.core.config import ServeConfig
from distributed_tensorflow_framework_tpu.serve import autoscale
from distributed_tensorflow_framework_tpu.serve.fleet import FleetRouter

pytestmark = pytest.mark.serve


# ------------------------------------------------------ policy: scaling


def _snap(**kw):
    base = dict(admitted=2, alive=2, booting=0, draining=0, give_up=0,
                load=0.0, capacity=8, shed_delta=0)
    base.update(kw)
    return autoscale.FleetSnapshot(**base)


def _asc(**kw):
    base = dict(min_replicas=1, max_replicas=4, up_threshold=0.75,
                down_threshold=0.25, cooldown_s=10.0, now=100.0)
    base.update(kw)
    return autoscale.Autoscaler(**base)


def test_priority_classes_and_header_mapping():
    assert autoscale.priority_of("high") == 0
    assert autoscale.priority_of("default") == 1
    assert autoscale.priority_of("batch") == 2
    # The class is the prefix before ":" — the suffix names the tenant.
    assert autoscale.priority_of("batch:nightly-eval") == 2
    # Unknown classes degrade to the configured default, never to high.
    assert autoscale.priority_of("gold-customer") == 1
    assert autoscale.priority_of(None) == 1
    assert autoscale.priority_of("typo", default_class="batch") == 2


def test_autoscaler_rejects_degenerate_knobs():
    with pytest.raises(ValueError, match="min_replicas"):
        _asc(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        _asc(min_replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        _asc(up_threshold=0.25, down_threshold=0.75)


def test_scale_up_on_pressure_bounded_by_max():
    asc = _asc()
    # First decision is allowed immediately (no cold-start cooldown).
    decision = asc.decide(_snap(load=14.0), now=100.0)  # 14/16 = 0.875
    assert decision.action == "up"
    assert (decision.from_replicas, decision.to_replicas) == (2, 3)
    assert decision.pressure == pytest.approx(0.875)
    # At the max bound the same pressure produces nothing.
    asc2 = _asc(max_replicas=2)
    assert asc2.decide(_snap(load=14.0), now=100.0) is None


def test_shed_delta_is_saturation_whatever_the_queues_say():
    asc = _asc()
    decision = asc.decide(_snap(load=0.0, shed_delta=3), now=100.0)
    assert decision is not None and decision.action == "up"
    assert decision.pressure >= asc.up_threshold


def test_cooldown_spaces_actions():
    asc = _asc(cooldown_s=10.0)
    assert asc.decide(_snap(load=14.0), now=100.0).action == "up"
    # Inside the cooldown window: still saturated, still silent.
    assert asc.decide(_snap(load=14.0, alive=3, admitted=3),
                      now=104.0) is None
    assert asc.decide(_snap(load=20.0, alive=3, admitted=3),
                      now=110.0).action == "up"


def test_hysteresis_band_holds_steady():
    asc = _asc()
    # Pressure between the thresholds: no action in either direction.
    assert asc.decide(_snap(load=8.0), now=100.0) is None  # 0.5
    assert asc.last_pressure == pytest.approx(0.5)


def test_scale_down_bounded_by_min_and_paused_while_draining():
    asc = _asc(cooldown_s=0.0)
    decision = asc.decide(_snap(load=1.0), now=100.0)  # 1/16 = 0.0625
    assert decision.action == "down"
    assert (decision.from_replicas, decision.to_replicas) == (2, 1)
    # At the min bound idleness produces nothing.
    assert asc.decide(_snap(admitted=1, alive=1, load=0.0),
                      now=101.0) is None
    # A drain already in progress must finish before the next verdict.
    assert asc.decide(_snap(load=0.0, draining=1), now=102.0) is None


def test_booting_replica_pauses_decisions():
    # The spawned-but-not-admitted replica already fills the gap the
    # pressure shows — deciding again would double-spawn for one spike.
    asc = _asc()
    assert asc.decide(_snap(load=16.0, booting=1, alive=3),
                      now=100.0) is None


def test_crash_loop_verdict_blocks_scale_up():
    asc = _asc()
    assert asc.decide(_snap(load=16.0, give_up=1), now=100.0) is None
    # ...but scale-DOWN still works: shrinking a broken fleet is fine.
    asc2 = _asc(cooldown_s=0.0)
    decision = asc2.decide(_snap(load=0.0, give_up=1), now=100.0)
    assert decision is not None and decision.action == "down"


def test_supervision_owns_the_nothing_admitted_phase():
    asc = _asc()
    assert asc.decide(_snap(admitted=0, alive=2, booting=2, load=0.0,
                            shed_delta=5), now=100.0) is None


# ------------------------------------------------------- policy: quotas


def test_quota_refills_across_clock_ticks():
    q = autoscale.TenantQuotas(2.0, burst=2)
    t = 100.0
    assert q.admit("batch", now=t).ok
    assert q.admit("batch", now=t).ok
    verdict = q.admit("batch", now=t)
    assert not verdict.ok
    # An honest Retry-After: one token refills in 1/rate seconds.
    assert verdict.retry_after_s == pytest.approx(0.5)
    # Partial refill is not enough for a whole token.
    half = q.admit("batch", now=t + 0.25)
    assert not half.ok and half.retry_after_s == pytest.approx(0.25)
    # A full tick later the bucket admits again...
    assert q.admit("batch", now=t + 0.75).ok
    # ...and a stale (non-monotonic) clock never drains or refills:
    # the 0.5 tokens left after the last admit are still exactly 0.5.
    stale = q.admit("batch", now=t)
    assert not stale.ok and stale.retry_after_s == pytest.approx(0.25)
    # Buckets are per tenant: an unrelated tenant starts full.
    assert q.admit("high", now=t).ok


def test_quota_concurrent_race_for_one_remaining_token():
    # burst=1 and a negligible rate: exactly one of N racing requests
    # may win the single token, no matter the interleaving.
    q = autoscale.TenantQuotas(1e-9, burst=1)
    start = threading.Barrier(12)
    verdicts = []
    lock = threading.Lock()

    def worker():
        start.wait()
        v = q.admit("batch", now=500.0)
        with lock:
            verdicts.append(v.ok)

    threads = [threading.Thread(target=worker) for _ in range(12)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(10)
    assert sum(verdicts) == 1 and len(verdicts) == 12


def test_quota_disabled_at_rate_zero():
    q = autoscale.TenantQuotas(0.0)
    assert not q.enabled
    for _ in range(100):
        assert q.admit("anyone").ok
    assert q.snapshot() == {}


def test_quota_burst_defaults_to_ceil_of_rate():
    assert autoscale.TenantQuotas(2.5).burst == 3
    assert autoscale.TenantQuotas(0.2).burst == 1
    assert autoscale.TenantQuotas(2.0, burst=7).burst == 7


# ------------------------------------------------- traffic-shaped chaos


def test_traffic_fault_specs_parse():
    plan = faults.FaultPlan.parse("spike:6:8s,tenant_stampede:3:4s")
    spike, stampede = plan.faults
    assert spike.kind == "spike" and spike.point == "fleet_chaos"
    assert spike.factor == 6.0 and spike.seconds == 8.0
    assert spike.step == 1  # the spike starts at fleet readiness
    assert stampede.kind == "tenant_stampede"
    assert stampede.point == "fleet_chaos"
    assert stampede.step == 3 and stampede.seconds == 4.0
    # Duration is optional for the stampede (default 5s).
    assert faults.FaultPlan.parse("tenant_stampede:2").faults[0].seconds \
        == 5.0


def test_traffic_fault_specs_validate():
    with pytest.raises(ValueError, match="factor"):
        faults.FaultPlan.parse("spike:0:8s")
    with pytest.raises(ValueError, match="duration"):
        faults.FaultPlan.parse("spike:6:0")
    with pytest.raises(ValueError, match="factor:seconds"):
        faults.FaultPlan.parse("spike:nope")
    with pytest.raises(ValueError, match="tick"):
        faults.FaultPlan.parse("tenant_stampede:0")


# ----------------------------------------------- router QoS front door


class StubReplica:
    """Minimal scriptable replica for QoS tests: settable ``digest``
    (the model it claims to serve) and ``queue_depth`` (the load it
    self-reports), plus the /reload contract the rolling roll needs."""

    def __init__(self, digest="digest-v1"):
        outer = self
        self.digest = digest
        self.queue_depth = 0
        self.predicts = 0
        self.reloads = 0
        self.lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                with outer.lock:
                    digest, depth = outer.digest, outer.queue_depth
                self._reply(200, {
                    "status": "ok", "task": "classify", "model": "stub",
                    "step": 1, "vocab_size": 10,
                    "input_spec": {"image": {"shape": [4], "dtype": "f32"}},
                    "artifact": {"step": 1, "content_digest": digest,
                                 "param_spec_digest": "spec",
                                 "reloads": outer.reloads},
                    "engine": {"state": "running", "queue_depth": depth,
                               "requests": outer.predicts},
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if self.path == "/reload":
                    payload = json.loads(body)
                    with outer.lock:
                        outer.reloads += 1
                        outer.digest = "digest-" + payload["artifact_dir"]
                        to_digest = outer.digest
                    self._reply(200, {"reloaded": True,
                                      "to_digest": to_digest})
                    return
                with outer.lock:
                    outer.predicts += 1
                self._reply(200, {"outputs": [[0.0]], "rows": 1, "step": 1})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


class FakeProc:
    """Stands in for a launcher-spawned subprocess: alive until the
    router terminates it (scale-down retirement or shutdown)."""

    def __init__(self):
        self.terminated = False

    def poll(self):
        return None

    def terminate(self):
        self.terminated = True

    kill = terminate

    def wait(self, timeout=None):
        return 0


def _router(stubs, *, writer=None, serve=False, launcher=None, **knobs):
    base = {"port": 0, "fleet_probe_interval_s": 0.1, "fleet_retries": 2,
            "fleet_retry_backoff_ms": 5.0, "fleet_eject_failures": 2,
            "fleet_deadline_s": 10.0, "fleet_attempt_timeout_s": 5.0,
            "fleet_healthz_stale_s": 2.0}
    base.update(knobs)
    router = FleetRouter(ServeConfig(**base), telemetry_writer=writer,
                         launcher=launcher)
    for stub in stubs:
        rep = router.add_replica(url=stub.url, admitted=True)
        # What the prober would have learned from /healthz, injected so
        # claim decisions are deterministic without a polling thread.
        with router._lock:
            rep.last_health = {
                "artifact": {"content_digest": stub.digest},
                "engine": {"queue_depth": stub.queue_depth},
            }
    thread = None
    if serve:
        thread = threading.Thread(target=router.serve_forever, daemon=True)
        thread.start()
    return router, thread


def _post(url, payload, headers=None, timeout=20.0):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


@pytest.fixture
def teardown():
    routers, stubs = [], []
    yield routers, stubs
    for router, thread in routers:
        router.shutdown("test teardown")
        if thread is not None:
            thread.join(10)
    for stub in stubs:
        stub.close()


def test_quota_breach_answers_429_with_retry_after(teardown):
    routers, stubs = teardown
    stubs.append(StubReplica())
    router, thread = _router(stubs, serve=True, tenant_quota_rps=1.0,
                             tenant_quota_burst=1)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    body = {"inputs": {"image": [[1.0]]}}
    status, _, _ = _post(url, body, headers={"X-DTF-Tenant": "high:team-a"})
    assert status == 200
    status, out, headers = _post(url, body,
                                 headers={"X-DTF-Tenant": "high:team-a"})
    assert status == 429
    assert out["retryable"] is True and out["tenant"] == "high:team-a"
    assert 0 < float(headers["Retry-After"]) <= 1.0
    # Buckets are per tenant — another tenant still rides through.
    status, _, _ = _post(url, body, headers={"X-DTF-Tenant": "batch:etl"})
    assert status == 200
    tenants = router.fleet_healthz()["fleet"]["tenants"]
    assert tenants["high:team-a"] == {"routed": 1, "shed": 0,
                                      "quota_rejected": 1}
    assert tenants["batch:etl"]["routed"] == 1


def test_shedding_is_priority_ordered_at_exact_capacity(teardown):
    # One replica self-reporting queue_depth=2 with capacity 3 and a
    # reserve of 1: batch may claim below 1, default below 2, high below
    # 3 — so at this exact load batch and default shed while high rides.
    routers, stubs = teardown
    stub = StubReplica()
    stub.queue_depth = 2
    stubs.append(stub)
    router, thread = _router(stubs, serve=True, queue_capacity=3,
                             tenant_priority_reserve=1,
                             fleet_shed_retry_after_s=1.5)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    body = {"inputs": {"image": [[1.0]]}}
    for tenant, expect in (("batch", 503), ("default", 503), ("high", 200)):
        status, out, headers = _post(url, body,
                                     headers={"X-DTF-Tenant": tenant})
        assert status == expect, tenant
        if expect == 503:
            assert out["shed"] is True and out["tenant"] == tenant
            assert headers["Retry-After"] == "1.5"
    tenants = router.fleet_healthz()["fleet"]["tenants"]
    assert tenants["batch"]["shed"] == 1
    assert tenants["default"]["shed"] == 1
    assert tenants["high"] == {"routed": 1, "shed": 0, "quota_rejected": 0}


def test_tenant_stampede_window_spares_reserved_headroom(teardown):
    # The chaos window injects synthetic load equal to every unreserved
    # queue slot: batch/default shed, high's reserve keeps flowing, and
    # when the window closes everyone routes again.
    routers, stubs = teardown
    stubs.append(StubReplica())
    router, thread = _router(stubs, serve=True, queue_capacity=4,
                             tenant_priority_reserve=1)
    routers.append((router, thread))
    fault = faults.FaultPlan.parse("tenant_stampede:1:30s").faults[0]
    router._apply_chaos(fault)
    url = f"http://{router.host}:{router.port}"
    body = {"inputs": {"image": [[1.0]]}}
    assert _post(url, body, headers={"X-DTF-Tenant": "batch"})[0] == 503
    assert _post(url, body, headers={"X-DTF-Tenant": "default"})[0] == 503
    assert _post(url, body, headers={"X-DTF-Tenant": "high"})[0] == 200
    with router._lock:  # close the window: back to classless service
        router._stampede_until = 0.0
    assert _post(url, body, headers={"X-DTF-Tenant": "batch"})[0] == 200


def test_model_header_pins_routing_and_models_rollup(teardown):
    routers, stubs = teardown
    stubs.extend([StubReplica(digest="modelA-1111"),
                  StubReplica(digest="modelB-2222")])
    router, thread = _router(stubs, serve=True)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    body = {"inputs": {"image": [[1.0]]}}
    for _ in range(3):
        status, _, headers = _post(url, body,
                                   headers={"X-DTF-Model": "modelA"})
        assert status == 200 and headers["X-DTF-Replica"] == "r0"
    status, _, headers = _post(url, body, headers={"X-DTF-Model": "modelB"})
    assert status == 200 and headers["X-DTF-Replica"] == "r1"
    # A digest prefix nothing serves is saturation FOR THAT MODEL: shed.
    assert _post(url, body, headers={"X-DTF-Model": "modelC"})[0] == 503
    models = router.fleet_healthz()["fleet"]["models"]
    assert models["modelA-1111"] == {"replicas": 1, "routed": 3}
    assert models["modelB-2222"] == {"replicas": 1, "routed": 1}


def test_rolling_reload_scoped_by_digest_and_count(teardown):
    routers, stubs = teardown
    stubs.extend([StubReplica(digest="modelA-1111"),
                  StubReplica(digest="modelB-2222")])
    router, thread = _router(stubs)
    routers.append((router, thread))
    # Scope by digest: only the modelB replica rolls; modelA untouched.
    results, ok = router.rolling_reload("v2", only_digest="modelB")
    assert ok is True
    assert [r["replica"] for r in results] == ["r1"]
    assert stubs[0].reloads == 0 and stubs[1].reloads == 1
    # Scope by count: exactly one replica rolls (the first in order).
    results, ok = router.rolling_reload("v3", count=1)
    assert ok is True
    assert [r["replica"] for r in results] == ["r0"]
    assert stubs[0].reloads == 1 and stubs[1].reloads == 1


# --------------------------------------------------- router actuation


def test_router_scales_up_then_drains_back_down(tmp_path, teardown):
    routers, stubs = teardown
    stubs.extend([StubReplica(), StubReplica()])
    procs = {}

    def launcher(index):
        procs[index] = FakeProc()
        endpoint = tmp_path / f"r{index}-endpoint.json"
        endpoint.write_text(json.dumps({"url": stubs[index].url}))
        return procs[index], str(endpoint)

    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    router, thread = _router(stubs[:1], writer=writer, launcher=launcher,
                             queue_capacity=8, fleet_autoscale=True,
                             fleet_min_replicas=1, fleet_max_replicas=2,
                             fleet_scale_up_threshold=0.5,
                             fleet_scale_down_threshold=0.2,
                             fleet_scale_cooldown_s=0.0,
                             drain_timeout_s=5.0)
    routers.append((router, thread))
    # Saturate the one admitted replica: 7/8 queue slots full.
    with router._lock:
        router._replicas[0].last_health["engine"]["queue_depth"] = 7
    router._autoscale_tick(time.monotonic())
    with router._lock:
        states = [r.state for r in router._replicas]
    assert states == ["admitted", "ejected"]  # spawned, not yet admitted
    # A booting replica pauses the loop — no double-spawn for one gap.
    router._autoscale_tick(time.monotonic())
    with router._lock:
        assert len(router._replicas) == 2
    # The prober's probe admits the spawn once its /healthz answers.
    router._probe_replica(router._replicas[1], time.monotonic())
    with router._lock:
        assert router._replicas[1].state == "admitted"
    # Load gone: the loop drains the NEWEST replica back out...
    with router._lock:
        router._replicas[0].last_health["engine"]["queue_depth"] = 0
    router._autoscale_tick(time.monotonic())
    with router._lock:
        victim = router._replicas[1]
        assert victim.state == "draining" and victim.retiring
    # ...and holds further verdicts until the drain completes.
    router._autoscale_tick(time.monotonic())
    router._advance_retirements(time.monotonic())
    with router._lock:
        assert victim.state == "retired"
    assert procs[1].terminated  # retirement SIGTERMs the subprocess
    health = router.fleet_healthz()["fleet"]
    assert health["router"]["scale_ups"] == 1
    assert health["router"]["scale_downs"] == 1
    assert health["autoscale"] == {"enabled": True, "min_replicas": 1,
                                   "max_replicas": 2,
                                   "pressure": health["autoscale"]["pressure"]}
    writer.close()
    summary = telemetry.summarize_events(events)
    scaling = summary["fleet"]["scaling"]
    assert scaling["ups"] == 1 and scaling["downs"] == 1
    assert [e["action"] for e in scaling["events"]] == ["up", "down"]


def test_spike_window_raises_pressure_without_touching_traffic(teardown):
    routers, stubs = teardown
    stubs.append(StubReplica())
    router, thread = _router(stubs, serve=True, queue_capacity=8,
                             fleet_autoscale=True, fleet_min_replicas=1,
                             fleet_max_replicas=2,
                             fleet_scale_up_threshold=0.5,
                             fleet_scale_down_threshold=0.2,
                             fleet_scale_cooldown_s=0.0)
    routers.append((router, thread))
    router._apply_chaos(faults.FaultPlan.parse("spike:6:30s").faults[0])
    # No launcher: the decision is logged and skipped, but the policy
    # saw the synthetic pressure (6 fake queued requests over 8 slots).
    router._autoscale_tick(time.monotonic())
    assert router._autoscaler.last_pressure == pytest.approx(0.75)
    with router._lock:
        assert len(router._replicas) == 1  # nothing to actuate with
    # The spike feeds ONLY the autoscaler: real requests route fine.
    url = f"http://{router.host}:{router.port}"
    status, _, _ = _post(url, {"inputs": {"image": [[1.0]]}},
                         headers={"X-DTF-Tenant": "batch"})
    assert status == 200


# ------------------------------------------------------- telemetry


def test_scale_and_admission_telemetry_rollup(tmp_path):
    """KIND_SCALE / KIND_ADMISSION / tenant-tagged KIND_SERVE_ROUTE
    aggregate into the summary's fleet section and the human rollup."""
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    writer.emit(telemetry.KIND_SCALE, metrics={"pressure": 0.91},
                action="up", reason="pressure 0.910 >= 0.75",
                replica="r2", from_replicas=2, to_replicas=3)
    writer.emit(telemetry.KIND_SCALE, metrics={"pressure": 0.12},
                action="down", reason="pressure 0.120 <= 0.25",
                replica="r2", from_replicas=3, to_replicas=2)
    for lat in (4.0, 6.0, 8.0):
        writer.emit(telemetry.KIND_SERVE_ROUTE,
                    metrics={"latency_ms": lat, "retries": 0, "status": 200},
                    replica="r0", shed=False, deadline_exceeded=False,
                    tenant="high")
    writer.emit(telemetry.KIND_ADMISSION, tenant="batch", priority=2,
                verdict="shed", retry_after_s=1.0)
    writer.emit(telemetry.KIND_ADMISSION, tenant="default", priority=1,
                verdict="quota", retry_after_s=0.5)
    writer.close()
    summary = telemetry.summarize_events(events)
    fleet = summary["fleet"]
    assert fleet["scaling"]["ups"] == 1 and fleet["scaling"]["downs"] == 1
    assert fleet["scaling"]["events"][0] == {
        "action": "up", "reason": "pressure 0.910 >= 0.75",
        "replica": "r2", "from_replicas": 2, "to_replicas": 3,
        "pressure": 0.91}
    high = fleet["tenants"]["high"]
    assert high["routed"] == 3 and high["shed"] == 0
    assert high["latency_ms"]["p50"] == pytest.approx(6.0)
    assert fleet["tenants"]["batch"]["shed"] == 1
    assert fleet["tenants"]["default"]["quota_rejected"] == 1
    text = telemetry.format_run_summary(summary)
    assert "scaling: 1 up / 1 down" in text
    assert "up->3@0.91" in text
    assert "tenant high: routed 3" in text
    assert "tenant batch: routed 0, shed 1" in text
