"""Autoscaling + QoS chaos drill (tier-2): a self-regulating fleet
under a traffic spike, a tenant stampede, and a mid-scale replica kill.

The acceptance bar is the control-plane headline:

  * a synthetic traffic spike (``spike:6:600s``) pushes fleet pressure
    over the up-threshold and the autoscaler grows the fleet to
    ``fleet_max_replicas`` — one supervised spawn at a time, pausing
    while any replica boots;
  * replica 0 is SIGKILLed MID-scale-event (``kill_replica:0:12``):
    supervision restarts it, the autoscaler waits out the boot, and the
    fleet still converges on exactly max replicas — the two loops never
    fight over the same hole;
  * a tenant stampede (``tenant_stampede:4:6s``) saturates every
    unreserved queue slot: batch and default shed (503 + Retry-After)
    while the priority reserve keeps high-class traffic flowing — a
    steady high-tenant client runs the WHOLE drill with zero failures;
  * the spike ends and the autoscaler drains back to
    ``fleet_min_replicas`` through retirement (drain → SIGTERM), again
    with zero failed in-flight requests.

The router runs in-process (chaos via faults.install, deterministic
relative to fleet readiness); every replica is a real ``cli/serve.py``
subprocess via the cli/fleet launcher. Traffic is scripts/load_gen.py
with a shaped open-loop schedule and a weighted tenant mix; its bench
JSON (per-tenant attribution) and the router's events.jsonl are
archived to ``DTF_SERVE_BENCH_DIR`` for the tier driver.
"""

import copy
import json
import os
import pathlib
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
from test_train_lenet import lenet_config

from distributed_tensorflow_framework_tpu.cli.fleet import (
    make_replica_launcher,
)
from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.serve import FleetRouter, export_checkpoint
from distributed_tensorflow_framework_tpu.train import Trainer

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.slow, pytest.mark.serve]


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def _post(url, tenant, timeout=60.0):
    rng = np.random.default_rng(7)
    image = rng.normal(size=(1, 28, 28, 1)).astype(np.float32).tolist()
    body = json.dumps({"inputs": {"image": image}}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json",
                 "X-DTF-Tenant": tenant})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def test_autoscale_chaos_drill(devices, tmp_path):
    # 1. Train + export the serving artifact.
    cfg = lenet_config(**{
        "checkpoint.directory": str(tmp_path / "ckpt"),
        "checkpoint.async_save": False,
        "checkpoint.save_interval_steps": 10,
        "train.total_steps": 10,
    })
    trainer = Trainer(cfg)
    trainer.build()
    trainer.train()
    cfg.serve.data = 1
    cfg.serve.allow_reshard = True
    art_dir = export_checkpoint(cfg, str(tmp_path / "artifact"))

    # 2. Router in-process with the full control loop armed: autoscale
    # 2..4 replicas over a small queue_capacity (so synthetic chaos
    # load moves pressure meaningfully) and a 2-slot priority reserve.
    serve_cfg = copy.deepcopy(cfg.serve)
    serve_cfg.port = 0
    serve_cfg.fleet_replicas = 2
    serve_cfg.fleet_probe_interval_s = 0.25
    serve_cfg.fleet_eject_failures = 2
    serve_cfg.fleet_healthz_stale_s = 5.0
    serve_cfg.fleet_attempt_timeout_s = 8.0
    serve_cfg.fleet_deadline_s = 45.0
    serve_cfg.fleet_retries = 3
    serve_cfg.drain_timeout_s = 30.0
    serve_cfg.queue_capacity = 8
    serve_cfg.fleet_autoscale = True
    serve_cfg.fleet_min_replicas = 2
    serve_cfg.fleet_max_replicas = 4
    serve_cfg.fleet_scale_up_threshold = 0.5
    serve_cfg.fleet_scale_down_threshold = 0.2
    serve_cfg.fleet_scale_cooldown_s = 1.0
    serve_cfg.tenant_priority_reserve = 2
    log_dir = tmp_path / "fleet_logs"
    log_dir.mkdir()
    events_path = str(log_dir / "events.jsonl")
    writer = telemetry.TelemetryWriter(events_path)
    launcher = make_replica_launcher(
        art_dir, str(log_dir),
        ["serve.max_batch_size=8", "serve.max_wait_ms=5"])
    router = FleetRouter(serve_cfg, telemetry_writer=writer,
                         launcher=launcher)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    serve_thread = threading.Thread(target=router.serve_forever,
                                    daemon=True)
    # The steady high-tenant client: posts for the WHOLE drill — through
    # the stampede, the kill, every scale event — and must never fail.
    high_stop = threading.Event()
    high_failures: list = []
    high_ok = [0]

    def high_loop(url):
        while not high_stop.is_set():
            try:
                status, out, _ = _post(url, "high:sla-team")
                if status == 200:
                    high_ok[0] += 1
                else:
                    high_failures.append((status, out))
            except Exception as e:  # noqa: BLE001 — record, keep driving
                high_failures.append(repr(e))
            high_stop.wait(0.15)

    try:
        # Chaos BEFORE the prober starts: the clock arms at readiness.
        # spike opens immediately and stays open until the drill closes
        # it (pressure 6/8 = 0.75 per replica regardless of fleet size,
        # so scale-up must run all the way to max); the stampede opens
        # ~1s in for 6s; the kill lands ~3s in, mid-scale-event.
        faults.install("spike:6:600s,tenant_stampede:4:6s,kill_replica:0:12")
        router.spawn_replicas()
        serve_thread.start()
        router.start()
        assert router.wait_ready(timeout=240.0), router.fleet_healthz()
        url = f"http://{router.host}:{router.port}"
        high_thread = threading.Thread(target=high_loop, args=(url,),
                                       daemon=True)
        high_thread.start()

        def fleet():
            return router.fleet_healthz()["fleet"]

        # 3. QoS under the stampede: batch and default shed with an
        # honest Retry-After while high's reserved headroom routes.
        _wait(lambda: router._stampede_until > time.monotonic(), 30,
              "the tenant_stampede window to open")
        status, out, headers = _post(url, "batch:nightly-eval")
        assert status == 503, (status, out)
        assert out["shed"] is True and out["tenant"] == "batch:nightly-eval"
        assert float(headers["Retry-After"]) > 0
        status, _, _ = _post(url, "default")
        assert status == 503
        status, _, _ = _post(url, "high:sla-team")
        assert status == 200

        # 4. Scale-up to max under the spike, with r0 killed mid-event:
        # supervision restarts it (the autoscaler pauses on the boot),
        # and the fleet converges on EXACTLY max — 2 scale-ups, 4
        # replica slots total, nobody double-filled the dead slot.
        _wait(lambda: fleet()["admitted"] == 4, 240,
              "scale-up to fleet_max_replicas")
        _wait(lambda: fleet()["replicas"][0]["restarts"] >= 1, 60,
              "supervised restart of the killed replica")
        _wait(lambda: all(r["state"] == "admitted"
                          for r in fleet()["replicas"]), 240,
              "every replica (including the restarted one) admitted")
        snap = fleet()
        assert snap["router"]["scale_ups"] == 2, snap["router"]
        assert len(snap["replicas"]) == 4
        assert snap["autoscale"]["enabled"] is True
        assert snap["autoscale"]["max_replicas"] == 4

        # 5. Shaped open-loop load with a weighted tenant mix across the
        # scaled-up fleet; per-tenant attribution lands in the bench.
        bench_dir = os.environ.get("DTF_SERVE_BENCH_DIR")
        if bench_dir:
            os.makedirs(bench_dir, exist_ok=True)
            bench_path = os.path.join(bench_dir,
                                      "SERVE_BENCH_AUTOSCALE.json")
        else:
            bench_path = str(tmp_path / "SERVE_BENCH_AUTOSCALE.json")
        gen = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "load_gen.py"),
             "--endpoint", url, "--requests", "150", "--concurrency", "16",
             "--mode", "open", "--rate", "30", "--shape", "spike",
             "--spike-factor", "3",
             "--tenants", "high=1,default=2,batch=1",
             "--out", bench_path],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=600)
        assert gen.returncode == 0, gen.stdout + gen.stderr
        bench = json.loads(pathlib.Path(bench_path).read_text())
        assert bench["schema"] == "dtf-serve-bench/2"
        run = bench["runs"][0]
        assert run["shape"] == "spike"
        assert set(run["by_tenant"]) == {"high", "default", "batch"}
        # Zero high-priority sheds: every high-class request succeeded.
        assert run["by_tenant"]["high"]["errors"] == 0, run["by_tenant"]
        assert bench["fleet"]["tenants"]  # router ledger snapshot rode in

        # 6. Spike over: the autoscaler drains back to min through
        # retirement, zero failed in-flight (the high client is still
        # running and never sees an error).
        with router._lock:
            router._spike_until = 0.0
            router._stampede_until = 0.0
        _wait(lambda: fleet()["router"]["scale_downs"] == 2, 120,
              "two drain-based scale-downs")
        _wait(lambda: fleet()["admitted"] == 2, 120,
              "fleet back at fleet_min_replicas")
        # admitted==2 can precede the second victim finishing its drain
        # (draining -> retired happens on a later prober tick).
        _wait(lambda: [r["state"] for r in fleet()["replicas"]]
              .count("retired") == 2, 60,
              "both drained replicas retired")
        snap = fleet()
        states = [r["state"] for r in snap["replicas"]]
        assert states.count("retired") == 2, states
        assert states.count("admitted") == 2, states

        # 7. The steady high-tenant client saw ZERO failures across the
        # stampede, the kill, and both scale directions.
        high_stop.set()
        high_thread.join(60)
        assert not high_failures, high_failures[:5]
        assert high_ok[0] > 0

        # 8. Telemetry tells the whole story: the scaling timeline, the
        # per-tenant admission ledger, and the kill's eject/restart —
        # through analyze_trace --json, the drivers' surface.
        writer.close()
        summary = telemetry.summarize_events(events_path)
        scaling = summary["fleet"]["scaling"]
        assert scaling["ups"] == 2 and scaling["downs"] == 2
        assert [e["action"] for e in scaling["events"]] == [
            "up", "up", "down", "down"]
        assert all(e["pressure"] is not None for e in scaling["events"])
        tenants = summary["fleet"]["tenants"]
        assert tenants["high:sla-team"]["routed"] > 0
        assert tenants["high:sla-team"]["shed"] == 0
        assert tenants["batch:nightly-eval"]["shed"] >= 1
        assert summary["fleet"]["restarts"] >= 1
        text = telemetry.format_run_summary(summary)
        assert "scaling: 2 up / 2 down" in text
        assert "tenant high:sla-team" in text
        from scripts import analyze_trace
        json_path = str(tmp_path / "RUN_SUMMARY.json")
        assert analyze_trace.main([events_path, "--json", json_path]) == 0
        obj = json.loads(pathlib.Path(json_path).read_text())
        assert obj["fleet"]["scaling"]["ups"] == 2
        assert "high:sla-team" in obj["fleet"]["tenants"]

        # Archive the raw scaling-event stream for the tier driver.
        if bench_dir:
            shutil.copyfile(events_path,
                            os.path.join(bench_dir,
                                         "AUTOSCALE_EVENTS.jsonl"))
    finally:
        high_stop.set()
        faults.install(None)
        clean = router.shutdown("drill teardown")
        serve_thread.join(30)
        try:
            writer.close()
        except ValueError:
            pass
        assert clean, "fleet drain left a replica running"
