"""The goodput-driven autotuner (scripts/autotune.py, tools/autotune).

CPU-only acceptance drill for the chip-window tuner, per the contracts
in docs/PERFORMANCE.md "Autotuning":

- a toy two-knob space over REAL config paths where the roofline/traffic
  model prunes at least one candidate with the prediction logged;
- a mid-search kill that resumes from the dtf-autotune-journal/1
  journal without re-running settled trials (subprocess, SIGKILL);
- the winner pinned in leaderboard.json with a digest bench.py's
  regression check verifies;
- `autotune.py --plan chip_window --dry-run` covering every section/
  label the chip_window_queue.sh wrapper's plan-manifest declares;
- KIND_AUTOTUNE_TRIAL telemetry rolled up by summarize_events and
  rendered by format_run_summary.

When DTF_AUTOTUNE_DIR is set (scripts/run_tier1.sh), the smoke drill's
journal + leaderboard are archived there as AUTOTUNE_* artifacts.
"""

import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import time

import pytest

from distributed_tensorflow_framework_tpu.core import telemetry
from tools import autotune as tune

REPO = pathlib.Path(__file__).resolve().parent.parent

# The toy space: two real knobs, incumbent (first value) = BENCH_r02's
# bf16/no-remat shape on one v5e. float32 activations re-widen the HBM
# traffic the precision pack shrank, so the model must prune them.
SPEC = {
    "workload": "resnet50",
    "incumbent": {
        "chip": "TPU v5 lite", "n_chips": 1,
        "flops_per_step": 6.26e12,
        "hbm_bytes_per_step": 6.26e12 / 78.7,
        "wire_bytes_per_step": 2e9,
        "opt_state_bytes": 1e9,
        "examples_per_step": 256,
    },
    "knobs": [
        {"path": "precision.activation_dtype",
         "values": ["bf16", "float32"], "env": "BENCH_PRECISION"},
        {"path": "model.remat_policy", "values": ["none", "full"]},
    ],
}

GOOD_PAYLOAD = {
    "workload": "resnet50", "value": 2600.0, "unit": "images/sec/chip",
    "bound": "hbm_bandwidth", "chip": "TPU v5 lite",
}
GOOD_SUMMARY = {"schema": "dtf-run-summary/1",
                "goodput_ledger": {"goodput_frac": 0.93}}


def _space_and_profile():
    space = tune.SearchSpace.from_spec(SPEC)
    profile = tune.TrafficProfile(**SPEC["incumbent"])
    return space, profile


def _archive(src: pathlib.Path, name: str) -> None:
    """run_tier1.sh contract: park drill artifacts in DTF_AUTOTUNE_DIR."""
    art_dir = os.environ.get("DTF_AUTOTUNE_DIR", "").strip()
    if art_dir and src.exists():
        shutil.copyfile(src, os.path.join(art_dir, name))


class TestSearchSpace:
    def test_paths_validated_against_real_config(self):
        with pytest.raises(tune.SearchSpaceError):
            tune.SearchSpace.from_spec({
                "workload": "w",
                "knobs": [{"path": "precision.no_such_knob",
                           "values": ["a", "b"]}],
            })

    def test_enumerate_baseline_first(self):
        space, _ = _space_and_profile()
        cands = list(space.enumerate())
        assert len(cands) == 4
        assert cands[0] == space.baseline() == {
            "precision.activation_dtype": "bf16",
            "model.remat_policy": "none",
        }

    def test_trial_env_maps_env_knobs_only(self):
        space, _ = _space_and_profile()
        env = space.trial_env({"precision.activation_dtype": "float32",
                               "model.remat_policy": "full"})
        assert env == {"BENCH_PRECISION": "float32"}


class TestPruning:
    def test_f32_pruned_bf16_kept(self):
        space, profile = _space_and_profile()
        base = space.baseline()
        skip, reason, detail = tune.prune_decision(
            profile, {"precision.activation_dtype": "float32",
                      "model.remat_policy": "none"}, base, 0.05)
        assert skip
        assert "worse on hbm_bandwidth" in reason
        assert detail["predicted_rate"] < detail["incumbent_rate"]
        skip2, _, _ = tune.prune_decision(profile, base, base, 0.05)
        assert not skip2

    def test_digest_is_stable_and_order_insensitive(self):
        a = tune.config_digest({"x": 1, "y": 2})
        b = tune.config_digest({"y": 2, "x": 1})
        assert a == b and a.startswith("sha256:")


class TestJournal:
    def test_terminal_vs_nonterminal(self, tmp_path):
        j = tune.TrialJournal(str(tmp_path / "j.jsonl"))
        j.record("t1", "started")
        j.record("t1", "done", score=1.0)
        j.record("t2", "started")          # interrupted — must re-run
        j.record("t3", "window_abort")     # aborted — must re-run
        j.record("t4", "skipped", reason="pruned")
        settled = tune.TrialJournal(str(tmp_path / "j.jsonl")).settled()
        assert set(settled) == {"t1", "t4"}
        assert settled["t1"]["score"] == 1.0

    def test_strict_replay_raises_on_garbage(self, tmp_path):
        p = tmp_path / "j.jsonl"
        p.write_text('{"schema": "wrong/1", "trial": "t", '
                     '"status": "done"}\n')
        with pytest.raises(tune.JournalError):
            tune.TrialJournal(str(p)).replay(strict=True)


class TestScoring:
    def test_goodput_weighted(self):
        s = tune.score_trial(GOOD_PAYLOAD, GOOD_SUMMARY)
        assert s["score"] == pytest.approx(2600.0 * 0.93)
        assert s["unit"] == "images/sec/chip"

    def test_no_ledger_means_full_weight(self):
        s = tune.score_trial({"value": 10.0, "unit": "x"}, None)
        assert s["score"] == 10.0 and s["goodput_frac"] == 1.0


class TestSmokeDrill:
    """The acceptance drill: search → prune → score → pin → bench reads
    the pin back. Everything in-process except the payloads, which come
    from the deterministic FakeRunner."""

    def _run(self, tmp_path):
        space, profile = _space_and_profile()
        runner = tune.FakeRunner({"*": {"exit_code": 0,
                                        "payload": GOOD_PAYLOAD,
                                        "summary": GOOD_SUMMARY}})
        journal_path = tmp_path / "journal.jsonl"
        logs: list[str] = []
        result = tune.run_space_search(
            space, profile, runner, tune.TrialJournal(str(journal_path)),
            prune_margin=0.05, log=logs.append)
        return space, journal_path, logs, result

    def test_prunes_at_least_one_with_logged_prediction(self, tmp_path):
        _, journal_path, logs, result = self._run(tmp_path)
        assert result["pruned"] >= 1 and result["ran"] >= 1
        pruned_logs = [ln for ln in logs if "PRUNE" in ln]
        assert pruned_logs and any("worse on" in ln for ln in pruned_logs)
        # The journal carries the full prediction for every skip.
        settled = tune.TrialJournal(str(journal_path)).settled()
        skipped = [r for r in settled.values()
                   if r.get("status") == "skipped"]
        assert skipped and all("predicted_rate" in r["prediction"]
                               for r in skipped)

    def test_winner_pinned_and_bench_verifies_digest(self, tmp_path,
                                                     monkeypatch):
        space, journal_path, _, result = self._run(tmp_path)
        board_path = tmp_path / "leaderboard.json"
        entry = tune.pin_winner(
            result, leaderboard_path=str(board_path),
            best_yaml_path=str(tmp_path / "best_resnet50.yaml"),
            log=lambda *_: None)
        assert entry["config_digest"] == tune.config_digest(
            entry["config"])
        assert entry["score"] == pytest.approx(2600.0 * 0.93)
        board = tune.load_board(str(board_path))
        assert board["schema"] == tune.LEADERBOARD_SCHEMA
        assert "resnet50" in board["entries"]
        # bench.py reads the pin back: digest verified, ratio annotated.
        import bench

        monkeypatch.setenv("BENCH_LEADERBOARD", str(board_path))
        out = {"value": 2600.0}
        bench._check_leaderboard(out, "resnet50")
        note = out["leaderboard"]
        assert note["digest_ok"] is True
        assert note["regression"] is False
        assert note["vs_incumbent"] == pytest.approx(2600.0 / entry["score"],
                                                     abs=1e-3)
        # A clearly slower rerun trips the regression flag.
        slow = {"value": 1000.0}
        bench._check_leaderboard(slow, "resnet50")
        assert slow["leaderboard"]["regression"] is True
        # A hand-edited pin fails the digest check.
        board["entries"]["resnet50"]["config"]["extra"] = True
        board_path.write_text(json.dumps(board))
        edited = {"value": 2600.0}
        bench._check_leaderboard(edited, "resnet50")
        assert edited["leaderboard"]["digest_ok"] is False
        _archive(journal_path, "AUTOTUNE_JOURNAL.jsonl")
        _archive(board_path, "AUTOTUNE_LEADERBOARD.json")

    def test_best_yaml_written_with_digest(self, tmp_path):
        _, _, _, result = self._run(tmp_path)
        yaml_path = tmp_path / "best_resnet50.yaml"
        tune.pin_winner(result,
                        leaderboard_path=str(tmp_path / "lb.json"),
                        best_yaml_path=str(yaml_path),
                        log=lambda *_: None)
        text = yaml_path.read_text()
        assert result["best"]["trial"] in text  # the digest, traceable
        assert "activation_dtype: bf16" in text

    def test_probe_hang_aborts_window_resumably(self, tmp_path):
        space, profile = _space_and_profile()
        journal_path = str(tmp_path / "j.jsonl")
        hang = tune.FakeRunner({"*": {"exit_code": 3}})
        result = tune.run_space_search(
            space, profile, hang, tune.TrialJournal(journal_path),
            prune_margin=0.05, log=lambda *_: None)
        assert result["aborted"] and result["ran"] == 0
        assert len(hang.calls) == 1  # the window stopped at the hang
        # window_abort is non-terminal: the resumed window re-runs it.
        ok = tune.FakeRunner({"*": {"exit_code": 0,
                                    "payload": GOOD_PAYLOAD,
                                    "summary": GOOD_SUMMARY}})
        resumed = tune.run_space_search(
            space, profile, ok, tune.TrialJournal(journal_path),
            prune_margin=0.05, log=lambda *_: None)
        assert not resumed["aborted"] and resumed["ran"] == 2
        assert resumed["best"] is not None


class TestKillResume:
    """SIGKILL the CLI mid-search; the journal must hand the next
    invocation every settled trial. Runs scripts/autotune.py exactly as
    an operator would (subprocess), with the FakeRunner supplying
    deterministic payloads and a long sleep to die inside."""

    def test_killed_search_resumes_without_rerunning(self, tmp_path):
        space, _ = _space_and_profile()
        trial_ids = [tune.trial_id_for(o) for o in space.enumerate()]
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(SPEC))
        journal_path = tmp_path / "journal.jsonl"
        good = {"exit_code": 0, "payload": GOOD_PAYLOAD,
                "summary": GOOD_SUMMARY}
        fake_path = tmp_path / "fake.json"
        # First invocation: trial 0 fast, trial 1 sleeps long enough to
        # be killed inside.
        fake_path.write_text(json.dumps({
            trial_ids[0]: good,
            "*": dict(good, sleep_s=60.0),
        }))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        argv = [sys.executable, "scripts/autotune.py",
                "--space", str(spec_path), "--fake-runner", str(fake_path),
                "--journal", str(journal_path),
                "--out-dir", str(tmp_path)]
        proc = subprocess.Popen(argv, cwd=str(REPO), env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        try:
            # Kill once trial 0 settled and trial 1 started.
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                text = (journal_path.read_text()
                        if journal_path.exists() else "")
                if '"done"' in text and text.count('"started"') >= 2:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("journal never reached the kill point")
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        settled = tune.TrialJournal(str(journal_path)).settled()
        assert settled[trial_ids[0]]["status"] == "done"
        assert trial_ids[1] not in settled  # died mid-trial: unsettled
        # Second invocation: no sleeps; must resume, not re-run.
        fake_path.write_text(json.dumps({"*": good}))
        done = subprocess.run(argv, cwd=str(REPO), env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=120)
        assert done.returncode == 0, done.stdout
        assert f"{trial_ids[0]} already done" in done.stdout
        result = json.loads(done.stdout.strip().splitlines()[-1])
        assert result["resumed"] >= 1 and result["ran"] >= 1
        # Exactly ONE done record for trial 0 across both invocations.
        records = [json.loads(ln)
                   for ln in journal_path.read_text().splitlines()]
        dones = [r for r in records
                 if r["trial"] == trial_ids[0] and r["status"] == "done"]
        assert len(dones) == 1
        # The completed window pinned its winner.
        board = tune.load_board(str(tmp_path / "leaderboard.json"))
        assert board["entries"]["resnet50"]["score"] == pytest.approx(
            2600.0 * 0.93)


class TestBenchOut:
    """BENCH_OUT=<path>: bench's ONE JSON line also lands in a file, so
    the runner never regexes results out of warning-polluted stdout."""

    def test_emit_json_line_writes_stdout_and_file(self, tmp_path,
                                                   monkeypatch, capsys):
        import bench

        out_path = tmp_path / "bench_out.json"
        monkeypatch.setenv("BENCH_OUT", str(out_path))
        bench._emit_json_line({"value": 1.5, "unit": "x"})
        assert json.loads(capsys.readouterr().out) == {"value": 1.5,
                                                       "unit": "x"}
        assert json.loads(out_path.read_text()) == {"value": 1.5,
                                                    "unit": "x"}

    def test_emit_json_line_overwrites_not_appends(self, tmp_path,
                                                   monkeypatch, capsys):
        import bench

        out_path = tmp_path / "bench_out.json"
        monkeypatch.setenv("BENCH_OUT", str(out_path))
        bench._emit_json_line({"try": 1})
        bench._emit_json_line({"try": 2})
        capsys.readouterr()
        # Whole-file semantics: the LAST emission is the file.
        assert json.loads(out_path.read_text()) == {"try": 2}

    def test_runner_payload_prefers_file_over_stdout(self, tmp_path):
        out_path = tmp_path / "out.json"
        out_path.write_text('{"value": 7}')
        got = tune.SubprocessRunner._read_payload(
            str(out_path), 'WARNING: noise\n{"value": 99}\n')
        assert got == {"value": 7}

    def test_runner_payload_stdout_fallback(self, tmp_path):
        got = tune.SubprocessRunner._read_payload(
            str(tmp_path / "missing.json"),
            'WARNING: noise\nnot json {\n{"value": 42}\n')
        assert got == {"value": 42}


class TestChipWindowPlan:
    """The compiled plan must cover every A/B the shell queue carried;
    chip_window_queue.sh's plan-manifest lines are the contract."""

    @pytest.fixture(scope="class")
    def dry_run(self):
        proc = subprocess.run(
            [sys.executable, "scripts/autotune.py", "--plan",
             "chip_window", "--dry-run"],
            cwd=str(REPO), env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    def _manifest(self) -> dict[str, list[str]]:
        sections: dict[str, list[str]] = {}
        script = (REPO / "scripts" / "chip_window_queue.sh").read_text()
        for line in script.splitlines():
            if line.startswith("# plan-manifest §"):
                head, labels = line[len("# plan-manifest §"):].split(":", 1)
                sections[head.strip()] = labels.split()
        return sections

    def test_every_manifest_label_in_dry_run(self, dry_run):
        manifest = self._manifest()
        assert manifest, "wrapper lost its plan-manifest lines"
        planned = {(ln.split()[1].lstrip("§"), ln.split()[2])
                   for ln in dry_run.splitlines() if ln.strip()}
        for section, labels in manifest.items():
            for label in labels:
                assert (section, label) in planned, (
                    f"§{section} {label} declared by chip_window_queue.sh "
                    f"but missing from --plan chip_window --dry-run")
        # And nothing planned that the manifest doesn't declare.
        declared = {(s, lb) for s, lbs in manifest.items() for lb in lbs}
        assert planned == declared

    def test_sections_7_to_17_all_covered(self, dry_run):
        manifest = self._manifest()
        for section in [str(n) for n in range(7, 18)]:
            assert manifest.get(section), f"§{section} missing"
            assert f"§{section} " in dry_run

    def test_priority_order(self, dry_run):
        lines = dry_run.splitlines()
        # §0/§0b preflights first, then the BENCH_r02 revalidation,
        # then the §13 precision ladder before everything else.
        assert "§0 graftcheck [preflight]" in lines[0]
        assert "§0b probe [preflight]" in lines[1]
        assert "§1 resnet" in lines[2]
        assert "§13" in lines[3]

    def test_wrapper_is_thin(self):
        script = (REPO / "scripts" / "chip_window_queue.sh").read_text()
        assert "exec python scripts/autotune.py --plan chip_window" \
            in script

    def test_gates_respected_in_plan_mode(self, tmp_path):
        trials = tune.compile_chip_window_plan()
        by_label = {t.label: t for t in trials}
        # Spot-check the load-bearing gates: measurement arms wait on
        # their verify/export predecessors.
        assert by_label["wk2048-fused"].gate == "wk-verify-2048"
        assert by_label["fused-bwd"].gate == "fused-bwd-verify"
        assert by_label["serve-batched"].gate == "serve-export"
        # A failed preflight refuses the window (§0 contract).
        hang_free_fail = tune.FakeRunner({"s0:graftcheck": {"exit_code": 1},
                                          "*": {"exit_code": 0}})
        result = tune.run_plan(
            trials, hang_free_fail,
            tune.TrialJournal(str(tmp_path / "j.jsonl")),
            log=lambda *_: None)
        assert result["preflight_failed"] and result["ran"] == 0


class TestTelemetryRollup:
    def test_kind_summarized_and_rendered(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        w = telemetry.TelemetryWriter(path)
        w.emit(telemetry.KIND_AUTOTUNE_TRIAL, trial="sha256:aa",
               status="done", score=2418.0, unit="images/sec/chip")
        w.emit(telemetry.KIND_AUTOTUNE_TRIAL, trial="sha256:bb",
               status="skipped", reason="pruned")
        w.emit(telemetry.KIND_AUTOTUNE_TRIAL, trial="sha256:cc",
               status="failed", error="exit 1")
        w.emit(telemetry.KIND_AUTOTUNE_TRIAL, trial="sha256:dd",
               status="window_abort", error="probe hang")
        w.close()
        summary = telemetry.summarize_events(path)
        at = summary["autotune"]
        assert at["ran"] == 1 and at["pruned"] == 1
        assert at["failed"] == 1 and at["window_aborts"] == 1
        assert at["best"] == {"trial": "sha256:aa", "score": 2418.0,
                              "unit": "images/sec/chip"}
        rendered = telemetry.format_run_summary(summary)
        assert "autotune: 1 ran / 1 pruned / 1 failed" in rendered
        assert "best: sha256:aa score 2418.0 images/sec/chip" in rendered

    def test_absent_without_events(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        w = telemetry.TelemetryWriter(path)
        w.emit(telemetry.KIND_TRAIN_STEP, step=1)
        w.close()
        assert telemetry.summarize_events(path)["autotune"] is None

    def test_search_loop_emits_the_kind(self, tmp_path):
        space, profile = _space_and_profile()
        runner = tune.FakeRunner({"*": {"exit_code": 0,
                                        "payload": GOOD_PAYLOAD,
                                        "summary": GOOD_SUMMARY}})
        path = str(tmp_path / "events.jsonl")
        w = telemetry.TelemetryWriter(path)
        tune.run_space_search(
            space, profile, runner,
            tune.TrialJournal(str(tmp_path / "j.jsonl")),
            prune_margin=0.05, writer=w, log=lambda *_: None)
        w.close()
        kinds = telemetry.summarize_events(path)["kinds"]
        assert kinds.get(telemetry.KIND_AUTOTUNE_TRIAL, 0) >= 4
