"""bench.py backend bring-up: the BENCH_WAIT bounded retry budget.

All probe/sleep/clock effects are injected, so these pin the retry POLICY
— legacy fast-fail, budgeted 5-minute re-probing, and the hang-is-final
rule (VERDICT item 2) — without touching any backend or real time.
"""

import importlib.util
import pathlib

import pytest

_BENCH_PATH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
_spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_clock():
    state = {"t": 0.0}

    def monotonic():
        return state["t"]

    def sleep(s):
        state["t"] += s

    return state, monotonic, sleep


def test_legacy_fast_fail_three_attempts():
    sleeps = []
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "RuntimeError: no tpu"),
            sleep=sleeps.append, wait_budget_s=0)
    history = exc.value.probe_history
    assert [r["attempt"] for r in history] == [1, 2, 3]
    assert all(r["outcome"] == "error" for r in history)
    assert sleeps == [5, 10]  # short backoff, no 5-min waits


def test_bench_wait_budget_probes_every_interval():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "tunnel down"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=20 * 60, retry_interval_s=300)
    history = exc.value.probe_history
    # Probes at t=0,300,...,1200 — every 5 min across the 20-min budget.
    assert len(history) == 5
    assert state["t"] == 1200
    assert "BENCH_WAIT" in str(exc.value)
    # The failure carries the full history, not just the last error.
    assert all(r["error"] == "tunnel down" for r in history)


def test_hang_is_final_even_with_budget():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", 4242),
            sleep=sleep, monotonic=monotonic, wait_budget_s=60 * 60)
    history = exc.value.probe_history
    assert len(history) == 1 and history[0]["outcome"] == "hang"
    assert state["t"] == 0  # no retry sleep: the chip client is exclusive
    assert "4242" in str(exc.value) and "wedge" in str(exc.value)


def test_recovers_after_transient_failure(devices):
    state, monotonic, sleep = _fake_clock()
    calls = {"n": 0}

    def flaky(timeout_s):
        calls["n"] += 1
        return ("ok", None) if calls["n"] >= 3 else ("error", "booting")

    n, kind = bench._init_backend(
        probe=flaky, sleep=sleep, monotonic=monotonic, wait_budget_s=30 * 60)
    assert calls["n"] == 3
    assert n == len(devices)


@pytest.mark.parametrize("raw,want", [
    ("", 0.0), ("0", 0.0), ("15", 900.0), ("1.5", 90.0),
    ("y", 3600.0),  # non-numeric truthy -> the default hour
])
def test_bench_wait_parsing(monkeypatch, raw, want):
    monkeypatch.setenv("BENCH_WAIT", raw)
    assert bench._bench_wait_budget_s() == want
