"""bench.py backend bring-up: the BENCH_WAIT bounded retry budget.

All probe/sleep/clock effects are injected, so these pin the retry POLICY
— legacy fast-fail, budgeted 5-minute re-probing, and the hang rules
(final + actionable without a budget; reaped and re-probed under one) —
without touching any backend or real time.
"""

import importlib.util
import pathlib

import pytest

_BENCH_PATH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
_spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_clock():
    state = {"t": 0.0}

    def monotonic():
        return state["t"]

    def sleep(s):
        state["t"] += s

    return state, monotonic, sleep


def test_legacy_fast_fail_three_attempts():
    sleeps = []
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "RuntimeError: no tpu"),
            sleep=sleeps.append, wait_budget_s=0)
    history = exc.value.probe_history
    assert [r["attempt"] for r in history] == [1, 2, 3]
    assert all(r["outcome"] == "error" for r in history)
    assert sleeps == [5, 10]  # short backoff, no 5-min waits


def test_bench_wait_budget_probes_every_interval():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "tunnel down"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=20 * 60, retry_interval_s=300)
    history = exc.value.probe_history
    # Probes at t=0,300,...,1200 — every 5 min across the 20-min budget.
    assert len(history) == 5
    assert state["t"] == 1200
    assert "BENCH_WAIT" in str(exc.value)
    # The failure carries the full history, not just the last error.
    assert all(r["error"] == "tunnel down" for r in history)


def test_hang_without_budget_is_final_and_actionable():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "probe exceeded 240s (pid 4242 reaped)"),
            sleep=sleep, monotonic=monotonic, wait_budget_s=0)
    history = exc.value.probe_history
    assert len(history) == 1 and history[0]["outcome"] == "hang"
    assert state["t"] == 0  # no blind retry without a time budget
    # The error must be actionable: it names the knob that arms retries.
    assert "4242" in str(exc.value) and "BENCH_WAIT" in str(exc.value)


def test_hang_is_retried_under_budget(devices):
    state, monotonic, sleep = _fake_clock()
    calls = {"n": 0}

    def wedged_then_ok(timeout_s):
        calls["n"] += 1
        return ("ok", None) if calls["n"] >= 3 else ("hang", "reaped")

    n, kind = bench._init_backend(
        probe=wedged_then_ok, sleep=sleep, monotonic=monotonic,
        wait_budget_s=60 * 60, hang_retry_delay_s=15)
    assert calls["n"] == 3
    assert state["t"] == 30  # two short settle delays, no 5-min waits
    assert n == len(devices)


def test_hang_budget_exhausted_raises_with_history():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "reaped"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=60, hang_retry_delay_s=15)
    history = exc.value.probe_history
    # Probes at t=0,15,30,45,60: re-probed until the budget ran out.
    assert len(history) == 5
    assert all(r["outcome"] == "hang" for r in history)
    assert "BENCH_WAIT" in str(exc.value)


def test_probe_timeout_capped_by_remaining_budget():
    state, monotonic, sleep = _fake_clock()
    timeouts = []

    def hang(timeout_s):
        timeouts.append(timeout_s)
        state["t"] += timeout_s  # a real hang burns its whole timeout
        return "hang", "reaped"

    with pytest.raises(bench.BenchBackendError):
        bench._init_backend(
            probe=hang, sleep=sleep, monotonic=monotonic,
            probe_timeout_s=240, wait_budget_s=300, hang_retry_delay_s=0)
    # First probe gets the full 240 s; the second only the 60 s left.
    assert timeouts[0] == 240
    assert all(t <= 240 for t in timeouts[1:])
    assert timeouts[1] == 60


def test_recovers_after_transient_failure(devices):
    state, monotonic, sleep = _fake_clock()
    calls = {"n": 0}

    def flaky(timeout_s):
        calls["n"] += 1
        return ("ok", None) if calls["n"] >= 3 else ("error", "booting")

    n, kind = bench._init_backend(
        probe=flaky, sleep=sleep, monotonic=monotonic, wait_budget_s=30 * 60)
    assert calls["n"] == 3
    assert n == len(devices)


@pytest.mark.parametrize("raw,want", [
    ("", 0.0), ("0", 0.0), ("15", 900.0), ("1.5", 90.0),
    ("y", 3600.0),  # non-numeric truthy -> the default hour
])
def test_bench_wait_parsing(monkeypatch, raw, want):
    monkeypatch.setenv("BENCH_WAIT", raw)
    assert bench._bench_wait_budget_s() == want


# ------------------------------------------------- roofline tagging ----
# Not a BENCH_WAIT concern, but the same injected-bench-module seam: the
# accum>1 roofline artifacts must carry the "accum-scaled-upper" tag
# (accum-scaled flops/bytes make hbm_bw_util an upper bound — untagged,
# they read as directly comparable roofline positions).


def _roofline(chip="v5litepod-8", *, accum_scaled, flops=1.0e12):
    out = {}
    result = {"flops_per_step": flops, "bytes_per_step": 2.0e9,
              "sec_per_step": 0.1}
    bench._annotate_roofline(out, result, chip, 8,
                             accum_scaled=accum_scaled)
    return out


def test_accum_scaled_roofline_is_tagged():
    out = _roofline(accum_scaled=True)
    assert out["roofline_bound"] == "accum-scaled-upper"
    # the tag annotates, never replaces, the roofline numbers
    assert "tflops_per_sec" in out and "arith_intensity" in out


def test_unscaled_roofline_carries_no_tag():
    out = _roofline(accum_scaled=False)
    assert "roofline_bound" not in out
    assert "tflops_per_sec" in out


def test_roofline_tag_needs_a_cost_model():
    # No XLA cost model (flops 0/None): nothing to scale, nothing to tag.
    assert _roofline(accum_scaled=True, flops=0) == {}


# ---------------------------------------------- hang classification ----
# A probe HANG is chip access flakiness (wedged tunnel, slice still
# provisioning), not a code regression: it must carry a distinct
# failure_class and exit the bench with rc 3, so the chip-window queue
# re-lands the dial instead of counting it against the code under test.


def test_hang_raises_with_probe_hang_class():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "reaped"),
            sleep=sleep, monotonic=monotonic, wait_budget_s=0)
    assert exc.value.failure_class == "probe_hang"


def test_hang_budget_exhausted_keeps_probe_hang_class():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "reaped"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=60, hang_retry_delay_s=15)
    assert exc.value.failure_class == "probe_hang"


def test_probe_error_is_not_a_hang():
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "RuntimeError: no tpu"),
            sleep=lambda s: None, wait_budget_s=0)
    assert exc.value.failure_class == "backend_error"


class _FakeWriter:
    run_id = "test-run"

    def __init__(self):
        self.events = []

    def emit(self, kind, **kw):
        self.events.append((kind, kw))

    def emit_run_meta(self, **kw):
        pass


def _run_with_backend_error(monkeypatch, capsys, err):
    import json

    monkeypatch.delenv("BENCH_WORKLOAD", raising=False)
    monkeypatch.delenv("BENCH_COLLECTIVE", raising=False)

    def boom():
        raise err

    monkeypatch.setattr(bench, "_init_backend", boom)
    writer = _FakeWriter()
    rc = bench._run(writer)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return rc, json.loads(out), writer


def test_run_exits_3_on_probe_hang(monkeypatch, capsys):
    err = bench.BenchBackendError(
        "backend probe hung", [{"attempt": 1, "outcome": "hang"}],
        failure_class="probe_hang")
    rc, fail, writer = _run_with_backend_error(monkeypatch, capsys, err)
    assert rc == 3
    assert fail["failure_class"] == "probe_hang"
    assert fail["value"] == 0.0 and "error" in fail
    # and the telemetry failure event carries the class too
    failures = [kw for kind, kw in writer.events
                if kw.get("health", {}).get("failure") == "backend_init"]
    assert failures and failures[0]["health"]["failure_class"] == "probe_hang"


def test_run_exits_1_on_ordinary_backend_error(monkeypatch, capsys):
    err = bench.BenchBackendError("RuntimeError: no tpu", [])
    rc, fail, writer = _run_with_backend_error(monkeypatch, capsys, err)
    assert rc == 1
    assert fail["failure_class"] == "backend_error"


# ------------------------------------------- collective wire-format A/B


def _fake_resnet(rate, wire_bytes):
    return {"images_per_sec": rate, "sec_per_step": 0.1,
            "flops_per_step": None, "bytes_per_step": None,
            "collectives": {"total_bytes": wire_bytes,
                            "total_logical_bytes": 800_000},
            "mesh_axes": {"data": 8}}


def test_collective_ab_reports_ratio_and_delta(monkeypatch, capsys):
    import json

    calls = []

    def fake_bench(bs, base_overrides=None, **kw):
        wire = (base_overrides or {}).get(
            "parallel", {}).get("collective_dtype", "")
        calls.append(wire)
        assert (base_overrides or {}).get(
            "train", {}).get("spmd_mode") == "shard_map"
        return (_fake_resnet(1040.0, 200_000) if wire == "int8"
                else _fake_resnet(1000.0, 800_000))

    monkeypatch.setattr(bench, "bench_resnet50", fake_bench)
    rc = bench._run_collective_ab(_FakeWriter(), "int8", 8, "TPU v5e")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert calls == ["", "int8"]  # baseline first, then the target wire
    assert out["value"] == 4.0    # wire-byte ratio from the tally
    assert out["throughput_delta"] == 0.04
    assert out["collective_dtype"] == "int8"
    assert out["baseline_wire_bytes"] == 800_000
    assert out["target_wire_bytes"] == 200_000


def test_collective_ab_f32_is_self_calibration(monkeypatch, capsys):
    import json

    calls = []

    def fake_bench(bs, base_overrides=None, **kw):
        calls.append(bs)
        return _fake_resnet(1000.0, 800_000)

    monkeypatch.setattr(bench, "bench_resnet50", fake_bench)
    rc = bench._run_collective_ab(_FakeWriter(), "f32", 8, "TPU v5e")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and len(calls) == 1  # one run: baseline IS the target
    assert out["value"] == 1.0 and out["throughput_delta"] == 0.0


def test_bench_collective_env_validated(monkeypatch, capsys):
    import json

    monkeypatch.setenv("BENCH_COLLECTIVE", "fp4")
    monkeypatch.setattr(bench, "_init_backend", lambda: (8, "TPU v5e"))
    rc = bench._run(_FakeWriter())
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and "BENCH_COLLECTIVE" in out["error"]
