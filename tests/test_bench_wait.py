"""bench.py backend bring-up: the BENCH_WAIT bounded retry budget.

All probe/sleep/clock effects are injected, so these pin the retry POLICY
— legacy fast-fail, budgeted 5-minute re-probing, and the hang rules
(final + actionable without a budget; reaped and re-probed under one) —
without touching any backend or real time.
"""

import importlib.util
import pathlib

import pytest

_BENCH_PATH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
_spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_clock():
    state = {"t": 0.0}

    def monotonic():
        return state["t"]

    def sleep(s):
        state["t"] += s

    return state, monotonic, sleep


def test_legacy_fast_fail_three_attempts():
    sleeps = []
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "RuntimeError: no tpu"),
            sleep=sleeps.append, wait_budget_s=0)
    history = exc.value.probe_history
    assert [r["attempt"] for r in history] == [1, 2, 3]
    assert all(r["outcome"] == "error" for r in history)
    assert sleeps == [5, 10]  # short backoff, no 5-min waits


def test_bench_wait_budget_probes_every_interval():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("error", "tunnel down"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=20 * 60, retry_interval_s=300)
    history = exc.value.probe_history
    # Probes at t=0,300,...,1200 — every 5 min across the 20-min budget.
    assert len(history) == 5
    assert state["t"] == 1200
    assert "BENCH_WAIT" in str(exc.value)
    # The failure carries the full history, not just the last error.
    assert all(r["error"] == "tunnel down" for r in history)


def test_hang_without_budget_is_final_and_actionable():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "probe exceeded 240s (pid 4242 reaped)"),
            sleep=sleep, monotonic=monotonic, wait_budget_s=0)
    history = exc.value.probe_history
    assert len(history) == 1 and history[0]["outcome"] == "hang"
    assert state["t"] == 0  # no blind retry without a time budget
    # The error must be actionable: it names the knob that arms retries.
    assert "4242" in str(exc.value) and "BENCH_WAIT" in str(exc.value)


def test_hang_is_retried_under_budget(devices):
    state, monotonic, sleep = _fake_clock()
    calls = {"n": 0}

    def wedged_then_ok(timeout_s):
        calls["n"] += 1
        return ("ok", None) if calls["n"] >= 3 else ("hang", "reaped")

    n, kind = bench._init_backend(
        probe=wedged_then_ok, sleep=sleep, monotonic=monotonic,
        wait_budget_s=60 * 60, hang_retry_delay_s=15)
    assert calls["n"] == 3
    assert state["t"] == 30  # two short settle delays, no 5-min waits
    assert n == len(devices)


def test_hang_budget_exhausted_raises_with_history():
    state, monotonic, sleep = _fake_clock()
    with pytest.raises(bench.BenchBackendError) as exc:
        bench._init_backend(
            probe=lambda t: ("hang", "reaped"),
            sleep=sleep, monotonic=monotonic,
            wait_budget_s=60, hang_retry_delay_s=15)
    history = exc.value.probe_history
    # Probes at t=0,15,30,45,60: re-probed until the budget ran out.
    assert len(history) == 5
    assert all(r["outcome"] == "hang" for r in history)
    assert "BENCH_WAIT" in str(exc.value)


def test_probe_timeout_capped_by_remaining_budget():
    state, monotonic, sleep = _fake_clock()
    timeouts = []

    def hang(timeout_s):
        timeouts.append(timeout_s)
        state["t"] += timeout_s  # a real hang burns its whole timeout
        return "hang", "reaped"

    with pytest.raises(bench.BenchBackendError):
        bench._init_backend(
            probe=hang, sleep=sleep, monotonic=monotonic,
            probe_timeout_s=240, wait_budget_s=300, hang_retry_delay_s=0)
    # First probe gets the full 240 s; the second only the 60 s left.
    assert timeouts[0] == 240
    assert all(t <= 240 for t in timeouts[1:])
    assert timeouts[1] == 60


def test_recovers_after_transient_failure(devices):
    state, monotonic, sleep = _fake_clock()
    calls = {"n": 0}

    def flaky(timeout_s):
        calls["n"] += 1
        return ("ok", None) if calls["n"] >= 3 else ("error", "booting")

    n, kind = bench._init_backend(
        probe=flaky, sleep=sleep, monotonic=monotonic, wait_budget_s=30 * 60)
    assert calls["n"] == 3
    assert n == len(devices)


@pytest.mark.parametrize("raw,want", [
    ("", 0.0), ("0", 0.0), ("15", 900.0), ("1.5", 90.0),
    ("y", 3600.0),  # non-numeric truthy -> the default hour
])
def test_bench_wait_parsing(monkeypatch, raw, want):
    monkeypatch.setenv("BENCH_WAIT", raw)
    assert bench._bench_wait_budget_s() == want


# ------------------------------------------------- roofline tagging ----
# Not a BENCH_WAIT concern, but the same injected-bench-module seam: the
# accum>1 roofline artifacts must carry the "accum-scaled-upper" tag
# (accum-scaled flops/bytes make hbm_bw_util an upper bound — untagged,
# they read as directly comparable roofline positions).


def _roofline(chip="v5litepod-8", *, accum_scaled, flops=1.0e12):
    out = {}
    result = {"flops_per_step": flops, "bytes_per_step": 2.0e9,
              "sec_per_step": 0.1}
    bench._annotate_roofline(out, result, chip, 8,
                             accum_scaled=accum_scaled)
    return out


def test_accum_scaled_roofline_is_tagged():
    out = _roofline(accum_scaled=True)
    assert out["roofline_bound"] == "accum-scaled-upper"
    # the tag annotates, never replaces, the roofline numbers
    assert "tflops_per_sec" in out and "arith_intensity" in out


def test_unscaled_roofline_carries_no_tag():
    out = _roofline(accum_scaled=False)
    assert "roofline_bound" not in out
    assert "tflops_per_sec" in out


def test_roofline_tag_needs_a_cost_model():
    # No XLA cost model (flops 0/None): nothing to scale, nothing to tag.
    assert _roofline(accum_scaled=True, flops=0) == {}
