"""Checkpoint/resume tests — SURVEY.md §7 hard part 3 (resume exactness).

The contract (reference: MonitoredTrainingSession + Saver auto-restore):
train N steps with checkpointing, kill, relaunch pointing at the same
directory → the restored run's parameters after N+K steps must equal an
uninterrupted N+K-step run exactly, INCLUDING the data-iterator position
and RNG stream.
"""

import jax
import numpy as np
import pytest

from tests.test_train_lenet import lenet_config
from distributed_tensorflow_framework_tpu.train import Trainer


@pytest.mark.slow
def test_resume_exactness(devices, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")

    # Uninterrupted run: 8 steps.
    cfg = lenet_config(**{"train.total_steps": 8, "train.log_interval": 4})
    t_full = Trainer(cfg)
    t_full.train()
    full_params = jax.device_get(t_full.state.params)

    # Interrupted run: 4 steps + save, then fresh process-equivalent
    # restores and continues to 8.
    cfg_a = lenet_config(**{"train.total_steps": 4, "train.log_interval": 4})
    cfg_a.checkpoint.directory = ckpt_dir
    cfg_a.checkpoint.save_interval_steps = 4
    cfg_a.checkpoint.async_save = False
    t_a = Trainer(cfg_a)
    t_a.train()
    assert t_a._ckpt_manager.latest_step() == 4

    cfg_b = lenet_config(**{"train.total_steps": 8, "train.log_interval": 4})
    cfg_b.checkpoint.directory = ckpt_dir
    cfg_b.checkpoint.save_interval_steps = 100
    cfg_b.checkpoint.async_save = False
    t_b = Trainer(cfg_b)
    t_b.build()
    assert t_b.host_step == 4, "restore did not pick up step"
    t_b.train()
    resumed_params = jax.device_get(t_b.state.params)

    for a, b in zip(jax.tree.leaves(full_params), jax.tree.leaves(resumed_params)):
        np.testing.assert_array_equal(a, b)


def test_restore_specific_step(devices, tmp_path):
    """checkpoint.restore_step pins an EARLIER snapshot (the Saver's
    restore-any-checkpoint capability): latest is 6 but the run restores
    3 (the eval-old-snapshot use). Guard rails: a missing step fails
    loudly instead of falling back; TRAINING on an older restore in a
    directory holding newer steps refuses (two lineages would
    interleave); restore_step with restoring disabled refuses."""
    ckpt_dir = str(tmp_path / "ckpt")
    base = {"train.total_steps": 6, "train.log_interval": 3}
    cfg = lenet_config(**base)
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.save_interval_steps = 3
    cfg.checkpoint.async_save = False
    t = Trainer(cfg)
    t.train()
    assert sorted(t._ckpt_manager.all_steps()) == [3, 6]

    cfg_b = lenet_config(**base)
    cfg_b.checkpoint.directory = ckpt_dir
    cfg_b.checkpoint.restore_step = 3
    cfg_b.checkpoint.async_save = False
    t_b = Trainer(cfg_b)
    t_b.build()
    assert t_b.host_step == 3  # pinned at 3, not latest (6)
    # Pinned params differ from the final ones (training moved them).
    moved = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(jax.device_get(t_b.state.params)),
                        jax.tree.leaves(jax.device_get(t.state.params))))
    assert moved
    # Branch-TRAINING into the same directory must refuse.
    with pytest.raises(ValueError, match="fresh checkpoint.directory"):
        t_b.train()

    cfg_c = lenet_config(**base)
    cfg_c.checkpoint.directory = ckpt_dir
    cfg_c.checkpoint.restore_step = 5  # never saved
    t_c = Trainer(cfg_c)
    with pytest.raises(ValueError, match="restore_step=5"):
        t_c.build()

    cfg_d = lenet_config(**base)
    cfg_d.checkpoint.restore_step = 3  # no directory -> silent-start guard
    t_d = Trainer(cfg_d)
    with pytest.raises(ValueError, match="restoring is disabled"):
        t_d.build()


def test_fused_qkv_layout_mismatch_names_the_fix(devices, tmp_path):
    """Restoring an unfused-attention checkpoint into a fused template must
    fail fast naming model.fused_qkv and the transplant path, not as an
    opaque Orbax tree mismatch (ADVICE r5)."""
    from distributed_tensorflow_framework_tpu.ckpt import CheckpointManager
    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data import get_dataset
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    def cfg_for(fused):
        return load_config(base={
            "name": "ckpt-qkv",
            "mesh": {"data": 8},
            "model": {"name": "bert", "vocab_size": 128, "hidden_size": 32,
                      "num_layers": 1, "num_heads": 2, "mlp_dim": 64,
                      "max_seq_len": 32, "dtype": "float32",
                      "attention_impl": "xla", "fused_qkv": fused},
            "data": {"name": "synthetic_mlm", "vocab_size": 128,
                     "global_batch_size": 8, "seq_len": 32},
            "optimizer": {"name": "adamw", "learning_rate": 1e-4},
            "train": {"total_steps": 10},
            "checkpoint": {"directory": str(tmp_path / "ckpt"),
                           "async_save": False},
        })

    cfg = cfg_for(False)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    batch = to_global(next(get_dataset(cfg.data)), mesh)
    state = builder.init_state(0, batch)
    mgr = CheckpointManager(cfg.checkpoint)
    assert mgr.save(1, state)
    mgr.wait_until_finished()

    cfg2 = cfg_for(True)
    fused_template = StepBuilder(cfg2, mesh).init_state(1, batch)
    mgr2 = CheckpointManager(cfg2.checkpoint)
    with pytest.raises(ValueError, match=r"model\.fused_qkv") as exc:
        mgr2.restore(fused_template)
    msg = str(exc.value)
    assert "transplant" in msg and "MIGRATING" in msg
    assert "test_fused_qkv_transplant_parity" in msg

    # Matching layout still restores.
    restored = mgr.restore(builder.init_state(2, batch))
    assert restored is not None
    mgr.close()
    mgr2.close()
