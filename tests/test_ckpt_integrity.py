"""Checkpoint integrity manifests: commit, verify, quarantine, fallback.

The recovery-correctness half of ISSUE 2 (docs/RESILIENCE.md): a torn or
corrupt "latest" checkpoint must cost at most one checkpoint interval —
restore detects it by hash, quarantines the directory sideways as
``<step>.corrupt``, falls back to the newest verified older step, and the
whole episode lands in the run's telemetry stream.
"""

import json
import os

import pytest

from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.train import Trainer
from tests.test_train_lenet import lenet_config


def _train_two_checkpoints(ckpt_dir):
    """6 lenet steps saving at 3 and 6 → a two-snapshot directory."""
    cfg = lenet_config(**{"train.total_steps": 6, "train.log_interval": 3})
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.save_interval_steps = 3
    cfg.checkpoint.async_save = False
    t = Trainer(cfg)
    t.train()
    assert sorted(t._ckpt_manager.all_steps()) == [3, 6]
    return t


def _resume_trainer(ckpt_dir, **overrides):
    cfg = lenet_config(**{"train.total_steps": 6, "train.log_interval": 3,
                          **overrides})
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.async_save = False
    t = Trainer(cfg)
    t.build()
    return t


def test_save_commits_manifest(devices, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    _train_two_checkpoints(ckpt_dir)
    for step in (3, 6):
        step_dir = os.path.join(ckpt_dir, str(step))
        manifest = mf.read_manifest(step_dir)
        assert manifest is not None, f"step {step} has no commit record"
        assert manifest["step"] == step
        assert manifest["file_count"] > 0
        assert mf.verify_step_dir(step_dir, manifest) == []
    assert mf.committed_steps(ckpt_dir) == [3, 6]
    assert mf.latest_committed_step(ckpt_dir) == 6


def test_torn_checkpoint_quarantined_with_fallback(devices, tmp_path):
    """The e2e torn-write drill: newest checkpoint truncated after commit
    → restore quarantines it, falls back to step 3, and emits
    ckpt_quarantined + restore_fallback telemetry."""
    ckpt_dir = str(tmp_path / "ckpt")
    _train_two_checkpoints(ckpt_dir)
    hit = faults.corrupt_checkpoint_dir(os.path.join(ckpt_dir, "6"))
    assert hit is not None

    t = _resume_trainer(ckpt_dir)
    assert t.host_step == 3, "restore did not fall back to the verified step"
    corrupt_dir = os.path.join(ckpt_dir, "6" + mf.CORRUPT_SUFFIX)
    assert os.path.isdir(corrupt_dir), "torn step was not quarantined"
    assert not os.path.exists(os.path.join(ckpt_dir, "6"))
    record = json.load(open(os.path.join(corrupt_dir, "quarantine.json")))
    assert record["step"] == 6
    assert record["reason"] == "integrity verification failed"
    assert any("truncated" in e or "hash mismatch" in e
               for e in record["errors"])
    # quarantined steps never reappear in step listings
    assert t._ckpt_manager.all_steps() == [3]
    assert mf.latest_committed_step(ckpt_dir) == 3

    events = list(telemetry.read_events(
        os.path.join(ckpt_dir, "events.jsonl"), strict=True))
    kinds = [e["kind"] for e in events]
    assert telemetry.KIND_CKPT_QUARANTINED in kinds
    assert telemetry.KIND_RESTORE_FALLBACK in kinds
    fb = next(e for e in events
              if e["kind"] == telemetry.KIND_RESTORE_FALLBACK)
    assert fb["health"]["from_step"] == 6
    assert fb["health"]["to_step"] == 3
    # ...and the run summary surfaces the recovery activity
    summary = telemetry.summarize_events(
        os.path.join(ckpt_dir, "events.jsonl"))
    assert summary["recovery"]["quarantined"][0]["step"] == 6
    assert summary["recovery"]["restore_fallbacks"] == [
        {"from_step": 6, "to_step": 3}]
    text = telemetry.format_run_summary(summary)
    assert "quarantined checkpoint step 6" in text
    assert "restore fell back: step 6 -> 3" in text


def test_uncommitted_step_skipped(devices, tmp_path):
    """A step directory without a manifest is an interrupted save (the
    crash_in_save artifact): quarantined as uncommitted, restore uses the
    older committed step."""
    ckpt_dir = str(tmp_path / "ckpt")
    _train_two_checkpoints(ckpt_dir)
    os.remove(os.path.join(ckpt_dir, "6", mf.MANIFEST_NAME))

    t = _resume_trainer(ckpt_dir)
    assert t.host_step == 3
    corrupt_dir = os.path.join(ckpt_dir, "6" + mf.CORRUPT_SUFFIX)
    record = json.load(open(os.path.join(corrupt_dir, "quarantine.json")))
    assert record["reason"] == "uncommitted save"


def test_legacy_store_without_manifests_restores(devices, tmp_path):
    """Pre-manifest checkpoint directories (zero manifests anywhere) must
    keep restoring — trusted unverified — instead of bricking old runs."""
    ckpt_dir = str(tmp_path / "ckpt")
    _train_two_checkpoints(ckpt_dir)
    for step in (3, 6):
        os.remove(os.path.join(ckpt_dir, str(step), mf.MANIFEST_NAME))
    t = _resume_trainer(ckpt_dir)
    assert t.host_step == 6
    assert sorted(t._ckpt_manager.all_steps()) == [3, 6]


def test_explicit_restore_step_fails_loudly_on_corruption(devices, tmp_path):
    """checkpoint.restore_step pins ONE snapshot; if that snapshot is
    corrupt the restore must raise — silently reading another step is the
    exact fallback restore_step exists to prevent."""
    ckpt_dir = str(tmp_path / "ckpt")
    _train_two_checkpoints(ckpt_dir)
    faults.corrupt_checkpoint_dir(os.path.join(ckpt_dir, "3"))
    with pytest.raises(ValueError, match="integrity verification"):
        _resume_trainer(ckpt_dir, **{"checkpoint.restore_step": 3})


def test_quarantine_suffix_collision(tmp_path):
    root = str(tmp_path)
    for _ in range(2):
        os.makedirs(os.path.join(root, "5"))
        assert mf.quarantine(root, 5, "test", ["e"]) is not None
    assert os.path.isdir(os.path.join(root, "5.corrupt"))
    assert os.path.isdir(os.path.join(root, "5.corrupt.1"))
    assert mf.quarantine(root, 5, "gone") is None  # already vanished
