"""Checkpoint roundtrip for non-trivially-sharded states.

test_ckpt.py pins exact resume for the replicated-param LeNet; these pin
save/restore when params are actually sharded — MoE expert weights over
``expert`` and pipelined layer stacks over ``pipe`` — including restore
into freshly-initialized (different-valued) state of the same topology.
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.ckpt import CheckpointManager
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data import get_dataset
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder

# Big-model compile times dominate the suite wall-clock (VERDICT r1 #9).
pytestmark = pytest.mark.slow


def _roundtrip(cfg, tmp_path):
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    ds = get_dataset(cfg.data)
    batch = to_global(next(ds), mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    state, _ = step(state, batch)

    cfg.checkpoint.directory = str(tmp_path / "ckpt")
    cfg.checkpoint.async_save = False
    mgr = CheckpointManager(cfg.checkpoint)
    assert mgr.save(1, state)
    mgr.wait_until_finished()

    # Restore into a DIFFERENT seed's state: every leaf must come back
    # equal to the saved run, with the template's shardings intact.
    template = builder.init_state(123, batch)
    restored = mgr.restore(template)
    mgr.close()
    assert restored is not None
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Shardings preserved (spot-check a known-sharded leaf).
    return state, restored


def test_moe_state_roundtrip(devices, tmp_path):
    cfg = load_config(base={
        "name": "ckpt-moe",
        "mesh": {"data": 2, "expert": 2, "model": 2},
        "model": {"name": "bert", "vocab_size": 128, "hidden_size": 32,
                  "num_layers": 2, "num_heads": 2, "mlp_dim": 64,
                  "max_seq_len": 32, "dtype": "float32", "num_experts": 4},
        "data": {"name": "synthetic_mlm", "vocab_size": 128,
                 "global_batch_size": 8, "seq_len": 32},
        "optimizer": {"name": "adamw", "learning_rate": 1e-3},
        "train": {"total_steps": 2},
    })
    state, restored = _roundtrip(cfg, tmp_path)
    wi = restored.params["layer1"]["moe"]["wi"]
    assert wi.sharding.spec[0] == "expert", wi.sharding.spec


def test_pipelined_state_roundtrip(devices, tmp_path):
    cfg = load_config(base={
        "name": "ckpt-pp",
        "mesh": {"data": 2, "pipe": 4},
        "model": {"name": "bert", "vocab_size": 64, "hidden_size": 32,
                  "num_layers": 4, "num_heads": 2, "mlp_dim": 64,
                  "max_seq_len": 16, "dtype": "float32",
                  "pipeline_stages": 4},
        "data": {"name": "synthetic_mlm", "vocab_size": 64,
                 "global_batch_size": 16, "seq_len": 16},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.1},
        "train": {"total_steps": 2},
    })
    state, restored = _roundtrip(cfg, tmp_path)
    leaf = jax.tree.leaves(restored.params["pipeline_layers"])[0]
    assert leaf.sharding.spec[0] == "pipe", leaf.sharding.spec
