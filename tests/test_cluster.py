"""Gang supervision: the cluster-level fault-tolerance layer.

Two tiers, both fast (no JAX, no real gang):

* Pure-library units for core/cluster.py — heartbeat naming, the worker
  discovery env, per-worker crash-loop keying, the rejoin→drop decision,
  the gang refit (mesh fit + effective-batch preservation) and the exit
  barrier's ordering/timeout, all driven through their test seams.
* Supervisor-loop scenarios for scripts/train_cluster.py — main() runs
  in-process with ``llc.spawn_gang`` monkeypatched to launch tiny
  ``python -c`` stub workers, so the whole ladder (coordinated restart,
  chaos drop → gang refit, stale-heartbeat watchdog, rejoin timeout,
  port-race retry, crash-loop break) is exercised against real child
  processes and real signals in well under a second per scenario.

The end-to-end gang drills (a REAL 2-process jax.distributed run killed
mid-step and resumed bit-exactly) live in tests/test_cluster_drill.py
behind the slow marker.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_tensorflow_framework_tpu.core import cluster  # noqa: E402
from distributed_tensorflow_framework_tpu.core import faults  # noqa: E402
from distributed_tensorflow_framework_tpu.core import goodput  # noqa: E402
from distributed_tensorflow_framework_tpu.core import supervision  # noqa: E402
from distributed_tensorflow_framework_tpu.core import telemetry  # noqa: E402


# ---------------------------------------------------------------------------
# Heartbeat file contract
# ---------------------------------------------------------------------------


class TestHeartbeatContract:
    def test_single_process_keeps_legacy_name(self):
        assert cluster.heartbeat_name(0, 1) == "heartbeat.json"

    def test_gang_names_are_per_worker(self):
        assert cluster.heartbeat_name(0, 2) == "heartbeat-p0.json"
        assert cluster.heartbeat_name(1, 2) == "heartbeat-p1.json"

    def test_out_of_range_index_is_typed_error(self):
        with pytest.raises(cluster.ClusterSpecError):
            cluster.heartbeat_name(2, 2)
        with pytest.raises(cluster.ClusterSpecError):
            cluster.heartbeat_name(-1, 2)

    def test_path_joins_ckpt_dir(self):
        assert cluster.heartbeat_path("/ck", 1, 2) == "/ck/heartbeat-p1.json"


# ---------------------------------------------------------------------------
# Worker discovery env
# ---------------------------------------------------------------------------


class TestWorkerEnv:
    def test_gang_sets_discovery_triple(self):
        env = cluster.worker_env(
            {"PATH": "/bin"}, coordinator_port=1234, num_processes=2,
            process_id=1, devices_per_proc=2)
        assert env["JAX_COORDINATOR_ADDRESS"] == "127.0.0.1:1234"
        assert env["JAX_NUM_PROCESSES"] == "2"
        assert env["JAX_PROCESS_ID"] == "1"
        assert env["JAX_PLATFORMS"] == "cpu"
        assert "xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
        assert env["PATH"] == "/bin"  # base env preserved

    def test_single_process_strips_discovery(self):
        # A gang refit down to one process must NOT inherit the dead
        # coordinator's address — the survivor runs single-process.
        base = {"JAX_COORDINATOR_ADDRESS": "127.0.0.1:9", "JAX_NUM_PROCESSES":
                "2", "JAX_PROCESS_ID": "1"}
        env = cluster.worker_env(
            base, coordinator_port=1234, num_processes=1, process_id=0,
            devices_per_proc=4)
        for key in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID"):
            assert key not in env
        assert "xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]

    def test_base_env_not_mutated(self):
        base = {"JAX_PROCESS_ID": "7"}
        cluster.worker_env(base, coordinator_port=1, num_processes=1,
                           process_id=0, devices_per_proc=1)
        assert base == {"JAX_PROCESS_ID": "7"}

    def test_bad_process_id_is_typed_error(self):
        with pytest.raises(cluster.ClusterSpecError):
            cluster.worker_env({}, coordinator_port=1, num_processes=2,
                               process_id=2, devices_per_proc=1)


# ---------------------------------------------------------------------------
# Per-worker crash-loop keying
# ---------------------------------------------------------------------------


class TestGangBreaker:
    def test_identical_failures_trip_one_worker(self):
        b = cluster.GangBreaker(threshold=2)
        assert not b.record(1, rc=139, last_step=5, ckpt_step=5)
        assert b.record(1, rc=139, last_step=5, ckpt_step=5)

    def test_other_workers_noise_does_not_reset_streak(self):
        # The whole point of per-worker keying: worker 0's unrelated
        # failure interleaving must not launder worker 1's crash loop.
        b = cluster.GangBreaker(threshold=2)
        assert not b.record(1, rc=139, last_step=5, ckpt_step=5)
        assert not b.record(0, rc=1, last_step=9, ckpt_step=5)
        assert b.record(1, rc=139, last_step=5, ckpt_step=5)

    def test_transient_resets_that_workers_streak(self):
        b = cluster.GangBreaker(threshold=2)
        assert not b.record(1, rc=139, last_step=5, ckpt_step=5)
        assert not b.record(1, rc=85, last_step=5, ckpt_step=5,
                            transient=True)
        assert not b.record(1, rc=139, last_step=5, ckpt_step=5)

    def test_report_tags_process_id(self):
        b = cluster.GangBreaker(threshold=2)
        b.record(3, rc=1, last_step=None, ckpt_step=None)
        assert b.report(3)["process_id"] == 3
        assert b.report(9) == {"verdict": "no_failures_recorded",
                               "process_id": 9}


# ---------------------------------------------------------------------------
# Rejoin watchdog decision
# ---------------------------------------------------------------------------


class TestDecideRejoin:
    def test_disabled_watchdog(self):
        assert cluster.decide_rejoin({0: None, 1: None}, elapsed_s=99,
                                     rejoin_timeout_s=0.0) == []

    def test_window_not_elapsed(self):
        assert cluster.decide_rejoin({0: 1.0, 1: None}, elapsed_s=5,
                                     rejoin_timeout_s=10) == []

    def test_nobody_joined_means_still_booting(self):
        assert cluster.decide_rejoin({0: None, 1: None}, elapsed_s=60,
                                     rejoin_timeout_s=10) == []

    def test_overdue_workers_dropped_when_peers_joined(self):
        assert cluster.decide_rejoin({0: 1.0, 1: None, 2: None},
                                     elapsed_s=60,
                                     rejoin_timeout_s=10) == [1, 2]


# ---------------------------------------------------------------------------
# Gang refit (the cluster-level rc-84 decision)
# ---------------------------------------------------------------------------


class TestDecideRefit:
    def test_shrink_preserves_effective_batch(self):
        refit = cluster.decide_refit(
            {"data": 4}, 16, 1, process_count=1, devices_per_proc=2)
        assert refit.process_count == 1
        assert refit.n_devices == 2
        assert refit.sizes["data"] == 2
        # 16×1 over dp=4 → 8×2 over dp=2: same effective batch.
        assert (refit.global_batch, refit.grad_accum) == (8, 2)
        assert refit.batch_preserved
        assert "mesh.data=2" in refit.overrides
        assert "checkpoint.allow_reshard=true" in refit.overrides
        assert "data.global_batch_size=8" in refit.overrides
        assert "train.grad_accum_steps=2" in refit.overrides

    def test_inferred_data_axis_cannot_promise_preservation(self):
        refit = cluster.decide_refit(
            {"data": -1}, 16, 1, process_count=1, devices_per_proc=2)
        assert not refit.batch_preserved
        assert not any("global_batch_size" in o for o in refit.overrides)

    def test_zero_survivors_is_typed_error(self):
        with pytest.raises(cluster.ClusterSpecError):
            cluster.decide_refit({"data": 2}, 8, 1, process_count=0,
                                 devices_per_proc=2)


# ---------------------------------------------------------------------------
# Exit barrier
# ---------------------------------------------------------------------------


class TestExitBarrier:
    def test_already_committed_returns_without_sleep(self):
        sleeps = []
        got = cluster.exit_barrier(
            "/ck", step=5, timeout_s=10,
            latest_step_fn=lambda d: 7, sleep=sleeps.append,
            clock=lambda: 0.0)
        assert got == 7
        assert sleeps == []

    def test_waits_for_commit_record(self):
        # The ordering contract: a survivor polling the manifest must NOT
        # return before the chief's commit record for the final step
        # lands — here it lands on the third poll.
        seen = iter([None, None, 5])
        sleeps = []
        got = cluster.exit_barrier(
            "/ck", step=5, timeout_s=10, poll_s=0.25,
            latest_step_fn=lambda d: next(seen), sleep=sleeps.append,
            clock=lambda: 0.0)
        assert got == 5
        assert sleeps == [0.25, 0.25]

    def test_stale_commit_does_not_release(self):
        # A leftover commit from a PREVIOUS attempt (step 3 < final step
        # 5) must not satisfy the barrier.
        seen = iter([3, 3, 5])
        got = cluster.exit_barrier(
            "/ck", step=5, timeout_s=10,
            latest_step_fn=lambda d: next(seen), sleep=lambda s: None,
            clock=lambda: 0.0)
        assert got == 5

    def test_timeout_raises_instead_of_dropping_shards(self):
        t = iter(range(100))
        with pytest.raises(cluster.ExitBarrierTimeoutError) as e:
            cluster.exit_barrier(
                "/ck", step=5, timeout_s=3.0,
                latest_step_fn=lambda d: None, sleep=lambda s: None,
                clock=lambda: float(next(t)))
        assert "step 5" in str(e.value)


# ---------------------------------------------------------------------------
# Cluster chaos fault parsing
# ---------------------------------------------------------------------------


class TestClusterFaults:
    def test_kill_worker_parses(self):
        (f,) = faults.FaultPlan.parse("kill_worker:1:3").faults
        assert (f.kind, f.worker, f.step) == ("kill_worker", 1, 3)
        assert f.point == "gang_chaos"

    def test_tick_defaults_to_first(self):
        (f,) = faults.FaultPlan.parse("drop_worker:2").faults
        assert (f.worker, f.step) == (2, 1)

    def test_stall_worker_parses_duration(self):
        (f,) = faults.FaultPlan.parse("stall_worker:0:10s").faults
        assert (f.worker, f.seconds, f.step) == (0, 10.0, 1)

    def test_stall_worker_zero_means_forever(self):
        (f,) = faults.FaultPlan.parse("stall_worker:1:0").faults
        assert f.seconds == faults._STALL_FOREVER_S

    def test_bad_specs_raise(self):
        for spec in ("kill_worker:x", "kill_worker:-1", "kill_worker:1:0",
                     "drop_worker:", "stall_worker:-1:5"):
            with pytest.raises(ValueError):
                faults.FaultPlan.parse(spec)

    def test_fire_at_gang_chaos_point(self):
        plan = faults.FaultPlan.parse("kill_worker:1:2,stall_worker:0:5s")
        assert [f.kind for f in plan.fire("gang_chaos", step=1)] == \
            ["stall_worker"]
        assert [f.kind for f in plan.fire("gang_chaos", step=2)] == \
            ["kill_worker"]
        assert plan.fire("gang_chaos", step=2) == []  # once per process


# ---------------------------------------------------------------------------
# Gang goodput stitching (satellite 1)
# ---------------------------------------------------------------------------


def _write_goodput(path, run_id, *, t0, wall, host=None, final=True):
    ev = telemetry.make_event(
        telemetry.KIND_GOODPUT, run_id=run_id,
        metrics={"wall_s": wall, "goodput_frac": 0.8},
        t0=t0, final=final,
        buckets={"step_compute": wall * 0.8, "other": wall * 0.2},
        counters={"steps": 10},
        **({"process_id": host} if host is not None else {}))
    with open(path, "a") as fh:
        fh.write(json.dumps(ev) + "\n")


class TestGangStitch:
    def test_per_host_streams_join_by_process_id(self, tmp_path):
        chief = str(tmp_path / "events.jsonl")
        peer = str(tmp_path / "events-p1.jsonl")
        # Host 0: two attempts with a 5 s restart gap between them.
        _write_goodput(chief, "r0a", t0=100.0, wall=10.0, host=0)
        _write_goodput(chief, "r0b", t0=115.0, wall=5.0, host=0)
        # Host 1: its own timeline (different pre-ledger import time).
        _write_goodput(peer, "r1a", t0=100.5, wall=9.0, host=1)
        _write_goodput(peer, "r1b", t0=116.0, wall=4.0, host=1)
        sup = tmp_path / "supervisor_events.jsonl"
        w = telemetry.TelemetryWriter(str(sup))
        w.emit(telemetry.KIND_SUPERVISOR_ATTEMPT, attempt=1, rc=137,
               classification="crashed", process_id=1)
        w.close()

        g = goodput.stitch_attempts([chief, peer])
        assert g is not None
        # Top level stays the chief's timeline.
        assert g["wall_s"] == pytest.approx(10 + 5 + 5)
        assert g["restart_gaps"][0]["classification"] == "crashed"
        per_host = g["per_host"]
        assert set(per_host) == {"0", "1"}
        # Each host's buckets (gap included) sum to its OWN span.
        for host in per_host.values():
            assert sum(host["buckets"].values()) == \
                pytest.approx(host["wall_s"])
        assert per_host["1"]["wall_s"] == pytest.approx(9 + 4 + 6.5)
        assert per_host["1"]["restart_gaps"][0]["classification"] == "crashed"
        table = goodput.format_goodput_table(g)
        assert "host 0:" in table and "host 1:" in table

    def test_single_stream_keeps_flat_shape(self, tmp_path):
        chief = str(tmp_path / "events.jsonl")
        _write_goodput(chief, "r0", t0=100.0, wall=10.0)
        g = goodput.stitch_attempts(chief)
        assert g is not None
        assert "per_host" not in g

    def test_analyze_trace_groups_worker_streams(self, tmp_path):
        from scripts import analyze_trace as at
        paths = [str(tmp_path / n) for n in
                 ("events-p1.jsonl", "events.jsonl",
                  "supervisor_events.jsonl")]
        groups = at._group_streams(paths)
        assert groups[0] == [str(tmp_path / "events.jsonl"),
                             str(tmp_path / "events-p1.jsonl")]
        assert groups[1] == [str(tmp_path / "supervisor_events.jsonl")]

    def test_analyze_trace_merges_multiple_run_dirs(self, tmp_path):
        from scripts import analyze_trace as at
        d0, d1 = tmp_path / "host0", tmp_path / "host1"
        d0.mkdir(), d1.mkdir()
        _write_goodput(str(d0 / "events.jsonl"), "r0", t0=100.0, wall=10.0,
                       host=0)
        _write_goodput(str(d1 / "events-p1.jsonl"), "r1", t0=100.5,
                       wall=9.0, host=1)
        out = tmp_path / "summary.json"
        assert at.main([str(d0), str(d1), "--json", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert doc["schema"] == "dtf-run-summary/1"
        assert len(doc["worker_streams"]) == 2
        assert set(doc["goodput_ledger"]["per_host"]) == {"0", "1"}


# ---------------------------------------------------------------------------
# Supervisor-loop scenarios (in-process main(), stub subprocess workers)
# ---------------------------------------------------------------------------

from scripts import train_cluster as tc  # noqa: E402


def _stub_crash(rc=1, text=""):
    """A worker that (optionally) prints and exits rc immediately."""
    return (f"import sys\n"
            f"print({text!r})\n"
            f"sys.exit({rc})\n")


def _stub_graceful(hb_path=None, step=3):
    """A worker that heartbeats (optionally) and honors SIGTERM with the
    graceful-preemption exit code, like a real chief force-saving."""
    return textwrap.dedent(f"""
        import json, os, signal, sys, time
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(83))
        hb = {hb_path!r}
        while True:
            if hb:
                tmp = hb + "." + str(os.getpid()) + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump({{"t": time.time(), "pid": os.getpid(),
                               "last_completed_step": {step}}}, fh)
                os.replace(tmp, hb)
            time.sleep(0.05)
    """)


def _stub_beat_once_then_wedge(hb_path):
    """One heartbeat, then silence — the wedged-collective signature."""
    return textwrap.dedent(f"""
        import json, os, time
        hb = {hb_path!r}
        with open(hb, "w") as fh:
            json.dump({{"t": time.time(), "pid": os.getpid(),
                       "last_completed_step": 1}}, fh)
        time.sleep(60)
    """)


@pytest.fixture
def gang(monkeypatch, tmp_path):
    """Harness for in-process tc.main(): monkeypatched spawn that launches
    ``python -c`` stubs (one program list per attempt), plus signal-handler
    and fault-plan restoration."""
    old_handlers = {s: signal.getsignal(s)
                    for s in (signal.SIGTERM, signal.SIGINT)}
    monkeypatch.setattr(tc, "_cancelled", False)
    calls = {"procs": [], "envs": []}

    def arm(programs_by_attempt):
        def spawn(train_args, *, procs, devices_per_proc, workdir, port,
                  base_env=None):
            idx = min(len(calls["procs"]), len(programs_by_attempt) - 1)
            programs = programs_by_attempt[idx]
            calls["procs"].append(procs)
            calls["envs"].append(dict(base_env or {}))
            os.makedirs(workdir, exist_ok=True)
            children, logs = [], []
            for i in range(procs):
                log = open(os.path.join(workdir, f"worker-{i}.log"), "w")
                logs.append(log)
                children.append(subprocess.Popen(
                    [sys.executable, "-c", programs[i]],
                    stdout=log, stderr=subprocess.STDOUT))
            return children, logs
        monkeypatch.setattr(tc.llc, "spawn_gang", spawn)
        return calls

    yield arm, calls
    faults.install(None)
    for s, h in old_handlers.items():
        signal.signal(s, h)


def _classifications(events_path):
    out = []
    for ev in telemetry.read_events(
            events_path, kind=telemetry.KIND_SUPERVISOR_ATTEMPT,
            strict=False):
        out.append((ev.get("extra") or {}))
    return out


class TestGangSupervisor:
    def _ck(self, tmp_path):
        ck = tmp_path / "ck"
        ck.mkdir()
        return str(ck)

    def _run(self, tmp_path, extra_args, cmd_extra=()):
        ck = self._ck(tmp_path)
        rc = tc.main([
            "--workdir", str(tmp_path / "logs"),
            "--retry-sleep", "0.05", "--jitter", "0", "--backoff-max", "0.1",
            *extra_args,
            "--", "--set", f"checkpoint.directory={ck}", *cmd_extra,
        ])
        return rc, os.path.join(ck, "supervisor_events.jsonl"), ck

    def test_worker_crash_restarts_whole_gang(self, gang, tmp_path):
        arm, calls = gang
        ck = str(tmp_path / "ck")
        arm([
            [_stub_graceful(os.path.join(ck, "heartbeat-p0.json")),
             _stub_crash(rc=1)],
            [_stub_crash(rc=0), _stub_crash(rc=0)],
        ])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "2", "--max-attempts", "3",
                       "--chaos-tick", "0"])
        assert rc == 0
        assert calls["procs"] == [2, 2]
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == ["crashed", "done"]
        # Root cause attributed to the crashing worker; the SIGTERMed
        # survivor's 83 is fallout, not the classification.
        assert attempts[0]["process_id"] == 1
        assert attempts[0]["rc"] == 1

    def test_drop_worker_chaos_refits_gang(self, gang, tmp_path):
        arm, calls = gang
        ck = str(tmp_path / "ck")
        faults.install("drop_worker:1:1")
        arm([
            [_stub_graceful(os.path.join(ck, "heartbeat-p0.json")),
             _stub_graceful(os.path.join(ck, "heartbeat-p1.json"))],
            [_stub_crash(rc=0)],
        ])
        rc, events, _ = self._run(
            tmp_path,
            ["--procs", "2", "--devices-per-proc", "2",
             "--max-attempts", "2", "--chaos-tick", "0.2"],
            cmd_extra=["--set", "mesh.data=4",
                       "--set", "data.global_batch_size=16"])
        assert rc == 0
        # Gang shrank 2 → 1 processes and the refit consumed NO attempt.
        assert calls["procs"] == [2, 1]
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == \
            ["gang_refit", "done"]
        assert attempts[0]["attempt"] == attempts[1]["attempt"] == 1
        (resize,) = [
            (ev.get("extra") or {}) for ev in telemetry.read_events(
                events, kind=telemetry.KIND_MESH_RESIZED, strict=False)]
        assert resize["process_count"] == 1
        assert resize["dropped_workers"] == [1]
        assert resize["to_axes"]["data"] == 2
        # 16×1 over dp=4 → 8×2 over dp=2: effective batch preserved.
        assert resize["effective_batch_preserved"] is True
        assert (resize["global_batch"], resize["grad_accum"]) == (8, 2)
        overrides = calls["envs"][1][supervision.ELASTIC_OVERRIDES_ENV]
        assert "mesh.data=2" in overrides
        assert "data.global_batch_size=8" in overrides
        assert "train.grad_accum_steps=2" in overrides

    def test_stale_heartbeat_watchdog_kills_and_restarts(self, gang,
                                                         tmp_path):
        arm, calls = gang
        ck = str(tmp_path / "ck")
        arm([
            [_stub_graceful(os.path.join(ck, "heartbeat-p0.json")),
             _stub_beat_once_then_wedge(
                 os.path.join(ck, "heartbeat-p1.json"))],
            [_stub_crash(rc=0), _stub_crash(rc=0)],
        ])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "2", "--max-attempts", "3",
                       "--chaos-tick", "0",
                       "--heartbeat-timeout", "0.4",
                       "--heartbeat-poll", "0.05"])
        assert rc == 0
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == ["hung", "done"]
        assert attempts[0]["process_id"] == 1
        assert attempts[0]["hung"] is True

    def test_rejoin_timeout_drops_and_refits(self, gang, tmp_path):
        arm, calls = gang
        ck = str(tmp_path / "ck")
        arm([
            # Worker 0 joins (heartbeats); worker 1 never does.
            [_stub_graceful(os.path.join(ck, "heartbeat-p0.json")),
             "import time; time.sleep(60)"],
            [_stub_crash(rc=0)],
        ])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "2", "--max-attempts", "2",
                       "--chaos-tick", "0",
                       "--rejoin-timeout", "0.5"])
        assert rc == 0
        assert calls["procs"] == [2, 1]
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == \
            ["gang_refit", "done"]
        (resize,) = [
            (ev.get("extra") or {}) for ev in telemetry.read_events(
                events, kind=telemetry.KIND_MESH_RESIZED, strict=False)]
        assert resize["dropped_workers"] == [1]

    def test_port_bind_race_relaunches_for_free(self, gang, tmp_path):
        arm, calls = gang
        arm([
            [_stub_crash(rc=1, text="RuntimeError: Address already in use"),
             _stub_graceful()],
            [_stub_crash(rc=0), _stub_crash(rc=0)],
        ])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "2", "--max-attempts", "1",
                       "--chaos-tick", "0"])
        # max-attempts=1 and we still recovered: the bind race consumed
        # no attempt.
        assert rc == 0
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == \
            ["port_race", "done"]

    def test_crash_loop_breaks_per_worker(self, gang, tmp_path):
        arm, calls = gang
        arm([[_stub_crash(rc=7)]])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "1", "--max-attempts", "5",
                       "--chaos-tick", "0",
                       "--crash-loop-threshold", "2"])
        assert rc == 7
        assert calls["procs"] == [1, 1]  # broke after 2, not 5
        loops = [ev for ev in telemetry.read_events(
            events, kind=telemetry.KIND_CRASH_LOOP, strict=False)]
        assert len(loops) == 1
        assert (loops[0].get("extra") or {})["process_id"] == 0

    def test_cancellation_is_not_retried(self, gang, tmp_path):
        arm, calls = gang
        arm([[_stub_crash(rc=130)]])
        rc, events, _ = self._run(
            tmp_path, ["--procs", "1", "--max-attempts", "5",
                       "--chaos-tick", "0"])
        assert rc == 130
        assert calls["procs"] == [1]
        attempts = _classifications(events)
        assert [a["classification"] for a in attempts] == ["cancelled"]


# ---------------------------------------------------------------------------
# Command-knob parsing
# ---------------------------------------------------------------------------


class TestParseRejoinTimeout:
    def test_default_disabled(self):
        assert tc.parse_rejoin_timeout(["--set", "mesh.data=2"]) == 0.0

    def test_set_override_wins_last(self):
        cmd = ["--set", "cluster.rejoin_timeout_s=5",
               "--set", "cluster.rejoin_timeout_s=30"]
        assert tc.parse_rejoin_timeout(cmd) == 30.0

    def test_yaml_knob(self, tmp_path):
        cfg = tmp_path / "c.yaml"
        cfg.write_text("cluster:\n  rejoin_timeout_s: 12.5\n")
        assert tc.parse_rejoin_timeout(["--config", str(cfg)]) == 12.5


class TestGangProbe:
    """probe_gang's failure classifier — the probe itself (a real
    2-process jax spawn) belongs to the slow tier via the
    gang_capability fixture; what tier-1 pins is the signature
    contract the skip decision rides on."""

    def test_cpu_backend_signature_is_unsupported(self):
        assert cluster.is_gang_unsupported(
            "jaxlib.xla_extension.XlaRuntimeError: INVALID_ARGUMENT: "
            "Multiprocess computations aren't implemented on the CPU "
            "backend.")

    def test_environmental_flake_is_not(self):
        # A refused coordinator connection is a flake worth surfacing,
        # not a this-backend-cannot-do-gangs verdict.
        assert not cluster.is_gang_unsupported(
            "RuntimeError: connection refused: 127.0.0.1:4444")

    def test_probe_worker_script_forces_cpu_via_jax_config(self):
        # The env var alone loses to a sitecustomize that sets
        # jax_platforms through jax.config at interpreter start.
        assert 'jax.config.update("jax_platforms", "cpu")' \
            in cluster._PROBE_WORKER
