"""End-to-end gang supervision drills (slow tier).

The whole cluster-resilience story against a REAL 2-process
``jax.distributed`` gang, supervised by the real scripts/train_cluster.py:

* kill drill — ``kill_worker:1:2`` SIGKILLs worker 1 mid-run; the
  supervisor must coordinate the shutdown (SIGTERM the chief, which
  force-saves via the graceful-preemption contract), relaunch the gang
  unattended, and the resumed run must reproduce the uninterrupted
  control's step metrics exactly.  The restart gap must land classified
  in the stitched per-host goodput ledger.
* drop drill — ``drop_worker:1`` loses a worker permanently; the
  supervisor must refit the mesh to the surviving process count
  (gang-level rc-84), preserve the EFFECTIVE batch via grad
  accumulation, relaunch smaller, and consume NO attempt doing it
  (enforced by running with ``--max-attempts 1``).

The drill's supervisor_events.jsonl is archived to ``DTF_GANG_DRILL_DIR``
when the tier driver sets it (scripts/run_tier1.sh), like the fleet
drill's serve bench.

Both drills gate on ``cluster.probe_gang()``: stock CPU jaxlib forms the
gang but rejects multi-process computations at compile time ("Multiprocess
computations aren't implemented on the CPU backend"), so on such hosts the
drills SKIP with the probe's evidence instead of failing — the same
preflight contract as chip_window_queue.sh §0b/§15.  The supervisor's
decision logic itself is covered without JAX in tests/test_cluster.py.
"""

import glob
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.slowest]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_tensorflow_framework_tpu.core import goodput  # noqa: E402
from distributed_tensorflow_framework_tpu.core import telemetry  # noqa: E402
from distributed_tensorflow_framework_tpu.core import tracing  # noqa: E402
from scripts import analyze_trace  # noqa: E402

SCRIPT = os.path.join(REPO, "scripts", "train_cluster.py")

# Both drills take the session-scoped ``gang_capability`` fixture
# (tests/conftest.py): one probe_gang() per session, skip-with-evidence
# on backends whose compiler rejects multi-process programs.


def _run_super(args, *, faults=None, timeout=600):
    env = dict(os.environ)
    env.pop("DTF_FAULTS", None)
    env.pop("DTF_FAULTS_STATE", None)
    if faults:
        env["DTF_FAULTS"] = faults
    return subprocess.run(
        [sys.executable, SCRIPT, *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def _lenet_cmd(ck_dir, *extra):
    return [
        "--config", "configs/lenet_mnist.yaml",
        "--set", "train.log_interval=4",
        "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
        # Frequent goodput snapshots: even a SIGKILLed worker leaves a
        # recent ledger for the stitcher.
        "--set", "train.goodput_interval_s=2",
        "--set", f"checkpoint.directory={ck_dir}",
        "--set", "checkpoint.save_interval_steps=2",
        *extra,
    ]


def _step_metrics(log: str, step: int) -> str:
    m = re.search(
        rf"step {step}: (grad_norm=\S+) (learning_rate=\S+) (loss=\S+) "
        rf"(top1=\S+) (top5=\S+)", log)
    assert m, f"no step-{step} metrics line:\n{log[-2000:]}"
    return " ".join(m.groups())


def _classifications(events_path):
    return [
        str((ev.get("extra") or {}).get("classification"))
        for ev in telemetry.read_events(
            events_path, kind=telemetry.KIND_SUPERVISOR_ATTEMPT,
            strict=False)
    ]


def _archive(events_path, name):
    art = os.environ.get("DTF_GANG_DRILL_DIR")
    if art and os.path.exists(events_path):
        os.makedirs(art, exist_ok=True)
        shutil.copyfile(events_path, os.path.join(art, name))


def test_kill_worker_gang_restart_resumes_bit_exact(tmp_path, gang_capability):
    # Control: the same 2-process gang, uninterrupted.
    ctrl_ck = tmp_path / "ctrl-ck"
    r = _run_super([
        "--procs", "2", "--devices-per-proc", "2",
        "--workdir", str(tmp_path / "w-ctrl"), "--max-attempts", "1",
        "--chaos-tick", "0",
        "--", *_lenet_cmd(ctrl_ck, "--set", "train.total_steps=8",
                          "--set", "mesh.data=-1"),
    ])
    assert r.returncode == 0, r.stderr[-4000:]
    want = _step_metrics(
        (tmp_path / "w-ctrl" / "worker-0.log").read_text(), 8)

    # Drill: SIGKILL worker 1 at chaos tick 2 (seconds after the whole
    # gang heartbeated). The supervisor must SIGTERM the survivor,
    # relaunch the gang unattended, and resume to the same step-8 state.
    ck = tmp_path / "ck"
    r = _run_super([
        "--procs", "2", "--devices-per-proc", "2",
        "--workdir", str(tmp_path / "w-drill"), "--max-attempts", "3",
        "--retry-sleep", "0.2", "--jitter", "0",
        "--chaos-tick", "1",
        "--", *_lenet_cmd(ck, "--set", "train.total_steps=8",
                          "--set", "mesh.data=-1"),
    ], faults="kill_worker:1:2")
    events = str(ck / "supervisor_events.jsonl")
    _archive(events, "GANG_DRILL_EVENTS.jsonl")
    assert r.returncode == 0, r.stderr[-4000:]
    assert "chaos SIGKILL worker 1" in r.stderr, r.stderr[-4000:]

    # Root cause attributed to worker 1 (SIGKILL → 137); the run ends
    # "done" after the unattended relaunch.
    cls = _classifications(events)
    assert cls[0] == "crashed", (cls, r.stderr[-3000:])
    assert cls[-1] == "done", cls
    crashed = [
        (ev.get("extra") or {}) for ev in telemetry.read_events(
            events, kind=telemetry.KIND_SUPERVISOR_ATTEMPT, strict=False)
        if (ev.get("extra") or {}).get("classification") == "crashed"]
    assert crashed[0]["process_id"] == 1
    assert crashed[0]["rc"] == 137

    # Bit-exact resume: the relaunched gang's chief reproduces the
    # uninterrupted control's step-8 metrics.
    got = _step_metrics(
        (tmp_path / "w-drill" / "worker-0.log").read_text(), 8)
    assert got == want

    # The restart gap is classified in the stitched per-host ledger.
    streams = [str(ck / "events.jsonl")]
    if (ck / "events-p1.jsonl").exists():
        streams.append(str(ck / "events-p1.jsonl"))
    g = goodput.stitch_attempts(streams)
    assert g is not None
    assert len(g["attempts"]) >= 2, g["attempts"]
    gaps = g["restart_gaps"]
    assert gaps and gaps[0]["classification"] == "crashed", gaps
    assert g["buckets"]["restart_gap"] > 0
    # Per-host section joins both workers' streams by process_id.
    assert "0" in (g.get("per_host") or {}), sorted(g)

    # Tracing: the whole recovery is ONE supervisor-rooted trace across
    # three processes — supervisor.run → supervisor.attempt per attempt,
    # each attempt parenting both workers' worker.run spans via
    # DTF_TRACE_CTX, with the restart gap a span on the critical path.
    traces = analyze_trace.build_traces(
        analyze_trace.collect_spans(analyze_trace._events_files(str(ck))))
    sup = [t for t in traces
           if any(s["name"] == "supervisor.run" for s in t["spans"])]
    assert len(sup) == 1, [t["trace"] for t in traces]
    tree = sup[0]
    names = [s["name"] for s in tree["spans"]]
    assert names.count("supervisor.attempt") >= 2, names
    assert "supervisor.restart_gap" in names
    workers = {s["service"] for s in tree["spans"]
               if s["name"] == "worker.run"}
    assert {"worker0", "worker1"} <= workers, workers
    by_id = {s["span"]: s for s in tree["spans"]}
    for s in tree["spans"]:
        if s["name"] == "worker.run":
            assert by_id[s["parent"]]["name"] == "supervisor.attempt", s
    assert analyze_trace.critical_path(tree)["restart_gap"] > 0

    # Flight recorders fired on both sides of the fault: the supervisor
    # dumped when it classified the crash (ring holds the crashed
    # attempt's span; the still-open supervisor.run is its parent), and
    # the SIGTERMed survivor flushed its telemetry and dumped before the
    # supervisor's SIGKILL grace expired (the dump existing at all is
    # the satellite-2 durability pin).
    dumps = [json.loads(open(p).read())
             for p in glob.glob(str(ck / "flightrec-*.json"))]
    assert dumps, "no flight-recorder dump under the checkpoint dir"
    sup_dump = next(d for d in dumps if "crashed" in d["reason"])
    ring_spans = [(e.get("extra") or {}).get("name")
                  for e in sup_dump["events"]
                  if e.get("kind") == telemetry.KIND_SPAN]
    assert "supervisor.attempt" in ring_spans, ring_spans
    assert any(s["name"] == "supervisor.run"
               for s in sup_dump["open_spans"])
    assert any(d["reason"] == "graceful_preemption" for d in dumps), \
        [d["reason"] for d in dumps]
    assert all(d["schema"] == tracing.FLIGHTREC_SCHEMA for d in dumps)

    # Perfetto export for the tier driver's artifact dir.
    trace_dir = os.environ.get("DTF_TRACE_DIR")
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        assert analyze_trace.main(
            [str(ck), "--spans", "--perfetto",
             os.path.join(trace_dir, "GANG_TRACE.json")]) == 0


def test_drop_worker_refits_gang_without_consuming_attempt(tmp_path, gang_capability):
    # Drop worker 1 permanently at tick 2. mesh.data=4 over 2 procs × 2
    # devices; the refit must land on data=2 over the 1 surviving
    # process and preserve the effective batch (16×1 → 8×2).
    # --max-attempts 1 makes "no attempt consumed" an execution fact:
    # the run only completes if the refit relaunch was free.
    ck = tmp_path / "ck"
    r = _run_super([
        "--procs", "2", "--devices-per-proc", "2",
        "--workdir", str(tmp_path / "w"), "--max-attempts", "1",
        "--chaos-tick", "1",
        "--", *_lenet_cmd(ck, "--set", "train.total_steps=6",
                          "--set", "mesh.data=4",
                          "--set", "data.global_batch_size=16"),
    ], faults="drop_worker:1:2")
    events = str(ck / "supervisor_events.jsonl")
    _archive(events, "GANG_DRILL_REFIT_EVENTS.jsonl")
    assert r.returncode == 0, r.stderr[-4000:]
    assert "gang refit #1" in r.stderr, r.stderr[-4000:]

    cls = _classifications(events)
    assert cls == ["gang_refit", "done"], (cls, r.stderr[-3000:])
    (resize,) = [
        (ev.get("extra") or {}) for ev in telemetry.read_events(
            events, kind=telemetry.KIND_MESH_RESIZED, strict=False)]
    assert resize["process_count"] == 1
    assert resize["dropped_workers"] == [1]
    assert resize["to_axes"]["data"] == 2
    assert resize["effective_batch_preserved"] is True
    assert (resize["global_batch"], resize["grad_accum"]) == (8, 2)

    # The relaunched survivor ran single-process on its 2 local devices.
    chief = (tmp_path / "w" / "worker-0.log").read_text()
    assert "2 local / 2 global devices" in chief, chief[-2000:]
