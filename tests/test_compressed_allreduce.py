"""Compressed (bf16-wire) gradient all-reduce — train.grad_allreduce_dtype."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _shard_map_allreduce(mesh, accumulate_f32):
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    @functools.partial(
        coll.shard_map, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def fn(x):
        return coll.allreduce_gradients(
            {"g": x}, ("data",), compute_dtype=jnp.bfloat16,
            accumulate_f32=accumulate_f32)["g"]

    return fn


@pytest.mark.parametrize("size", [8 * 37 + 3, 5, 1])  # ragged, < n, scalar-ish
def test_f32_accum_single_rounding(devices, size):
    """f32-accumulate mode: error vs the exact f32 mean is ONE bf16
    rounding of the mean, independent of replica count — strictly tighter
    than the pure-bf16 ('wire') reduction on the same data."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    mesh = create_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(1)
    # Per-replica values with wildly different magnitudes so narrow-dtype
    # partial sums actually lose bits.
    x = (rng.standard_normal((8, size)) * np.logspace(-3, 3, 8)[:, None]
         ).astype(np.float32)
    exact = x.mean(axis=0)

    got_f32 = np.asarray(_shard_map_allreduce(mesh, True)(jnp.asarray(x)))
    got_wire = np.asarray(_shard_map_allreduce(mesh, False)(jnp.asarray(x)))
    # Every replica holds the same reduced value.
    np.testing.assert_array_equal(got_f32[0], got_f32[1])

    one_rounding = np.abs(
        exact.astype(np.float32) - exact.astype(jnp.bfloat16).astype(np.float32))
    err_f32 = np.abs(got_f32[0] - exact)
    err_wire = np.abs(got_wire[0] - exact)
    # f32-accumulate == quantize-the-mean-once (up to f32 division order).
    assert np.all(err_f32 <= one_rounding + 1e-6 * np.abs(exact) + 1e-12)
    # And it is no worse than the wire-accumulated reduction anywhere.
    assert err_f32.sum() <= err_wire.sum() + 1e-12


def _run(wire_dtype: str, steps: int = 5, accum: str = "float32"):
    cfg = load_config(base={
        "name": "compressed-ar",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": steps, "spmd_mode": "shard_map",
                  "grad_allreduce_dtype": wire_dtype,
                  "grad_allreduce_accum": accum},
    })
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    return jax.device_get(state.params), losses


def test_wire_dtype_rejected_under_jit(devices):
    import pytest

    from distributed_tensorflow_framework_tpu.core.config import load_config

    cfg = load_config(base={
        "name": "bad", "mesh": {"data": 8},
        "model": {"name": "lenet5", "dtype": "float32"},
        "train": {"spmd_mode": "jit", "grad_allreduce_dtype": "bfloat16"},
    })
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="explicit collective"):
        StepBuilder(cfg, mesh)


@pytest.mark.slow
@pytest.mark.parametrize("accum", ["wire", "float32"])
def test_bf16_wire_close_to_f32(devices, accum):
    p32, l32 = _run("")
    p16, l16 = _run("bfloat16", accum=accum)
    # Trajectories track closely (bf16 has ~3 decimal digits) and training
    # still makes progress.
    assert all(np.isfinite(l) for l in l16)
    assert l16[-1] < l16[0]
    for a, b in zip(jax.tree.leaves(p32), jax.tree.leaves(p16)):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=1e-3)
    # And it is genuinely different arithmetic (the compression happened).
    flat32 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p32)])
    flat16 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p16)])
    assert not np.array_equal(flat32, flat16)


def test_bad_accum_rejected(devices):
    cfg = load_config(base={
        "name": "bad", "mesh": {"data": 8},
        "model": {"name": "lenet5", "dtype": "float32"},
        "train": {"spmd_mode": "shard_map",
                  "grad_allreduce_accum": "f16"},
    })
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="grad_allreduce_accum"):
        StepBuilder(cfg, mesh)
