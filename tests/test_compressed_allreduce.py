"""Compressed (bf16-wire) gradient all-reduce — train.grad_allreduce_dtype."""

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _run(wire_dtype: str, steps: int = 5):
    cfg = load_config(base={
        "name": "compressed-ar",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": steps, "spmd_mode": "shard_map",
                  "grad_allreduce_dtype": wire_dtype},
    })
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    return jax.device_get(state.params), losses


def test_wire_dtype_rejected_under_jit(devices):
    import pytest

    from distributed_tensorflow_framework_tpu.core.config import load_config

    cfg = load_config(base={
        "name": "bad", "mesh": {"data": 8},
        "model": {"name": "lenet5", "dtype": "float32"},
        "train": {"spmd_mode": "jit", "grad_allreduce_dtype": "bfloat16"},
    })
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="explicit collective"):
        StepBuilder(cfg, mesh)


@pytest.mark.slow
def test_bf16_wire_close_to_f32(devices):
    p32, l32 = _run("")
    p16, l16 = _run("bfloat16")
    # Trajectories track closely (bf16 has ~3 decimal digits) and training
    # still makes progress.
    assert all(np.isfinite(l) for l in l16)
    assert l16[-1] < l16[0]
    for a, b in zip(jax.tree.leaves(p32), jax.tree.leaves(p16)):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=1e-3)
    # And it is genuinely different arithmetic (the compression happened).
    flat32 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p32)])
    flat16 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p16)])
    assert not np.array_equal(flat32, flat16)
