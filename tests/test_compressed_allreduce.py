"""Quantized collectives — parallel.collective_dtype (bf16 + int8 EF).

Covers the bf16-wire gradient all-reduce (the original
train.grad_allreduce_dtype feature, now a deprecated spelling of the
knob), the int8 block-scaled all-reduce with error feedback, the
linearized multi-axis routing order, and the tier-1 acceptance gate:
int8 wire bytes on the dp+fsdp recipe drop >= 3x vs the f32 wire
(docs/PERFORMANCE.md)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _shard_map_allreduce(mesh, accumulate_f32):
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    @functools.partial(
        coll.shard_map, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def fn(x):
        return coll.allreduce_gradients(
            {"g": x}, ("data",), compute_dtype=jnp.bfloat16,
            accumulate_f32=accumulate_f32)["g"]

    return fn


@pytest.mark.parametrize("size", [8 * 37 + 3, 5, 1])  # ragged, < n, scalar-ish
def test_f32_accum_single_rounding(devices, size):
    """f32-accumulate mode: error vs the exact f32 mean is ONE bf16
    rounding of the mean, independent of replica count — strictly tighter
    than the pure-bf16 ('wire') reduction on the same data."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    mesh = create_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(1)
    # Per-replica values with wildly different magnitudes so narrow-dtype
    # partial sums actually lose bits.
    x = (rng.standard_normal((8, size)) * np.logspace(-3, 3, 8)[:, None]
         ).astype(np.float32)
    exact = x.mean(axis=0)

    got_f32 = np.asarray(_shard_map_allreduce(mesh, True)(jnp.asarray(x)))
    got_wire = np.asarray(_shard_map_allreduce(mesh, False)(jnp.asarray(x)))
    # Every replica holds the same reduced value.
    np.testing.assert_array_equal(got_f32[0], got_f32[1])

    one_rounding = np.abs(
        exact.astype(np.float32) - exact.astype(jnp.bfloat16).astype(np.float32))
    err_f32 = np.abs(got_f32[0] - exact)
    err_wire = np.abs(got_wire[0] - exact)
    # f32-accumulate == quantize-the-mean-once (up to f32 division order).
    assert np.all(err_f32 <= one_rounding + 1e-6 * np.abs(exact) + 1e-12)
    # And it is no worse than the wire-accumulated reduction anywhere.
    assert err_f32.sum() <= err_wire.sum() + 1e-12


def _base_cfg(wire_dtype: str, steps: int, accum: str,
              parallel: dict | None, mesh_cfg: dict | None) -> dict:
    base = {
        "name": "compressed-ar",
        "mesh": mesh_cfg or {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": steps, "spmd_mode": "shard_map",
                  "grad_allreduce_accum": accum},
    }
    if wire_dtype:
        base["train"]["grad_allreduce_dtype"] = wire_dtype  # legacy knob
    if parallel is not None:
        base["parallel"] = parallel
    return base


def _build(wire_dtype: str, steps: int = 5, accum: str = "float32", *,
           parallel: dict | None = None, mesh_cfg: dict | None = None):
    cfg = load_config(base=_base_cfg(wire_dtype, steps, accum,
                                     parallel, mesh_cfg))
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    return builder, state, batch


def _run(wire_dtype: str, steps: int = 5, accum: str = "float32", *,
         parallel: dict | None = None, mesh_cfg: dict | None = None):
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    builder, state, batch = _build(wire_dtype, steps, accum,
                                   parallel=parallel, mesh_cfg=mesh_cfg)
    step = builder.make_train_step(batch)
    losses = []
    with coll.tally() as t:  # counters record at trace time (first call)
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(jax.device_get(metrics["loss"])))
    return jax.device_get(state.params), losses, t.summary()


def _tally_for(parallel: dict | None, mesh_cfg: dict | None,
               legacy_wire: str = "") -> dict:
    """Trace-time collective byte tally of one train step — no compile,
    no execution, so the tier-1 acceptance gate stays cheap."""
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    builder, state, batch = _build(legacy_wire, steps=1,
                                   parallel=parallel, mesh_cfg=mesh_cfg)
    step = builder.make_train_step(batch)
    with coll.tally() as t:
        step.lower(state, batch)
    return t.summary()


def test_wire_dtype_rejected_under_jit(devices):
    import pytest

    from distributed_tensorflow_framework_tpu.core.config import load_config

    cfg = load_config(base={
        "name": "bad", "mesh": {"data": 8},
        "model": {"name": "lenet5", "dtype": "float32"},
        "train": {"spmd_mode": "jit", "grad_allreduce_dtype": "bfloat16"},
    })
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="explicit collective"):
        StepBuilder(cfg, mesh)


@pytest.mark.slow
@pytest.mark.parametrize("accum", ["wire", "float32"])
def test_bf16_wire_close_to_f32(devices, accum):
    p32, l32, _ = _run("")
    p16, l16, _ = _run("bfloat16", accum=accum)
    # Trajectories track closely (bf16 has ~3 decimal digits) and training
    # still makes progress.
    assert all(np.isfinite(l) for l in l16)
    assert l16[-1] < l16[0]
    for a, b in zip(jax.tree.leaves(p32), jax.tree.leaves(p16)):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=1e-3)
    # And it is genuinely different arithmetic (the compression happened).
    flat32 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p32)])
    flat16 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(p16)])
    assert not np.array_equal(flat32, flat16)


def test_bad_accum_rejected(devices):
    cfg = load_config(base={
        "name": "bad", "mesh": {"data": 8},
        "model": {"name": "lenet5", "dtype": "float32"},
        "train": {"spmd_mode": "shard_map",
                  "grad_allreduce_accum": "f16"},
    })
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="grad_allreduce_accum"):
        StepBuilder(cfg, mesh)


# ----------------------------------------------- int8 + error feedback ----


def test_int8_single_step_error_bound(devices):
    """One int8 block-scaled all-reduce: per-element error vs the exact
    f32 mean is bounded by one block rounding on the scatter phase plus
    one on the gather phase — each at most blockmax/254 <= maxabs/254."""
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    mesh = create_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(2)
    x = (rng.standard_normal((8, 500)) * np.logspace(-2, 2, 8)[:, None]
         ).astype(np.float32)
    exact = x.mean(axis=0)

    @functools.partial(coll.shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"), check_vma=False)
    def fn(v):
        m, _ = coll.allreduce_gradients_ef({"g": v}, None, ("data",),
                                           block_size=64)
        return m["g"]

    got = np.asarray(fn(jnp.asarray(x)))
    np.testing.assert_array_equal(got[0], got[-1])  # replicas agree
    bound = 2 * np.abs(x).max() / 254 + 1e-6
    assert np.abs(got[0] - exact).max() <= bound


def test_linear_axis_index_matches_gather_order(devices):
    """linear_axis_index (first axis major) must match the row order of
    all_gather(tiled=False) over the same axis tuple — the EF all-reduce
    routes chunk ownership with one and reassembles with the other."""
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    mesh = create_mesh(MeshConfig(data=4, fsdp=2))

    @functools.partial(coll.shard_map, mesh=mesh, in_specs=(),
                       out_specs=P(), check_vma=False)
    def fn():
        idx = coll.linear_axis_index(("data", "fsdp"))
        return jax.lax.all_gather(idx, ("data", "fsdp"), tiled=False)

    np.testing.assert_array_equal(np.asarray(fn()), np.arange(8))


def test_int8_ef_dp_loss_parity(devices):
    """ACCEPTANCE (dp recipe): with error feedback on, the int8 loss
    curve tracks the f32 curve within tolerance, and the tally shows the
    wire actually narrowed."""
    _, l32, _ = _run("")
    p8, l8, s8 = _run("", parallel={"collective_dtype": "int8",
                                    "collective_block_size": 64})
    assert all(np.isfinite(l) for l in l8)
    assert l8[-1] < l8[0]
    np.testing.assert_allclose(l8, l32, rtol=0.02, atol=2e-3)
    # And the compression happened: int8 wire, f32 logical.
    assert s8["total_bytes"] * 3 <= s8["total_logical_bytes"]
    assert "allreduce_grads_q8_gather_bytes" in s8


@pytest.mark.slow
def test_int8_ef_fsdp_loss_parity(devices):
    """dp+fsdp recipe: the explicit-fsdp path (quantized param gather +
    combined-axis EF all-reduce + grad slice-back) tracks the same-mesh
    f32 explicit-fsdp trajectory."""
    mesh_cfg = {"data": 4, "fsdp": 2}
    _, l32, _ = _run("", steps=3, mesh_cfg=mesh_cfg)
    _, l8, _ = _run("", steps=3, mesh_cfg=mesh_cfg,
                    parallel={"collective_dtype": "int8",
                              "collective_block_size": 64})
    assert all(np.isfinite(l) for l in l8)
    np.testing.assert_allclose(l8, l32, rtol=0.02, atol=2e-3)


def test_int8_wire_bytes_drop_3x_dp_fsdp(devices):
    """ACCEPTANCE: on the dp+fsdp recipe the tallied wire bytes for the
    gradient all-reduce AND the fsdp param gather drop >= 3x vs the f32
    wire. Trace-time tally only — no compile, no steps."""
    mesh_cfg = {"data": 4, "fsdp": 2}
    f32 = _tally_for(None, mesh_cfg)
    q8 = _tally_for({"collective_dtype": "int8",
                     "collective_block_size": 64}, mesh_cfg)
    ratio = f32["total_bytes"] / q8["total_bytes"]
    assert ratio >= 3.0, (ratio, f32, q8)
    # Both halves of the story are on the wire: quantized grad exchange
    # and the quantized fsdp param gather.
    assert "allreduce_grads_q8_scatter_bytes" in q8
    assert "allreduce_grads_q8_gather_bytes" in q8
    assert q8["all_gather_bytes"] < f32["all_gather_bytes"]
    # The logical traffic is the same experiment on both sides, up to
    # the int8 path's block/chunk padding (zeros on the wire, counted at
    # their logical width).
    assert (abs(q8["total_logical_bytes"] - f32["total_logical_bytes"])
            <= 0.05 * f32["total_logical_bytes"])


def test_old_knob_routes_to_new_knob(devices):
    """train.grad_allreduce_dtype=bfloat16 (deprecated) and
    parallel.collective_dtype=bfloat16 must produce the identical
    collective traffic — the shim maps, it does not fork behavior."""
    old = _tally_for(None, {"data": 8}, legacy_wire="bfloat16")
    new = _tally_for({"collective_dtype": "bfloat16"}, {"data": 8})
    assert old == new
    assert old["total_bytes"] < old["total_logical_bytes"]
