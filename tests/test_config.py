"""Config system tests (SURVEY.md §2 row 11 replacement)."""

import pytest

from distributed_tensorflow_framework_tpu.core.config import (
    ExperimentConfig,
    load_config,
)


def test_default_config():
    cfg = load_config()
    assert isinstance(cfg, ExperimentConfig)
    assert cfg.model.name == "lenet5"
    assert cfg.mesh.data == -1


def test_yaml_and_overrides(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(
        """
name: lenet-mnist
model:
  name: lenet5
  num_classes: 10
data:
  name: mnist
  global_batch_size: 128
optimizer:
  name: sgd_momentum
  learning_rate: 0.01
train:
  total_steps: 500
"""
    )
    cfg = load_config(p, overrides=["train.total_steps=7", "optimizer.learning_rate=0.5", "mesh.data=4", "mesh.fsdp=2"])
    assert cfg.name == "lenet-mnist"
    assert cfg.train.total_steps == 7
    assert cfg.optimizer.learning_rate == 0.5
    assert cfg.mesh.data == 4 and cfg.mesh.fsdp == 2
    assert cfg.data.global_batch_size == 128


def test_override_scalar_coercion():
    # YAML-1.1 gap: "1e-3" (no dot) parses as a string — we coerce it.
    cfg = load_config(overrides=["optimizer.learning_rate=1e-3"])
    assert cfg.optimizer.learning_rate == 1e-3
    cfg = load_config(overrides=["optimizer.learning_rate=2.5E+2"])
    assert cfg.optimizer.learning_rate == 250.0
    # But float()-parseable *strings* must stay strings: a bare float()
    # would turn these into nan / inf. ("1_000" is already an int per
    # YAML 1.1 underscore syntax — that's the YAML parser, not coercion.)
    for raw in ("nan", "inf", "infinity", "1e", "e5"):
        cfg = load_config(overrides=[f"name={raw}"])
        assert cfg.name == raw, raw


def test_unknown_key_rejected(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("modell: {name: lenet5}\n")
    with pytest.raises(ValueError, match="Unknown key"):
        load_config(p)


def test_print_config_roundtrips(capsys):
    """--print-config dumps the resolved config as YAML and exits before
    any device/Trainer work; the dump must parse and carry overrides."""
    import yaml

    from distributed_tensorflow_framework_tpu.cli.train import main

    rc = main(["--config", "configs/bert_base_mlm.yaml",
               "--set", "mesh.data=4", "--set", "train.total_steps=7",
               "--print-config"])
    assert rc == 0
    dumped = yaml.safe_load(capsys.readouterr().out)
    assert dumped["mesh"]["data"] == 4
    assert dumped["train"]["total_steps"] == 7
    # And the dump is itself a loadable config (round-trip property).
    cfg = load_config(base=dumped)
    assert cfg.train.total_steps == 7


def test_grad_allreduce_dtype_deprecation_shim(caplog):
    """train.grad_allreduce_dtype predates parallel.collective_dtype; the
    old spelling must keep working (mapped with a warning), agree-both
    must pass silently, and a conflict must be a hard error — a silent
    precedence pick would change which wire format a run uses."""
    import logging

    with caplog.at_level(logging.WARNING):
        cfg = load_config(overrides=["train.grad_allreduce_dtype=bfloat16"])
    assert cfg.parallel.collective_dtype == "bfloat16"
    assert "deprecated" in caplog.text

    # Both knobs set to the SAME value: fine (explicit, unambiguous).
    cfg = load_config(overrides=["train.grad_allreduce_dtype=int8",
                                 "parallel.collective_dtype=int8"])
    assert cfg.parallel.collective_dtype == "int8"

    with pytest.raises(ValueError, match="conflicts"):
        load_config(overrides=["train.grad_allreduce_dtype=bfloat16",
                               "parallel.collective_dtype=int8"])


def test_collective_dtype_validated():
    with pytest.raises(ValueError, match="collective_dtype"):
        load_config(overrides=["parallel.collective_dtype=fp8"])
    with pytest.raises(ValueError, match="collective_block_size"):
        load_config(overrides=["parallel.collective_block_size=0"])


def test_fleet_autoscale_and_tenant_knobs_validated():
    with pytest.raises(ValueError, match="fleet_min_replicas"):
        load_config(overrides=["serve.fleet_min_replicas=0"])
    with pytest.raises(ValueError, match="fleet_max_replicas"):
        load_config(overrides=["serve.fleet_min_replicas=4",
                               "serve.fleet_max_replicas=2"])
    # The hysteresis band must be a band: 0 < down < up.
    with pytest.raises(ValueError, match="hysteresis"):
        load_config(overrides=["serve.fleet_scale_down_threshold=0.9"])
    with pytest.raises(ValueError, match="cooldown"):
        load_config(overrides=["serve.fleet_scale_cooldown_s=-1"])
    # A reserve so large the lowest class can never claim is a footgun.
    with pytest.raises(ValueError, match="tenant_priority_reserve"):
        load_config(overrides=["serve.queue_capacity=4",
                               "serve.tenant_priority_reserve=2"])
    with pytest.raises(ValueError, match="tenant_quota_rps"):
        load_config(overrides=["serve.tenant_quota_rps=-1"])
    with pytest.raises(ValueError, match="tenant_quota_burst"):
        load_config(overrides=["serve.tenant_quota_burst=-1"])
    cfg = load_config(overrides=["serve.fleet_autoscale=true",
                                 "serve.fleet_max_replicas=4",
                                 "serve.tenant_quota_rps=2.5"])
    assert cfg.serve.fleet_autoscale is True
    assert cfg.serve.fleet_max_replicas == 4
    assert cfg.serve.tenant_quota_rps == 2.5
