"""Exactly-once data acceptance drills (ISSUE 19, docs/RESILIENCE.md).

Two tiers:

* **Tier-1 multiset drill** (in-process, fast): a block-sharded gang of N
  hosts trains to a mid-epoch checkpoint, "crashes", and resumes on M
  hosts from the chief's snapshot. The multiset of consumed samples over
  the whole interrupted run must equal an uninterrupted single-host
  control's — no sample twice, none dropped, INCLUDING across the N→M
  refit (the property data/shard.py's block bounds guarantee).

* **Supervised drill** (subprocess, slow): a crash_at_step kill mid-run;
  the relaunch restores the committed checkpoint, whose manifest carries
  the data-state commit record, emits KIND_DATA_STATE, and the restart
  is classified in the stitched goodput/recovery rollup.
"""

import json
import math
import os
import shutil
import subprocess
import sys
from collections import Counter

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data import shard
from distributed_tensorflow_framework_tpu.data.mnist import make_mnist

N_TRAIN = 64
GLOBAL_B = 16


@pytest.fixture(scope="module")
def mnist_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mnist_drill"))
    rng = np.random.default_rng(23)
    np.savez(os.path.join(root, "mnist.npz"),
             x_train=rng.integers(0, 255, (N_TRAIN, 28, 28), dtype=np.uint8),
             y_train=rng.integers(0, 10, N_TRAIN).astype(np.int64),
             x_test=rng.integers(0, 255, (8, 28, 28), dtype=np.uint8),
             y_test=rng.integers(0, 10, 8).astype(np.int64))
    return root


def _gang(root, P):
    cfg = DataConfig(name="mnist", data_dir=root,
                     global_batch_size=GLOBAL_B, seed=5, shard_mode="block")
    return [make_mnist(cfg, h, P) for h in range(P)]


def _consume(gang, k) -> Counter:
    """Pull k global batches from every member; multiset of sample rows."""
    rows = Counter()
    for ds in gang:
        for _ in range(k):
            batch = next(ds)
            rows.update(batch["image"][j].tobytes()
                        for j in range(len(batch["image"])))
    return rows


def test_kill_midepoch_resume_on_refit_gang_is_exactly_once(mnist_dir):
    """2 hosts → kill mid-epoch → resume the checkpointed position on 4
    hosts: consumed multiset equals the uninterrupted 1-host control."""
    total = shard.epoch_batches(N_TRAIN, GLOBAL_B, 1) * 2  # two epochs
    control = _consume(_gang(mnist_dir, 1), total)

    gang = _gang(mnist_dir, 2)
    consumed = _consume(gang, 3)  # 3 global batches reach the "checkpoint"
    snap = gang[0].state()
    # Every member of a block gang holds the SAME host-count-invariant
    # position — any of them can serve as the chief's commit record.
    assert all(ds.state() == snap for ds in gang)
    record = shard.data_state_record(snap, process_count=2,
                                     repartition=gang[0].repartition)
    # Pulls past the snapshot die with the crash: intentionally dropped
    # here — the restore gate guarantees they are re-produced below.
    _consume(gang, 1)

    survivors = _gang(mnist_dir, 4)
    plan = shard.check_restore_data(record, snap, process_count=4)
    assert plan["action"] == "repartition"
    for ds in survivors:
        ds.restore(dict(snap))
    consumed += _consume(survivors, total - 3)

    assert consumed == control, (
        "consumed-sample multiset diverged from the uninterrupted control "
        "across the kill + 2->4 refit")


def test_same_count_resume_is_exactly_once(mnist_dir):
    """The no-refit case: kill and resume at the same host count."""
    total = shard.epoch_batches(N_TRAIN, GLOBAL_B, 1)
    control = _consume(_gang(mnist_dir, 1), total)

    gang = _gang(mnist_dir, 2)
    consumed = _consume(gang, 2)
    snap = gang[0].state()
    relaunch = _gang(mnist_dir, 2)
    for ds in relaunch:
        ds.restore(dict(snap))
    consumed += _consume(relaunch, total - 2)
    assert consumed == control


def _child_env(env_extra: dict) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra)
    return env


@pytest.mark.slow
@pytest.mark.slowest
def test_supervised_crash_resumes_data_state_exactly(tmp_path):
    """Kill at step 30 (after the step-20 save): the relaunch restores a
    checkpoint whose manifest commits the data state, replays the stream
    from it (KIND_DATA_STATE action=resume), and the restart shows up in
    the recovery/goodput rollup."""
    from distributed_tensorflow_framework_tpu.core import telemetry

    # Local on purpose: a module-level *_DRIVER constant would make the
    # slow-marker audit treat the (in-process, fast) multiset drills
    # above as subprocess drills too.
    DRILL_DRIVER = """
import sys
import jax; jax.config.update('jax_platforms','cpu')
from distributed_tensorflow_framework_tpu.cli.train import main
sys.exit(
 main(['--set','model.name=lenet5','--set','model.dtype=float32',
      '--set','data.name=synthetic_images','--set','data.image_size=28',
      '--set','data.channels=1','--set','data.global_batch_size=16',
      '--set','optimizer.name=sgd_momentum','--set','optimizer.learning_rate=0.01',
      '--set','train.total_steps=40','--set','train.log_interval=10',
      '--set','train.eval_steps=0',
      '--set','checkpoint.directory={ckpt}',
      '--set','checkpoint.save_interval_steps=20',
      '--set','checkpoint.async_save=false']))
"""
    ckpt_dir = str(tmp_path / "ckpt")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "scripts/train_resilient.py",
         "--max-attempts", "3", "--retry-sleep", "0.2", "--jitter", "0",
         "--", sys.executable, "-c", DRILL_DRIVER.format(ckpt=ckpt_dir)],
        cwd=repo_root, capture_output=True, text=True, timeout=900,
        env=_child_env({
            "DTF_FAULTS": "crash_at_step:30",
            "DTF_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        }))
    assert r.returncode == 0, r.stderr[-4000:]
    assert "exited rc=137" in r.stderr, r.stderr[-4000:]

    # The committed manifests carry the data-state record, digest and all.
    for step in (20, 40):
        mf = json.load(open(os.path.join(ckpt_dir, str(step),
                                         "manifest.json")))
        rec = mf[shard.DATA_RECORD_KEY]
        assert rec["schema"] == shard.DATA_STATE_SCHEMA
        assert rec["process_count"] == 1
        assert len(rec["sha256"]) == 64
        assert rec["position"]["consumed"] >= step

    events_path = os.path.join(ckpt_dir, "events.jsonl")
    restores = list(telemetry.read_events(
        events_path, kind=telemetry.KIND_DATA_STATE, strict=False))
    assert restores, "relaunch emitted no data_state restore event"
    plan = restores[-1]["extra"]["plan"]
    assert plan["action"] == "resume"
    assert plan["from_processes"] == 1 and plan["to_processes"] == 1

    # Every attempt announced its shard layout.
    shards = list(telemetry.read_events(
        events_path, kind=telemetry.KIND_DATA_SHARD, strict=False))
    assert len(shards) >= 2
    assert shards[-1]["extra"]["shard"]["shard_mode"] == "block"

    # The stitched rollup classifies the data restore as recovery
    # activity, next to the goodput ledger.
    summary = telemetry.format_run_summary(
        telemetry.summarize_events(events_path))
    assert "data state restored at step 20: resume" in summary, summary
    assert "data shard: host 0/1 reads 16 of 16 rows/batch (block mode)" \
        in summary, summary

    # The run finished at the horizon with a finite loss.
    final = [e for e in telemetry.read_events(
                 events_path, kind=telemetry.KIND_TRAIN_STEP, strict=False)
             if e.get("step") == 40]
    assert final and math.isfinite(final[-1]["metrics"]["loss"])

    # run_tier1.sh contract: archive the drill telemetry when asked.
    art = os.environ.get("DTF_DATA_DRILL_DIR")
    if art:
        os.makedirs(art, exist_ok=True)
        shutil.copy(events_path,
                    os.path.join(art, "DATA_DRILL_events.jsonl"))
