"""Per-host shard assignment + data-state records (data/shard.py).

Fast tier-1 mechanics for the exactly-once data layer (ISSUE 19,
docs/RESILIENCE.md "Exactly-once data"): block-sharding geometry (disjoint,
complete, host-count-invariant consumed prefix), the manifest commit
record and its restore-time gate (resume / repartition / typed refusal /
forced), the KIND_DATA_SHARD plan the Trainer emits, and the per-worker
``data_chaos`` fault specs. The end-to-end multiset drill lives in
tests/test_data_drill.py.
"""

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core import cluster, faults
from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data import shard
from distributed_tensorflow_framework_tpu.data.mnist import make_mnist
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset


@pytest.fixture(autouse=True)
def _no_fault_plan():
    yield
    faults.install(faults.FaultPlan())


# ------------------------------------------------------------ assignment

def test_assignment_from_env_defaults_to_single_process():
    a = shard.ShardAssignment.from_env({})
    assert (a.process_index, a.process_count) == (0, 1)


def test_assignment_from_env_reads_gang_discovery_vars():
    a = shard.ShardAssignment.from_env({
        cluster.ENV_NUM_PROCESSES: "4", cluster.ENV_PROCESS_ID: "2"})
    assert (a.process_index, a.process_count) == (2, 4)


def test_assignment_rejects_malformed_env_and_bad_index():
    with pytest.raises(shard.DataShardError):
        shard.ShardAssignment.from_env({cluster.ENV_NUM_PROCESSES: "four"})
    with pytest.raises(shard.DataShardError):
        shard.ShardAssignment(process_index=4, process_count=4)
    with pytest.raises(shard.DataShardError):
        shard.ShardAssignment(process_index=0, process_count=0)


def test_shard_plan_layout_and_validation():
    plan = shard.shard_plan(
        shard.ShardAssignment(process_index=1, process_count=4),
        global_batch=32, data_parallel=8, shard_mode="block")
    assert plan["host_batch"] == 8
    assert plan["process_index"] == 1 and plan["process_count"] == 4
    assert plan["shard_mode"] == "block"
    with pytest.raises(shard.DataShardError):
        shard.shard_plan(shard.ShardAssignment(0, 3), global_batch=32)
    with pytest.raises(shard.DataShardError):
        shard.shard_plan(shard.ShardAssignment(0, 4), global_batch=32,
                         data_parallel=6)


# -------------------------------------------------------- block geometry

def test_block_bounds_disjoint_and_complete():
    """Global batch i at P hosts: the per-host blocks tile [i*B, (i+1)*B)
    exactly — no overlap, no gap."""
    b, P = 4, 4
    B = b * P
    for i in range(3):
        rows = []
        for h in range(P):
            lo, hi = shard.block_bounds(i, b, h, P)
            assert hi - lo == b
            rows.extend(range(lo, hi))
        assert sorted(rows) == list(range(i * B, (i + 1) * B))


def test_block_consumed_prefix_is_host_count_invariant():
    """After k global batches the union of all hosts' rows is perm[:k*B]
    at ANY host count — the property an N→M refit resume relies on."""
    B, k = 16, 3

    def consumed(P):
        b = B // P
        rows = set()
        for i in range(k):
            for h in range(P):
                lo, hi = shard.block_bounds(i, b, h, P)
                rows.update(range(lo, hi))
        return rows

    assert consumed(1) == consumed(2) == consumed(4) == set(range(k * B))


def test_epoch_batches_identical_across_modes_and_hosts():
    # 100 examples, host batch 8, 2 hosts → 6 full global batches; every
    # host (and both shard modes) must agree on the cardinality.
    assert shard.epoch_batches(100, 8, 2) == 6
    assert shard.epoch_batches(100, 16, 1) == 6


@pytest.fixture(scope="module")
def mnist_dir(tmp_path_factory):
    import os

    root = str(tmp_path_factory.mktemp("mnist_shard"))
    rng = np.random.default_rng(7)
    np.savez(os.path.join(root, "mnist.npz"),
             x_train=rng.integers(0, 255, (64, 28, 28), dtype=np.uint8),
             y_train=rng.integers(0, 10, 64).astype(np.int64),
             x_test=rng.integers(0, 255, (16, 28, 28), dtype=np.uint8),
             y_test=rng.integers(0, 10, 16).astype(np.int64))
    return root


def _batches(ds, k):
    return [next(ds) for _ in range(k)]


def test_block_and_stride_identical_at_one_process(mnist_dir):
    """P=1 is the compatibility anchor: the default shard_mode flip must
    be bit-invisible to every existing single-process run."""
    def cfg(mode):
        return DataConfig(name="mnist", data_dir=mnist_dir,
                          global_batch_size=8, seed=3, shard_mode=mode)

    for a, b in zip(_batches(make_mnist(cfg("block"), 0, 1), 10),
                    _batches(make_mnist(cfg("stride"), 0, 1), 10)):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_block_mode_multiset_invariant_across_host_counts(mnist_dir):
    """k global batches at P=2 and P=4 consume the SAME sample multiset
    (and so does the P=1 control) — real reader, not just index math."""
    def consumed(P, k):
        cfg = DataConfig(name="mnist", data_dir=mnist_dir,
                         global_batch_size=16, seed=3, shard_mode="block")
        rows = []
        for h in range(P):
            for batch in _batches(make_mnist(cfg, h, P), k):
                rows.extend(batch["image"][j].tobytes()
                            for j in range(len(batch["image"])))
        return sorted(rows)

    assert consumed(1, 3) == consumed(2, 3) == consumed(4, 3)


def test_stride_mode_tagged_non_repartitionable(mnist_dir):
    cfg = DataConfig(name="mnist", data_dir=mnist_dir, global_batch_size=8,
                     shard_mode="stride")
    assert make_mnist(cfg, 0, 1).repartition == shard.REPARTITION_NONE
    cfg = DataConfig(name="mnist", data_dir=mnist_dir, global_batch_size=8)
    assert make_mnist(cfg, 0, 1).repartition == shard.REPARTITION_INVARIANT


# ------------------------------------------------------- commit records

def test_data_state_record_shape_and_digest():
    state = {"epoch": 1, "batch_in_epoch": 5, "consumed": 11}
    rec = shard.data_state_record(state, process_count=2,
                                  repartition=shard.REPARTITION_INVARIANT,
                                  watermark=3)
    assert rec["schema"] == shard.DATA_STATE_SCHEMA
    assert rec["sha256"] == shard.state_digest(state)
    assert rec["process_count"] == 2 and rec["watermark"] == 3
    assert rec["position"] == {"epoch": 1, "batch_in_epoch": 5,
                               "consumed": 11}
    # Digest is over canonical JSON: key order must not matter.
    assert shard.state_digest({"b": 1, "a": 2}) == \
        shard.state_digest({"a": 2, "b": 1})


def test_check_restore_same_count_resumes():
    state = {"epoch": 0, "consumed": 4}
    rec = shard.data_state_record(state, process_count=2, watermark=1)
    plan = shard.check_restore_data(rec, state, process_count=2)
    assert plan["action"] == "resume"
    assert plan["from_processes"] == 2 and plan["to_processes"] == 2
    assert plan["watermark"] == 1


def test_check_restore_legacy_record_is_none():
    assert shard.check_restore_data(None, {"consumed": 1},
                                    process_count=1) is None


def test_check_restore_digest_mismatch_raises_typed_error():
    state = {"consumed": 4}
    rec = shard.data_state_record(state, process_count=1)
    with pytest.raises(shard.DataShardError):
        shard.check_restore_data(rec, {"consumed": 5}, process_count=1)
    plan = shard.check_restore_data(rec, {"consumed": 5}, process_count=1,
                                    resume_strict=False)
    assert plan["action"] == "forced" and plan["reason"] == "digest_mismatch"


def test_check_restore_refit_repartitions_invariant_state():
    state = {"epoch": 2, "batch_in_epoch": 7, "consumed": 31}
    rec = shard.data_state_record(state, process_count=4,
                                  repartition=shard.REPARTITION_INVARIANT)
    plan = shard.check_restore_data(rec, state, process_count=2)
    assert plan["action"] == "repartition"
    assert plan["from_processes"] == 4 and plan["to_processes"] == 2


def test_check_restore_refit_refuses_non_repartitionable_state():
    state = {"batches": 9}
    rec = shard.data_state_record(state, process_count=4,
                                  repartition=shard.REPARTITION_NONE)
    with pytest.raises(shard.DataShardError) as ei:
        shard.check_restore_data(rec, state, process_count=2)
    assert "resume_strict" in str(ei.value)  # names the unblocking knob
    plan = shard.check_restore_data(rec, state, process_count=2,
                                    resume_strict=False)
    assert plan["action"] == "forced"
    assert plan["reason"] == "host_count_change"


def test_check_restore_unknown_schema_raises():
    with pytest.raises(shard.DataShardError):
        shard.check_restore_data({"schema": "dtf-data-state/99"},
                                 {}, process_count=1)


# ------------------------------------------------- data_chaos fault specs

def test_corrupt_shard_parse_and_worker_filter():
    plan = faults.FaultPlan.parse("corrupt_shard:1")
    f = plan.faults[0]
    assert (f.kind, f.worker, f.step) == ("corrupt_shard", 1, 1)
    # A different host's pull must NOT consume the one-shot fault...
    assert plan.fire("data_chaos", step=1, worker=0) == []
    # ...so the targeted host still gets it.
    assert [x.kind for x in plan.fire("data_chaos", step=1, worker=1)] == \
        ["corrupt_shard"]
    assert plan.fire("data_chaos", step=1, worker=1) == []  # once only

    plan = faults.FaultPlan.parse("corrupt_shard:0:3")
    assert plan.faults[0].step == 3
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("corrupt_shard:-1")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("corrupt_shard:0:0")


def test_skew_shard_parse():
    plan = faults.FaultPlan.parse("skew_shard:2:1.5s")
    f = plan.faults[0]
    assert (f.kind, f.worker, f.seconds, f.step) == ("skew_shard", 2, 1.5,
                                                     None)
    # step=None: fires at host 2's FIRST pull, whatever its ordinal.
    assert plan.fire("data_chaos", step=7, worker=0) == []
    assert [x.kind for x in plan.fire("data_chaos", step=7, worker=2)] == \
        ["skew_shard"]
    # 0 (or omitted) seconds = the stall-forever sentinel.
    assert faults.FaultPlan.parse("skew_shard:0:0").faults[0].seconds > 3600
    assert faults.FaultPlan.parse("skew_shard:1").faults[0].seconds > 3600
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("skew_shard:-1:5s")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("skew_shard:one:5s")


def test_corrupt_shard_poisons_only_float_fields_end_to_end():
    def make_iter(state):
        while True:
            yield {"image": np.ones((2, 4), np.float32),
                   "label": np.arange(2, dtype=np.int32)}

    ds = HostDataset(make_iter, element_spec={
        "image": ((2, 4), np.float32), "label": ((2,), np.int32)})
    faults.install("corrupt_shard:0:2")
    first = next(ds)
    assert np.isfinite(first["image"]).all()  # pull 1 untouched
    second = next(ds)
    assert np.isnan(second["image"]).all()
    np.testing.assert_array_equal(second["label"], np.arange(2))
    assert np.isfinite(next(ds)["image"]).all()  # once only


def test_trainer_shard_plan_event_reference():
    # KIND_DATA_SHARD rides the telemetry contract: the Trainer emits the
    # shard_plan dict under extra["shard"] at build time.
    from distributed_tensorflow_framework_tpu.core import telemetry

    assert telemetry.KIND_DATA_SHARD == "data_shard"
    ev = telemetry.make_event(telemetry.KIND_DATA_SHARD, run_id="t", step=0,
                              shard=shard.shard_plan(
                                  shard.ShardAssignment(0, 1),
                                  global_batch=8))
    assert ev["extra"]["shard"]["host_batch"] == 8
