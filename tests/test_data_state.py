"""state()/restore() round-trip for EVERY reader in data/ (ISSUE 19).

The exactly-once contract is only as strong as its weakest reader: for
each dataset the suite pulls a few batches, snapshots the iterator state,
keeps pulling (the expected continuation), then rebuilds a FRESH dataset,
restores the snapshot, and requires the continuation bit-for-bit. Plus
the skip-batch/rollback interaction (``batches_skipped`` recording,
replay-time discard, snapshot pruning) and the typed refusal a
non-repartitionable reader must raise at an N→M refit.
"""

import os
import pickle

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data import get_dataset, shard
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset

SEQ = 16


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    """One directory holding every on-disk dataset the suite needs."""
    import tensorflow as tf

    from tests.conftest import write_imagenet_records

    root = str(tmp_path_factory.mktemp("data_state"))
    rng = np.random.default_rng(11)

    np.savez(os.path.join(root, "mnist.npz"),
             x_train=rng.integers(0, 255, (64, 28, 28), dtype=np.uint8),
             y_train=rng.integers(0, 10, 64).astype(np.int64),
             x_test=rng.integers(0, 255, (16, 28, 28), dtype=np.uint8),
             y_test=rng.integers(0, 10, 16).astype(np.int64))

    cifar = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(cifar)
    for name, count in [(f"data_batch_{i}", 16) for i in range(1, 6)] + \
            [("test_batch", 16)]:
        with open(os.path.join(cifar, name), "wb") as fh:
            pickle.dump({
                b"data": rng.integers(0, 255, (count, 3072), dtype=np.uint8),
                b"labels": rng.integers(0, 10, count).tolist(),
            }, fh)

    mlm = os.path.join(root, "mlm")
    os.makedirs(mlm)
    for f in range(2):
        with tf.io.TFRecordWriter(
                os.path.join(mlm, f"mlm-{f:03d}.tfrecord")) as w:
            for _ in range(12):
                n = int(rng.integers(4, SEQ + 1))
                ids = np.zeros(SEQ, np.int64)
                ids[:n] = rng.integers(1000, 2000, n)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=ids)),
                }))
                w.write(ex.SerializeToString())

    write_imagenet_records(os.path.join(root, "imagenet"), counts=(8, 8),
                           size=(40, 32), label_fn=lambda n: (n % 10) + 1)
    return root


def _config(name: str, root: str) -> DataConfig:
    common = dict(global_batch_size=4, seed=13, shuffle_buffer=8)
    if name == "mnist_stride":
        return DataConfig(name="mnist", data_dir=root, shard_mode="stride",
                          **common)
    if name in ("text_mlm", "text_mlm_packed"):
        return DataConfig(name="text_mlm", data_dir=os.path.join(root, "mlm"),
                          seq_len=SEQ, vocab_size=2000,
                          pack_factor=2 if name.endswith("packed") else 1,
                          **common)
    if name == "imagenet":
        return DataConfig(name="imagenet",
                          data_dir=os.path.join(root, "imagenet"),
                          image_size=16, num_classes=10, **common)
    if name == "synthetic_mlm":
        return DataConfig(name="synthetic_mlm", seq_len=SEQ, **common)
    return DataConfig(name=name, data_dir=root, **common)


READERS = ["synthetic_images", "synthetic_mlm", "mnist", "mnist_stride",
           "cifar10", "imagenet", "text_mlm", "text_mlm_packed"]


def _assert_batches_equal(got, want, label):
    assert len(got) == len(want)
    for i, (g, w) in enumerate(zip(got, want)):
        assert set(g) == set(w)
        for k in w:
            np.testing.assert_array_equal(
                np.asarray(g[k]), np.asarray(w[k]),
                err_msg=f"{label}: batch {i} field {k!r} diverged")


@pytest.mark.parametrize("name", READERS)
def test_state_round_trip_resumes_bit_exact(name, data_root):
    cfg = _config(name, data_root)
    ds = get_dataset(cfg, process_index=0, process_count=1)
    for _ in range(3):
        next(ds)
    snap = ds.state()
    expected = [next(ds) for _ in range(4)]

    fresh = get_dataset(cfg, process_index=0, process_count=1)
    fresh.restore(snap)
    got = [next(fresh) for _ in range(4)]
    _assert_batches_equal(got, expected, name)
    # The resumed stream's position agrees with the original's.
    assert fresh.state() == ds.state()


def test_state_round_trip_mid_epoch_boundary(data_root):
    """Resume placed exactly at an epoch boundary (the reshuffle seam)."""
    cfg = _config("mnist", data_root)
    ds = get_dataset(cfg, process_index=0, process_count=1)
    epoch_len = ds.cardinality
    for _ in range(epoch_len):
        next(ds)
    snap = ds.state()
    # The rollover is lazy (the generator advances epoch on the NEXT
    # pull), so the seam snapshot reads (0, epoch_len) — what matters is
    # that a restore of it replays epoch 1 identically.
    assert snap["batch_in_epoch"] == epoch_len
    expected = [next(ds) for _ in range(3)]
    fresh = get_dataset(cfg, process_index=0, process_count=1)
    fresh.restore(snap)
    _assert_batches_equal([next(fresh) for _ in range(3)], expected,
                          "epoch boundary")


def test_packed_state_carries_token_census(data_root):
    from distributed_tensorflow_framework_tpu.data import packing

    cfg = _config("text_mlm_packed", data_root)
    ds = get_dataset(cfg, process_index=0, process_count=1)
    next(ds)
    st = ds.state()
    assert st[packing.REAL_TOKENS_KEY] > 0
    assert st[packing.PADDED_TOKENS_KEY] >= 0
    # Counters ride the snapshot: a restore resumes the census, it does
    # not reset it.
    fresh = get_dataset(cfg, process_index=0, process_count=1)
    fresh.restore(st)
    next(fresh)
    assert fresh.state()[packing.REAL_TOKENS_KEY] > st[packing.REAL_TOKENS_KEY]


@pytest.mark.parametrize("name", READERS)
def test_refit_capability_is_declared_and_enforced(name, data_root):
    """Every reader declares whether its state survives an N→M refit, and
    check_restore_data enforces the declaration with a typed error."""
    cfg = _config(name, data_root)
    ds = get_dataset(cfg, process_index=0, process_count=1)
    assert ds.repartition in (shard.REPARTITION_INVARIANT,
                              shard.REPARTITION_NONE)
    expected_invariant = name in ("synthetic_images", "synthetic_mlm",
                                  "mnist", "cifar10")
    assert (ds.repartition == shard.REPARTITION_INVARIANT) == \
        expected_invariant, name

    next(ds)
    state = ds.state()
    record = shard.data_state_record(state, process_count=2,
                                     repartition=ds.repartition)
    if expected_invariant:
        plan = shard.check_restore_data(record, state, process_count=1)
        assert plan["action"] == "repartition"
    else:
        with pytest.raises(shard.DataShardError):
            shard.check_restore_data(record, state, process_count=1)
        plan = shard.check_restore_data(record, state, process_count=1,
                                        resume_strict=False)
        assert plan["action"] == "forced"


# ------------------------------------------------ skip-batch round trip

def _counting_dataset():
    def make_iter(state):
        state.setdefault("n", 0)
        while True:
            state["n"] += 1
            yield {"x": np.full((2,), state["n"], np.int32)}

    return HostDataset(make_iter, element_spec={"x": ((2,), np.int32)})


def test_skip_records_survive_round_trip_and_discard_on_replay():
    ds = _counting_dataset()
    for _ in range(3):
        next(ds)
    ds.record_skipped([4, 5])
    snap = ds.state()
    assert snap["batches_skipped"] == [4, 5]

    fresh = _counting_dataset()
    fresh.restore(snap)
    # The replayed stream discards the skipped ordinals: next delivered
    # batch is the 6th produced one.
    batch = next(fresh)
    assert int(batch["x"][0]) == 6
    assert fresh.state()["consumed"] == 6
    # Passed skip entries are pruned from later snapshots — dead weight
    # must not accumulate in checkpoints.
    assert "batches_skipped" not in fresh.state()


def test_record_skipped_rebinds_not_mutates():
    """state() snapshots share nested lists; record_skipped must rebind so
    queued save snapshots keep their as-of-save contents."""
    ds = _counting_dataset()
    next(ds)
    ds.record_skipped([2])
    queued = ds.state()
    ds.record_skipped([3])
    assert queued["batches_skipped"] == [2]
    assert ds.state()["batches_skipped"] == [2, 3]


def test_skip_records_merge_sorted_union():
    ds = _counting_dataset()
    ds.record_skipped([5, 3])
    ds.record_skipped([4, 3])
    assert ds.state()["batches_skipped"] == [3, 4, 5]
