"""Autoregressive decode engine (serve/decode.py): paged KV cache,
continuous batching, int8 KV pages, eviction/resume, reload drain, the
KIND_DECODE_STEP / KIND_KV_CACHE telemetry rollups, and the fleet
router's X-DTF-Session affinity contract.

The slow end-to-end drill (server subprocesses + load_gen --mode decode,
continuous-vs-static throughput, HTTP logit parity, rolling reload with
live streams) lives in test_decode_drill.py; this file stays tier-1 by
driving the engine in-process on a tiny model.
"""

import json
import threading
import time

import jax
import numpy as np
import pytest
from test_train_models import tiny_bert_base

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.serve.decode import (
    CacheFullError,
    DecodeClosedError,
    DecodeEngine,
    DecodeError,
    PagePool,
    StreamTooLongError,
    page_table_buckets,
    pages_for,
)
from distributed_tensorflow_framework_tpu.serve.engine import (
    QueueFullError,
    pick_bucket,
    serving_mesh,
)
from distributed_tensorflow_framework_tpu.serve.export import (
    input_spec_for,
    load_artifact,
    save_artifact,
)

pytestmark = pytest.mark.serve

MAX_LEN = 32


# ------------------------------------------------- bucket arithmetic


def test_pick_bucket_exact_fit():
    # A value landing exactly on a bucket boundary takes THAT bucket,
    # not the next one up — off-by-one here doubles padding waste.
    assert pick_bucket(8, [4, 8, 16]) == 8
    assert pick_bucket(4, [4, 8, 16]) == 4
    assert pick_bucket(16, [4, 8, 16]) == 16
    assert pick_bucket(5, [4, 8, 16]) == 8


def test_pick_bucket_past_largest():
    with pytest.raises(ValueError, match="exceeds largest bucket"):
        pick_bucket(17, [4, 8, 16])


def test_pick_bucket_empty_ladder():
    # An empty ladder is a configuration error with its own message,
    # not an IndexError from buckets[-1].
    with pytest.raises(ValueError, match="empty bucket ladder"):
        pick_bucket(1, [])


def test_pages_for_boundaries():
    assert pages_for(1, 4) == 1
    assert pages_for(4, 4) == 1  # exact fill: no spare page
    assert pages_for(5, 4) == 2
    assert pages_for(0, 4) == 1  # a stream always owns >= 1 page


def test_page_table_buckets_pow2_capped():
    # Default ladder: powers of two capped at the max table size.
    assert page_table_buckets(32, 4, []) == [1, 2, 4, 8]
    # Explicit ladders are extended to reach the cap, never truncated
    # below it (a stream at max_len must have a bucket to land in).
    assert page_table_buckets(32, 4, [2, 3])[-1] == 8
    assert page_table_buckets(32, 4, [2, 3])[:2] == [2, 3]


# ------------------------------------------------------- page pool


def test_pagepool_all_or_nothing():
    pool = PagePool(8)  # page 0 reserved: 7 allocatable
    assert pool.capacity == 7
    got = pool.alloc(7)
    assert got is not None and len(got) == 7
    assert 0 not in got  # scratch page never leaves the pool
    assert pool.alloc(1) is None  # empty: all-or-nothing refusal
    pool.free(got[:3])
    assert pool.alloc(4) is None  # 3 free < 4 wanted: no partial grant
    assert len(pool.alloc(3)) == 3


def test_pagepool_race_for_last_block():
    """Exact-capacity race: many threads contend for the final page
    block; the all-or-nothing contract means exactly capacity pages are
    granted overall and no page is granted twice."""
    pool = PagePool(17)  # capacity 16
    grants: list[list[int]] = []
    lock = threading.Lock()
    start = threading.Event()

    def claim():
        start.wait()
        for _ in range(8):
            got = pool.alloc(2)
            if got is not None:
                with lock:
                    grants.append(got)

    threads = [threading.Thread(target=claim) for _ in range(8)]
    for t in threads:
        t.start()
    start.set()
    for t in threads:
        t.join()
    granted = [p for g in grants for p in g]
    assert len(granted) == 16  # every page granted exactly...
    assert len(set(granted)) == 16  # ...once
    assert pool.available() == 0
    pool.free(granted)
    assert pool.available() == 16


# ------------------------------------------------------ engine fixtures


@pytest.fixture(scope="module")
def decode_artifact_dir(tmp_path_factory):
    base = tiny_bert_base(max_seq_len=MAX_LEN)
    base["data"]["seq_len"] = MAX_LEN
    base["data"]["global_batch_size"] = 8
    cfg = load_config(base=base)
    mesh = serving_mesh(1)
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg.mesh.data = 1
    builder = StepBuilder(cfg, mesh)
    sample = {
        "input_ids": np.zeros((1, MAX_LEN), np.int32),
        "targets": np.full((1, MAX_LEN), -1, np.int32),
        "attention_mask": np.ones((1, MAX_LEN), np.int32),
    }
    state = builder.init_state(0, sample)
    out = tmp_path_factory.mktemp("decode_artifact") / "bert"
    save_artifact(
        str(out),
        model_config=cfg.model, task="mlm",
        params=jax.device_get(state.params),
        batch_stats=jax.device_get(state.batch_stats),
        step=0, input_spec=input_spec_for(cfg, "mlm"),
        vocab_size=cfg.data.vocab_size)
    return str(out)


@pytest.fixture(scope="module")
def decode_artifact(decode_artifact_dir):
    return load_artifact(decode_artifact_dir)


def _decode_cfg(**extra):
    base = {
        "model": {"name": "bert", "max_seq_len": MAX_LEN},
        "decode": {"enabled": True, "max_len": MAX_LEN, "page_size": 4,
                   "num_pages": 64, "max_streams": 4,
                   "max_new_tokens": 8},
    }
    for key, value in extra.items():
        base["decode"][key] = value
    cfg = load_config(base=base)
    cfg.serve.data = 1
    cfg.serve.report_interval_s = 60.0
    return cfg


@pytest.fixture(scope="module")
def decode_engine(decode_artifact):
    cfg = _decode_cfg()
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    yield eng
    eng.drain(30.0)


# --------------------------------------------------- decode behavior


def test_single_stream_greedy(decode_engine):
    out = decode_engine.generate([5, 6, 7], max_new_tokens=4, timeout=120)
    assert len(out["tokens"]) == 4
    assert out["finish"] == "length"
    assert out["admissions"] == 1
    assert out["ttft_ms"] is not None
    # Greedy decode over fixed weights is deterministic.
    again = decode_engine.generate([5, 6, 7], max_new_tokens=4, timeout=120)
    assert again["tokens"] == out["tokens"]


def test_stream_events_order(decode_engine):
    stream = decode_engine.submit([9, 10], max_new_tokens=3)
    seen = list(stream.events(timeout=120))
    kinds = [k for k, _ in seen]
    assert kinds == ["token", "token", "token", "done"]
    tokens = [p["token"] for k, p in seen if k == "token"]
    assert seen[-1][1]["tokens"] == tokens
    assert [p["index"] for k, p in seen if k == "token"] == [0, 1, 2]


def test_stream_interval_batches_delivery(decode_artifact):
    """decode.stream_interval buffers token delivery scheduler-side:
    the consumer sees every token, in order, with the same indices —
    only the queue-wakeup granularity changes. The first token still
    flushes immediately (TTFT), and finish() flushes the remainder."""
    cfg = _decode_cfg(stream_interval=4)
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    try:
        stream = eng.submit([9, 10], max_new_tokens=7)
        seen = list(stream.events(timeout=120))
        kinds = [k for k, _ in seen]
        assert kinds == ["token"] * 7 + ["done"]
        assert [p["index"] for k, p in seen if k == "token"] == \
            list(range(7))
        assert seen[-1][1]["tokens"] == \
            [p["token"] for k, p in seen if k == "token"]
        # Identical tokens to an unbatched-delivery engine: the knob
        # changes transport, never the decode itself.
        ref = eng.generate([9, 10], max_new_tokens=7, timeout=120)
        assert ref["tokens"] == seen[-1][1]["tokens"]
    finally:
        eng.drain(30.0)
    with pytest.raises(ValueError, match="stream_interval"):
        _decode_cfg(stream_interval=0)


def test_batched_logits_match_single(decode_artifact, decode_engine):
    """Continuous batching must be invisible to numerics: a stream
    decoded alongside neighbors yields bitwise-identical per-token
    logits to the same stream on a fresh, otherwise-idle engine."""
    prompt = [3, 1, 4, 1, 5]
    solo_stream = decode_engine.submit(
        prompt, max_new_tokens=4, return_logits=True)
    solo_events = list(solo_stream.events(timeout=120))
    solo_tokens = [p["token"] for k, p in solo_events if k == "token"]
    solo_logits = [p["logits"] for k, p in solo_events if k == "token"]

    streams = [
        decode_engine.submit(prompt, max_new_tokens=4, return_logits=True),
        decode_engine.submit([2, 7], max_new_tokens=6),
        decode_engine.submit(list(range(1, 12)), max_new_tokens=3),
    ]
    batched_logits = [
        p["logits"] for k, p in streams[0].events(timeout=120)
        if k == "token"]
    for s in streams[1:]:
        s.result(timeout=120)
    assert [int(np.argmax(lg)) for lg in batched_logits] == solo_tokens
    for got, ref in zip(batched_logits, solo_logits):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_compile_grid_bounded(decode_engine):
    """Every compiled executable's key must come from the fixed
    |prompt buckets| x |page buckets| x |row ladder| grid — arbitrary
    lengths must never mint new XLA programs."""
    stats = decode_engine.stats()
    rows = set(decode_engine.row_buckets)
    pages = set(decode_engine.page_buckets)
    prompts = set(decode_engine.prompt_buckets)
    import ast

    for key in stats["compiled_buckets"]:
        kind, a, b = ast.literal_eval(key)  # "('decode', rows, pages)"
        if kind == "prefill":
            assert a in prompts and b in pages
        else:
            assert kind == "decode"
            assert a in rows and b in pages
    assert len(stats["compiled_buckets"]) <= (
        len(prompts) * len(pages) + len(rows) * len(pages))


def test_submit_typed_errors(decode_engine):
    with pytest.raises(StreamTooLongError):
        decode_engine.submit(list(range(MAX_LEN)), max_new_tokens=8)
    with pytest.raises(DecodeError):
        decode_engine.submit([], max_new_tokens=2)


def test_cache_full_refuses_never_fitting(decode_artifact):
    # 5 pages * 4 slots, page 0 reserved -> 16 usable slots; a stream
    # needing more KV slots than the whole cache can NEVER be admitted:
    # typed backpressure at submit, not a deadlocked queue entry.
    cfg = _decode_cfg(num_pages=5, max_streams=2, max_new_tokens=4)
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    try:
        with pytest.raises(CacheFullError):
            eng.submit(list(range(1, 20)), max_new_tokens=4)
        # ...while a stream that fits exactly still completes.
        out = eng.generate(list(range(1, 14)), max_new_tokens=4,
                           timeout=120)
        assert len(out["tokens"]) == 4
    finally:
        eng.drain(30.0)


def test_queue_backpressure(decode_artifact):
    cfg = _decode_cfg()
    cfg.serve.queue_capacity = 2
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    try:
        streams = []
        with pytest.raises(QueueFullError):
            for _ in range(64):  # far past capacity + in-flight slots
                streams.append(eng.submit([1, 2], max_new_tokens=8))
        for s in streams:
            s.result(timeout=120)
    finally:
        eng.drain(30.0)


def test_eviction_resumes_bitwise(decode_artifact):
    """Under page pressure the newest stream is evicted and re-prefilled
    over prompt+generated: its final tokens must be IDENTICAL to an
    uncontended run — eviction is a scheduling event, not a numerics
    event."""
    cfg = _decode_cfg(num_pages=64, max_streams=2)
    ref_eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                           mesh=serving_mesh(1))
    long_prompt = list(range(1, 12))
    short_prompt = [7, 3]
    try:
        ref_long = ref_eng.generate(long_prompt, max_new_tokens=8,
                                    timeout=120)
        ref_short = ref_eng.generate(short_prompt, max_new_tokens=8,
                                     timeout=120)
    finally:
        ref_eng.drain(30.0)

    # 7 usable pages * 4 slots = 28 KV slots; both streams admitted
    # (13 + 4 initial pages-worth) but growth collides mid-decode.
    tight = _decode_cfg(num_pages=8, max_streams=2)
    eng = DecodeEngine(decode_artifact, tight.decode, tight.serve,
                       mesh=serving_mesh(1))
    try:
        s_long = eng.submit(long_prompt, max_new_tokens=8)
        s_short = eng.submit(short_prompt, max_new_tokens=8)
        out_long = s_long.result(timeout=120)
        out_short = s_short.result(timeout=120)
        assert out_long["tokens"] == ref_long["tokens"]
        assert out_short["tokens"] == ref_short["tokens"]
        stats = eng.stats()
        assert (stats["evictions"] >= 1
                or out_long["admissions"] + out_short["admissions"] >= 3)
    finally:
        eng.drain(30.0)


def test_int8_kv_close_to_f32(decode_artifact):
    cfg8 = _decode_cfg(kv_dtype="int8")
    cfg32 = _decode_cfg()
    eng8 = DecodeEngine(decode_artifact, cfg8.decode, cfg8.serve,
                        mesh=serving_mesh(1))
    eng32 = DecodeEngine(decode_artifact, cfg32.decode, cfg32.serve,
                         mesh=serving_mesh(1))
    try:
        prompt = [3, 1, 4, 1, 5, 9]
        lg8s = [p["logits"] for k, p in eng8.submit(
            prompt, max_new_tokens=3, return_logits=True
        ).events(timeout=120) if k == "token"]
        lg32s = [p["logits"] for k, p in eng32.submit(
            prompt, max_new_tokens=3, return_logits=True
        ).events(timeout=120) if k == "token"]
        assert eng8.stats()["kv_dtype"] == "int8"
        assert len(lg8s) == len(lg32s) == 3
        for lg8, lg32 in zip(lg8s, lg32s):
            diff = float(np.max(np.abs(
                np.asarray(lg8) - np.asarray(lg32))))
            # Block-codec int8 KV on an untrained tiny model: the bound
            # is loose in absolute terms but catches a broken codec
            # (garbage pages push logits O(1) apart).
            assert diff < 0.05, f"int8 KV drifted {diff} from f32"
    finally:
        eng8.drain(30.0)
        eng32.drain(30.0)


def test_reload_drains_then_swaps(decode_artifact, decode_artifact_dir):
    cfg = _decode_cfg()
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    try:
        stream = eng.submit([2, 4, 6], max_new_tokens=8)
        result = eng.reload(decode_artifact_dir, timeout=120.0)
        # The in-flight stream got every token (drain, never kill)...
        out = stream.result(timeout=120)
        assert len(out["tokens"]) == 8
        assert result["to_step"] == decode_artifact.step
        assert eng.stats()["reloads"] == 1
        # ...and the engine still serves after the swap.
        again = eng.generate([2, 4, 6], max_new_tokens=2, timeout=120)
        assert len(again["tokens"]) == 2
    finally:
        eng.drain(30.0)


def test_drain_then_closed(decode_artifact):
    cfg = _decode_cfg()
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1))
    assert eng.drain(30.0) is True
    with pytest.raises(DecodeClosedError):
        eng.submit([1], max_new_tokens=1)


# -------------------------------------------- telemetry kind rollups


def test_decode_telemetry_rollup(decode_artifact, tmp_path):
    """KIND_DECODE_STEP / KIND_KV_CACHE events from a real engine run
    roll up through summarize_events and format_run_summary."""
    writer = telemetry.TelemetryWriter(str(tmp_path / "events.jsonl"))
    cfg = _decode_cfg()
    eng = DecodeEngine(decode_artifact, cfg.decode, cfg.serve,
                       mesh=serving_mesh(1), telemetry_writer=writer)
    try:
        eng.generate([1, 2, 3], max_new_tokens=4, timeout=120)
    finally:
        eng.drain(30.0)
        writer.close()
    events = [json.loads(line)
              for line in open(tmp_path / "events.jsonl")]
    kinds = {e["kind"] for e in events}
    assert telemetry.KIND_DECODE_STEP in kinds
    assert telemetry.KIND_KV_CACHE in kinds
    summary = telemetry.summarize_events(str(tmp_path / "events.jsonl"))
    dec = summary["decode"]
    # 4 generated tokens = 1 from the prefill emit + 3 decode steps.
    assert dec["steps"] >= 3
    assert dec["tokens"] >= 3
    assert dec["kv_samples"] >= 1
    assert dec["pages_used_max"] >= 1
    text = telemetry.format_run_summary(summary)
    assert "decode:" in text
    assert "kv cache:" in text


def test_decode_step_rollup_math(tmp_path):
    path = tmp_path / "events.jsonl"
    writer = telemetry.TelemetryWriter(str(path))
    writer.emit(telemetry.KIND_DECODE_STEP,
                metrics={"rows": 3, "padded_rows": 4, "step_ms": 10.0,
                         "per_token_ms": 10 / 3, "occupancy": 0.75})
    writer.emit(telemetry.KIND_DECODE_STEP,
                metrics={"rows": 1, "padded_rows": 4, "step_ms": 6.0,
                         "per_token_ms": 6.0, "occupancy": 0.25})
    writer.emit(telemetry.KIND_KV_CACHE,
                metrics={"pages_used": 9, "pages_free": 54,
                         "streams_active": 3, "streams_waiting": 2,
                         "evictions": 1})
    writer.emit(telemetry.KIND_KV_CACHE,
                metrics={"pages_used": 4, "pages_free": 59,
                         "streams_active": 1, "streams_waiting": 0,
                         "evictions": 1})
    writer.close()
    dec = telemetry.summarize_events(str(path))["decode"]
    assert dec["steps"] == 2
    assert dec["tokens"] == 4
    assert dec["padded_rows"] == 8
    assert dec["step_ms_total"] == pytest.approx(16.0)
    assert dec["pages_used_max"] == 9
    assert dec["streams_waiting_max"] == 2
    assert dec["evictions"] == 1  # cumulative counter: max, not sum
    assert dec["kv_samples"] == 2


# ------------------------------------------------ HTTP + fleet routes


@pytest.fixture()
def decode_server(decode_artifact):
    from distributed_tensorflow_framework_tpu.serve.engine import (
        InferenceEngine,
    )
    from distributed_tensorflow_framework_tpu.serve.server import (
        ServingServer,
    )

    cfg = _decode_cfg()
    cfg.serve.port = 0
    cfg.serve.max_wait_ms = 2.0
    mesh = serving_mesh(1)
    eng = InferenceEngine(decode_artifact, cfg.serve, mesh=mesh)
    dec = DecodeEngine(decode_artifact, cfg.decode, cfg.serve, mesh=mesh)
    srv = ServingServer(eng, cfg.serve, decode_engine=dec)
    thread = threading.Thread(target=srv.httpd.serve_forever, daemon=True)
    thread.start()
    yield srv
    srv.shutdown("test")
    thread.join(timeout=10)


def _post_generate(host, port, body, headers=None, timeout=120):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps(body).encode(),
                     {"Content-Type": "application/json", **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        lines = [json.loads(line) for line in raw.splitlines()
                 if line.strip()]
        return resp.status, dict(resp.headers), lines
    finally:
        conn.close()


def test_http_generate_streams_ndjson(decode_server):
    status, headers, lines = _post_generate(
        decode_server.host, decode_server.port,
        {"prompt": [5, 6, 7], "max_new_tokens": 3})
    assert status == 200
    assert headers.get("Content-Type") == "application/x-ndjson"
    assert headers.get("Transfer-Encoding") == "chunked"
    tokens = [ln["token"] for ln in lines if "token" in ln]
    assert len(tokens) == 3
    assert lines[-1]["done"] is True
    assert lines[-1]["tokens"] == tokens
    # In-process reference: HTTP adds no numerics of its own.
    ref = decode_server.decode_engine.generate(
        [5, 6, 7], max_new_tokens=3, timeout=120)
    assert ref["tokens"] == tokens


def test_http_generate_error_mapping(decode_server):
    status, _, lines = _post_generate(
        decode_server.host, decode_server.port,
        {"prompt": list(range(MAX_LEN + 8))})
    assert status == 400  # too long: can never be admitted
    status, _, _ = _post_generate(
        decode_server.host, decode_server.port, {"prompt": []})
    assert status == 400
    # healthz grows the decode section when the engine is attached.
    import urllib.request

    with urllib.request.urlopen(
            f"http://{decode_server.host}:{decode_server.port}/healthz",
            timeout=30) as resp:
        health = json.load(resp)
    assert health["decode"]["kv_dtype"] == "float32"
    assert health["decode"]["pages"]["total"] == 64


def test_fleet_session_affinity_409(decode_server):
    """X-DTF-Session pins a session to one replica; while that replica
    drains for a rolling reload the router answers 409 + Retry-After
    (the KV pages are worth waiting for), and repins only once the
    replica is genuinely gone."""
    from distributed_tensorflow_framework_tpu.serve.fleet import (
        SESSION_HEADER,
        FleetRouter,
    )

    cfg = _decode_cfg()
    cfg.serve.port = 0
    router = FleetRouter(cfg.serve)
    rep = router.add_replica(
        url=f"http://{decode_server.host}:{decode_server.port}",
        admitted=True)
    thread = threading.Thread(target=router.httpd.serve_forever,
                              daemon=True)
    thread.start()
    try:
        body = {"prompt": [5, 6], "max_new_tokens": 2}
        status, headers, lines = _post_generate(
            router.host, router.port, body,
            headers={SESSION_HEADER: "sess-a"})
        assert status == 200
        assert headers.get("X-DTF-Replica") == "r0"
        assert lines[-1]["done"] is True
        assert router._sessions == {"sess-a": 0}

        rep.state = "draining"  # what rolling_reload sets mid-roll
        status, headers, lines = _post_generate(
            router.host, router.port, body,
            headers={SESSION_HEADER: "sess-a"})
        assert status == 409
        assert float(headers.get("Retry-After")) > 0
        assert lines[0]["retryable"] is True

        rep.state = "admitted"  # reload done: same session lands again
        status, _, _ = _post_generate(
            router.host, router.port, body,
            headers={SESSION_HEADER: "sess-a"})
        assert status == 200

        rep.state = "dead"  # replica gone for good: the pin is dropped
        status, _, _ = _post_generate(
            router.host, router.port, body,
            headers={SESSION_HEADER: "sess-a"})
        assert status == 503  # nothing routable in this 1-replica fleet
        assert "sess-a" not in router._sessions
    finally:
        router.httpd.shutdown()
        thread.join(timeout=10)
