"""End-to-end decode acceptance drill (tier-2).

Three claims, each against REAL ``cli/serve.py`` subprocesses on the
8-device CPU mesh:

  * continuous batching beats the static batch-synchronous arm by >= 2x
    tokens/s on a mixed-length workload (mostly-short streams + one
    long per batch — the static arm idles finished slots while the long
    stream runs out);
  * per-token logits served from inside a busy continuous batch are
    BITWISE equal to the same prompt decoded solo (f32 KV; JSON float
    repr round-trips f32 exactly, so equality holds over HTTP too);
  * a fleet rolling reload under live decode streams loses ZERO streams
    — 409 + Retry-After retries on the session-affinity miss are part
    of the client protocol, failed streams are not.
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest
from test_train_models import tiny_bert_base

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.serve.engine import serving_mesh
from distributed_tensorflow_framework_tpu.serve.export import (
    input_spec_for,
    save_artifact,
)

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.slow, pytest.mark.serve]

MAX_LEN = 64


def _load_gen():
    spec = importlib.util.spec_from_file_location(
        "load_gen_drill", str(REPO / "scripts" / "load_gen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bert_artifact_dir(tmp_path_factory):
    # Wider than the tiny unit-test model on purpose: with hidden 128 /
    # vocab 8192 a decode step's device time dwarfs the per-token Python
    # bookkeeping (frame writes, client parsing), so the A/B measures
    # batch scheduling rather than interpreter overhead.
    base = tiny_bert_base(max_seq_len=MAX_LEN, hidden_size=128,
                          num_layers=4, vocab_size=8192, mlp_dim=256)
    base["data"]["seq_len"] = MAX_LEN
    base["data"]["global_batch_size"] = 8
    base["data"]["vocab_size"] = 8192
    cfg = load_config(base=base)
    mesh = serving_mesh(1)
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg.mesh.data = 1
    builder = StepBuilder(cfg, mesh)
    sample = {
        "input_ids": np.zeros((1, MAX_LEN), np.int32),
        "targets": np.full((1, MAX_LEN), -1, np.int32),
        "attention_mask": np.ones((1, MAX_LEN), np.int32),
    }
    state = builder.init_state(0, sample)
    out = tmp_path_factory.mktemp("decode_drill") / "bert"
    save_artifact(
        str(out),
        model_config=cfg.model, task="mlm",
        params=jax.device_get(state.params),
        batch_stats=jax.device_get(state.batch_stats),
        step=0, input_spec=input_spec_for(cfg, "mlm"),
        vocab_size=cfg.data.vocab_size)
    return str(out)


def _spawn_server(artifact_dir: str, log_dir: str, *,
                  scheduler: str = "continuous",
                  extra: list[str] | None = None) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    args = [
        sys.executable, "-m",
        "distributed_tensorflow_framework_tpu.cli.serve",
        "--artifact", artifact_dir,
        "--set", "serve.port=0",
        "--set", "serve.data=8",
        "--set", f"serve.log_dir={log_dir}",
        "--set", "serve.max_wait_ms=2",
        "--set", "serve.report_interval_s=60",
        "--set", "decode.enabled=true",
        "--set", f"decode.max_len={MAX_LEN}",
        "--set", "decode.page_size=4",
        "--set", "decode.num_pages=192",
        "--set", "decode.max_streams=8",
        "--set", "decode.max_new_tokens=56",
        # Small prefill bucket: the A/B prompts are short, and padding
        # every prefill to the 64-token bucket would make BOTH arms
        # prefill-bound, hiding the decode-scheduling difference the
        # drill exists to measure.
        "--set", "decode.prompt_buckets=[8,64]",
        # Batch token delivery: on a 1-core box per-token handler
        # wakeups steal enough scheduler CPU to dilute BOTH arms
        # equally, compressing the very ratio under test.
        "--set", "decode.stream_interval=8",
        "--set", f"decode.scheduler={scheduler}",
    ] + (extra or [])
    return subprocess.Popen(args, cwd=str(REPO), env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait_for_endpoint(path, proc, timeout=240.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server exited rc={proc.returncode} before serving:\n"
                f"{proc.stdout.read()}")
        if os.path.isfile(path):
            with open(path) as fh:
                return json.load(fh)
        time.sleep(0.5)
    raise AssertionError(f"no endpoint.json at {path} after {timeout}s")


def _stop(proc):
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


def _healthz(url):
    with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
        return json.load(resp)


def test_continuous_vs_static_and_parity(bert_artifact_dir, tmp_path,
                                         devices):
    lg = _load_gen()
    benches = {}
    urls = {}
    procs = {}
    try:
        for arm in ("continuous", "static"):
            procs[arm] = _spawn_server(
                bert_artifact_dir, str(tmp_path / arm), scheduler=arm)
        for arm, proc in procs.items():
            endpoint = _wait_for_endpoint(
                str(tmp_path / arm / "endpoint.json"), proc)
            urls[arm] = endpoint["url"]
            # Warm the compile grid outside the timed window so the A/B
            # measures scheduling, not XLA compile order. A 3-token and
            # a 7-token prompt between them cover both prefill page
            # widths the bench prompts hit (1 and 2 pages); the full-
            # budget stream walks the decode page ladder to its top.
            warm = [lg.stream_generate(urls[arm], [1 + i, 2, 3],
                                       max_new=56, session=f"warm-{i}")
                    for i in range(3)]
            warm.append(lg.stream_generate(
                urls[arm], [1, 2, 3, 4, 5, 6, 7], max_new=2,
                session="warm-2page"))
            assert all(w["status"] == 200 for w in warm), warm

        # The throughput A/B on a shared 1-core box: warmup compile
        # bursts and noisy neighbours skew whichever bench runs while
        # the CPU budget is depleted, so settle before measuring and
        # allow a bounded re-measure of BOTH arms in the same window.
        ratio = 0.0
        for attempt in range(3):
            time.sleep(5.0)  # let warmup / previous attempt's load fade
            for arm in ("continuous", "static"):
                out = tmp_path / f"BENCH_{arm}.json"
                gen = subprocess.run(
                    [sys.executable,
                     str(REPO / "scripts" / "load_gen.py"),
                     "--endpoint", urls[arm], "--mode", "decode",
                     "--requests", "48", "--concurrency", "8",
                     "--max-new-tokens", "56", "--out", str(out)],
                    cwd=str(REPO),
                    env=dict(os.environ, JAX_PLATFORMS="cpu"),
                    capture_output=True, text=True, timeout=900)
                assert gen.returncode == 0, gen.stdout + gen.stderr
                benches[arm] = json.loads(out.read_text())
                # Archive contract (scripts/run_tier1.sh): slow runs
                # keep the A/B bench JSON next to the other artifacts.
                bench_dir = os.environ.get("DTF_DECODE_BENCH_DIR")
                if bench_dir:
                    dest = pathlib.Path(bench_dir)
                    dest.mkdir(parents=True, exist_ok=True)
                    (dest / f"DECODE_BENCH_{arm}.json").write_text(
                        out.read_text())

            for arm, bench in benches.items():
                run = bench["runs"][0]
                assert run["mode"] == "decode"
                assert run["ok"] == 48, (arm, run["by_status"])
                assert run["tokens_per_sec"] > 0
                assert run["ttft_ms"]["p99"] >= run["ttft_ms"]["p50"] > 0
                assert run["tpot_ms"]["count"] > 0
                assert bench["decode_delta"]["scheduler"] == arm

            cont = benches["continuous"]["runs"][0]["tokens_per_sec"]
            stat = benches["static"]["runs"][0]["tokens_per_sec"]
            ratio = max(ratio, cont / stat)
            if ratio >= 2.0:
                break
        assert ratio >= 2.0, (
            f"continuous batching {cont:.1f} tok/s vs static {stat:.1f} "
            f"tok/s — expected >= 2x (best ratio {ratio:.2f} over "
            f"{attempt + 1} attempts)")

        # Recompiles stay on the fixed bucket grid even after the full
        # mixed-length workload.
        health = _healthz(urls["continuous"])
        dec = health["decode"]
        grid = (len(dec["prompt_buckets"]) * len(dec["page_buckets"])
                + len(dec["row_buckets"]) * len(dec["page_buckets"]))
        assert 0 < len(dec["compiled_buckets"]) <= grid, dec

        # Logit parity over HTTP: one return_logits stream inside a busy
        # batch vs the same prompt decoded solo afterwards. f32 KV ->
        # bitwise equality (JSON shortest-repr round-trips f32 exactly).
        url = urls["continuous"]
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]

        def _generate_logits():
            body = json.dumps({"prompt": prompt, "max_new_tokens": 8,
                               "return_logits": True}).encode()
            req = urllib.request.Request(
                url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                lines = [json.loads(line) for line in resp
                         if line.strip()]
            return ([ln["token"] for ln in lines if "token" in ln],
                    [ln["logits"] for ln in lines if "token" in ln])

        noise = [threading.Thread(
            target=lg.stream_generate, args=(url, [7 + i, 8, 9]),
            kwargs={"max_new": 24, "session": f"noise-{i}"}, daemon=True)
            for i in range(6)]
        for t in noise:
            t.start()
        busy_tokens, busy_logits = _generate_logits()
        for t in noise:
            t.join(timeout=300)
        solo_tokens, solo_logits = _generate_logits()

        assert busy_tokens == solo_tokens
        for got, ref in zip(busy_logits, solo_logits):
            assert got == ref  # exact float lists: bitwise, not approx
    finally:
        for proc in procs.values():
            _stop(proc)


def test_fleet_rolling_reload_zero_failed_streams(bert_artifact_dir,
                                                  tmp_path, devices):
    """Two decode replicas behind an in-process FleetRouter; a rolling
    reload fires while 16 session-pinned streams are in flight. Every
    stream must complete with its full token count — 409 retries are
    allowed, failures are not."""
    from distributed_tensorflow_framework_tpu.serve.fleet import (
        FleetRouter,
    )

    lg = _load_gen()
    cfg = load_config(base={"serve": {"port": 0}})
    procs = []
    try:
        for i in range(2):
            procs.append(_spawn_server(
                bert_artifact_dir, str(tmp_path / f"rep{i}")))
        urls = []
        for i, proc in enumerate(procs):
            endpoint = _wait_for_endpoint(
                str(tmp_path / f"rep{i}" / "endpoint.json"), proc)
            urls.append(endpoint["url"])

        router = FleetRouter(cfg.serve)
        for u in urls:
            router.add_replica(url=u, admitted=True)
        router.start()  # prober refreshes last_health for the reloader
        assert router.wait_ready(timeout=120)
        rthread = threading.Thread(target=router.httpd.serve_forever,
                                   daemon=True)
        rthread.start()
        rurl = f"http://{router.host}:{router.port}"

        results: list[dict] = []
        lock = threading.Lock()

        def one_stream(i):
            out = lg.stream_generate(rurl, [1 + i, 2, 3], max_new=16,
                                     session=f"roll-{i}", timeout=300)
            with lock:
                results.append(out)

        threads = [threading.Thread(target=one_stream, args=(i,),
                                    daemon=True) for i in range(16)]
        for t in threads[:8]:
            t.start()
        time.sleep(0.5)  # streams in flight before the roll begins
        roll_results, ok = router.rolling_reload(bert_artifact_dir)
        assert ok, roll_results
        for t in threads[8:]:  # more arrive while replicas readmit
            t.start()
        for t in threads:
            t.join(timeout=600)

        assert len(results) == 16
        failed = [r for r in results if r["status"] != 200
                  or r["tokens"] != 16]
        assert not failed, failed
        retried = sum(r["retried_409"] for r in results)
        # The roll drained both replicas in turn; retries are expected
        # but must never surface as failures.
        assert all(r["status"] == 200 for r in results), (retried, results)

        # The router bumps its counter in the handler's finally, which
        # can land a beat after the client reads the final chunk.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            health = _healthz(rurl)
            if health["fleet"]["router"]["generate_streams"] >= 16:
                break
            time.sleep(0.2)
        assert health["fleet"]["router"]["generate_streams"] >= 16
        router.shutdown("drill done")
        rthread.join(timeout=30)
    finally:
        for proc in procs:
            _stop(proc)
