"""Elastic reshard acceptance drill (ISSUE 6 tentpole, docs/RESILIENCE.md).

Losing a SLICE, not just a process: a supervised ``{data:8}`` run is
killed mid-training, and on relaunch a ``drop_devices`` drill masks the
child's visible device set to 4 — the CPU stand-in for a slice going
away. The child cannot build its mesh, exits ELASTIC_RESHARD_RC (84),
and the supervisor refits the mesh to ``{data:4}``, rescales the batch
to preserve the effective batch (64×1@dp8 → 32×2@dp4), and relaunches
with ``checkpoint.allow_reshard=true`` — all without consuming a retry
attempt or feeding the crash-loop breaker. The relaunched child restores
the step-20 checkpoint across the mesh change and finishes.

The fast reshard mechanics (fit_axis_sizes, rescale_for_devices,
cross-mesh bit-exact parity) live in tests/test_reshard.py; this module
is the end-to-end drill and is tier-2 by its slow marks.
"""

import json
import math
import os
import subprocess
import sys

import pytest

ELASTIC_DRIVER = """
import sys
import jax; jax.config.update('jax_platforms','cpu')
from distributed_tensorflow_framework_tpu.cli.train import main
sys.exit(
 main(['--set','model.name=lenet5','--set','model.dtype=float32',
      '--set','data.name=synthetic_images','--set','data.image_size=28',
      '--set','data.channels=1','--set','data.global_batch_size=64',
      '--set','mesh.data=8',
      '--set','optimizer.name=sgd_momentum','--set','optimizer.learning_rate=0.01',
      '--set','train.total_steps={steps}','--set','train.log_interval=20',
      '--set','train.eval_steps=0',
      '--set','checkpoint.directory={ckpt}',
      '--set','checkpoint.save_interval_steps=20',
      '--set','checkpoint.async_save=false']))
"""


def _child_env(env_extra: dict) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra)
    return env


def _events(path: str, kind: str) -> list[dict]:
    from distributed_tensorflow_framework_tpu.core import telemetry

    return list(telemetry.read_events(path, kind=kind, strict=False))


@pytest.mark.slow
@pytest.mark.slowest
def test_supervised_slice_loss_reshards_and_resumes(tmp_path):
    """Kill at step 30, drop 8→4 devices on the relaunch: the run must
    finish via one rc-84 elastic reshard, restore the step-20 checkpoint
    onto the {data:4} mesh, and preserve the effective batch."""
    from distributed_tensorflow_framework_tpu.core import telemetry

    ckpt_dir = str(tmp_path / "ckpt")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "scripts/train_resilient.py",
           "--max-attempts", "3", "--retry-sleep", "0.2", "--jitter", "0",
           "--", sys.executable, "-c",
           ELASTIC_DRIVER.format(ckpt=ckpt_dir, steps=60)]
    r = subprocess.run(
        cmd, cwd=repo_root, capture_output=True, text=True, timeout=900,
        env=_child_env({
            # crash_at_step kills attempt 1 at step 30 (after the step-20
            # save); drop_devices:4:2 fires at the SECOND relaunch point
            # and masks the child to 4 devices. The state file makes both
            # one-shot, so the post-reshard child runs clean.
            "DTF_FAULTS": "crash_at_step:30,drop_devices:4:2",
            "DTF_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        }))

    assert r.returncode == 0, r.stderr[-4000:]
    # Attempt 1 died to the injected SIGKILL (a real failure, budgeted)...
    assert "exited rc=137" in r.stderr, r.stderr[-4000:]
    # ...then the relaunch saw 4 devices and took the elastic path:
    assert "child device set masked to 4" in r.stderr
    assert ("elastic reshard #1 (rc=84) — mesh {data:8} -> {data:4} on "
            "4 devices, global_batch 64 -> 32, grad_accum 1 -> 2"
            ) in r.stderr, r.stderr[-4000:]
    # The reshard consumed NO attempt and never tripped the breaker.
    assert "done (attempt 2)" in r.stderr, r.stderr[-4000:]
    assert "attempt 3/3" not in r.stderr
    assert "CRASH LOOP" not in r.stderr

    # The child reported its device shortfall before exiting rc=84.
    report = json.load(open(os.path.join(ckpt_dir, "devices.json")))
    assert report["visible_devices"] == 4
    assert report["needed"] == 8

    # Supervisor telemetry: the resize is a first-class recovery event.
    sup_events = os.path.join(ckpt_dir, "supervisor_events.jsonl")
    resizes = _events(sup_events, telemetry.KIND_MESH_RESIZED)
    assert len(resizes) == 1, resizes
    extra = resizes[0]["extra"]
    assert extra["from_axes"]["data"] == 8
    assert extra["to_axes"]["data"] == 4
    assert extra["effective_batch_preserved"] is True
    assert extra["global_batch"] == 32 and extra["grad_accum"] == 2
    attempts = _events(sup_events, telemetry.KIND_SUPERVISOR_ATTEMPT)
    assert [a["extra"]["classification"] for a in attempts] == \
        ["crashed", "elastic_reshard", "done"]

    # Child telemetry: the cross-mesh restore was validated and recorded.
    reshards = _events(os.path.join(ckpt_dir, "events.jsonl"),
                       telemetry.KIND_CKPT_RESHARDED)
    assert reshards, "no ckpt_resharded event in the child's events.jsonl"
    rx = reshards[-1]["extra"]
    assert rx["from_axes"]["data"] == 8 and rx["to_axes"]["data"] == 4
    assert rx["leaf_count"] > 0

    # Both events surface in the analyze_trace rollup.
    summary = telemetry.format_run_summary(
        telemetry.summarize_events(sup_events))
    assert "mesh resized: {data:8} -> {data:4}" in summary, summary

    # The run resumed from the step-20 save and trained to the horizon
    # on the smaller mesh with the effective batch preserved.
    final = [e for e in _events(os.path.join(ckpt_dir, "events.jsonl"),
                                telemetry.KIND_TRAIN_STEP)
             if e.get("step") == 60]
    assert final, "no train_step event at step 60"
    assert math.isfinite(final[-1]["metrics"]["loss"])
