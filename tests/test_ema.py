"""EMA of parameters (optimizer.ema_decay) — the
tf.train.ExponentialMovingAverage of the reference recipe class."""

import os

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train import Trainer
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _cfg(**train_overrides):
    base = {
        "name": "ema-test",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05,
                      "ema_decay": 0.9},
        "train": dict({"total_steps": 5, "log_interval": 5}, **train_overrides),
    }
    return load_config(base=base)


def test_ema_update_formula(devices):
    cfg = _cfg()
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    p0 = jax.device_get(state.params)
    # ema initialized to the params
    for a, b in zip(jax.tree.leaves(p0),
                    jax.tree.leaves(jax.device_get(state.ema_params))):
        np.testing.assert_array_equal(a, b)

    step = builder.make_train_step(batch)
    state, _ = step(state, batch)
    p1 = jax.device_get(state.params)
    ema1 = jax.device_get(state.ema_params)
    # step 0: d = min(0.9, (1+0)/(10+0)) = 0.1 → ema = 0.1*p0 + 0.9*p1
    for a0, a1, e in zip(jax.tree.leaves(p0), jax.tree.leaves(p1),
                         jax.tree.leaves(ema1)):
        np.testing.assert_allclose(e, 0.1 * a0 + 0.9 * a1,
                                   rtol=1e-5, atol=1e-6)


def _cfg_ckpt(ckpt_dir: str, ema_decay: float, total_steps: int = 4):
    base = {
        "name": "ema-toggle",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05,
                      "ema_decay": ema_decay},
        "train": {"total_steps": total_steps, "log_interval": 4},
        "checkpoint": {"directory": ckpt_dir, "save_interval_steps": 4,
                       "async_save": False},
    }
    return load_config(base=base)


@pytest.mark.slow
def test_ema_toggle_across_resume(devices, tmp_path):
    """optimizer.ema_decay flipped across a restart must not fail the
    restore (ADVICE r1: StandardRestore template mismatch)."""
    # Save WITHOUT ema, resume WITH: EMA re-seeded from restored params.
    d1 = str(tmp_path / "no_ema")
    t = Trainer(_cfg_ckpt(d1, ema_decay=0.0))
    t.train()
    t2 = Trainer(_cfg_ckpt(d1, ema_decay=0.9, total_steps=8))
    t2.build()
    assert t2.host_step == 4
    for p, e in zip(jax.tree.leaves(jax.device_get(t2.state.params)),
                    jax.tree.leaves(jax.device_get(t2.state.ema_params))):
        np.testing.assert_array_equal(p, e)
    t2.train()  # EMA path runs fine from the re-seed

    # Save WITH ema, resume WITHOUT: EMA dropped, params intact.
    d2 = str(tmp_path / "with_ema")
    t3 = Trainer(_cfg_ckpt(d2, ema_decay=0.9))
    t3.train()
    saved = jax.device_get(t3.state.params)
    t4 = Trainer(_cfg_ckpt(d2, ema_decay=0.0, total_steps=8))
    t4.build()
    assert t4.host_step == 4
    assert not jax.tree.leaves(t4.state.ema_params)
    for a, b in zip(jax.tree.leaves(saved),
                    jax.tree.leaves(jax.device_get(t4.state.params))):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_ema_metadata_probe_pins_orbax_format(devices, tmp_path):
    """Version-drift canary for `_stored_has_ema` (VERDICT r3 weak #6):
    the probe parses orbax-private `_METADATA` JSON, so an orbax upgrade
    that reshapes the tree metadata would silently flip every EMA-toggle
    restore into the warn-and-retry path. Pin the contract POSITIVELY on
    the installed orbax: the probe must answer True/False from the real
    metadata (never via its best-effort default), and fall back to the
    default only when the file is actually unreadable."""
    from distributed_tensorflow_framework_tpu.ckpt.checkpoint import (
        CheckpointManager,
    )

    # Saved WITH EMA → probe says True regardless of the default.
    d1 = str(tmp_path / "with_ema")
    t = Trainer(_cfg_ckpt(d1, ema_decay=0.9))
    t.train()
    ck = CheckpointManager(t.config.checkpoint)
    assert ck._stored_has_ema(4, default=False) is True
    ck.close()

    # Saved WITHOUT EMA (empty-Dict marker) → probe says False.
    d2 = str(tmp_path / "no_ema")
    t2 = Trainer(_cfg_ckpt(d2, ema_decay=0.0))
    t2.train()
    ck2 = CheckpointManager(t2.config.checkpoint)
    assert ck2._stored_has_ema(4, default=True) is False

    # Unreadable metadata → best-effort default, not a crash.
    meta = os.path.join(d2, "4", "state", "_METADATA")
    assert os.path.exists(meta), (
        "orbax no longer writes state/_METADATA where _stored_has_ema "
        "reads it — update the probe for this orbax version"
    )
    os.rename(meta, meta + ".bak")
    assert ck2._stored_has_ema(4, default=True) is True
    assert ck2._stored_has_ema(4, default=False) is False
    ck2.close()


def test_eval_uses_ema(devices):
    cfg = _cfg()
    trainer = Trainer(cfg)
    trainer.train()
    ema_eval = trainer.evaluate(num_batches=2)

    cfg_raw = _cfg(eval_use_ema=False)
    # Same trained state, different eval path: rebuild the eval step only.
    trainer.builder.config = cfg_raw
    trainer.eval_step = trainer.builder.make_eval_step(
        to_global(next(trainer.dataset), trainer.mesh)
    )
    raw_eval = trainer.evaluate(num_batches=2)
    # EMA params differ from raw params after a few steps, so the losses
    # must differ (they both remain finite).
    assert np.isfinite(ema_eval["eval_loss"])
    assert np.isfinite(raw_eval["eval_loss"])
    assert ema_eval["eval_loss"] != raw_eval["eval_loss"]
