"""Exact full-validation-set evaluation (SURVEY.md §3.4 eval-loop contract).

Round-1 gap: eval took `eval_steps` batches from repeat()ed streams, so
top-1 was measured on a truncated/recycled subset. These tests pin the new
contract: one pass, every example exactly once, padded final batch masked
out, metrics equal to a numpy reference computed over the raw set.
"""

import os

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.data.pipeline import finite_array_eval
from distributed_tensorflow_framework_tpu.train import Trainer

N_TEST = 87  # deliberately not divisible by any batch size used below


def test_finite_array_eval_covers_every_example_once():
    images = np.arange(N_TEST, dtype=np.float32).reshape(N_TEST, 1, 1, 1)
    labels = (np.arange(N_TEST) % 10).astype(np.int32)
    ds = finite_array_eval(images, labels, batch=16, process_index=0,
                          process_count=1, out_dtype=np.float32)
    assert ds.cardinality == 6  # ceil(87/16)
    seen = []
    total_weight = 0.0
    batches = list(ds)
    assert len(batches) == 6
    for b in batches:
        assert b["image"].shape == (16, 1, 1, 1)
        w = b["weight"]
        total_weight += float(w.sum())
        seen.extend(b["image"][w > 0, 0, 0, 0].tolist())
        # padding is zeroed and zero-weighted
        assert (b["image"][w == 0] == 0).all()
    assert total_weight == N_TEST
    assert sorted(seen) == list(range(N_TEST))  # each example exactly once
    # Stream is finite: a second pull raises StopIteration.
    with pytest.raises(StopIteration):
        next(ds)


def test_finite_array_eval_multihost_equal_batch_counts():
    # 87 examples over 4 hosts: shards of 22,22,22,21 — every host must
    # still yield ceil(22/8)=3 batches so collectives stay in step.
    images = np.zeros((N_TEST, 1, 1, 1), np.float32)
    labels = np.zeros((N_TEST,), np.int32)
    counts, weights = [], []
    for p in range(4):
        ds = finite_array_eval(images, labels, batch=8, process_index=p,
                              process_count=4, out_dtype=np.float32)
        bs = list(ds)
        counts.append(len(bs))
        weights.append(sum(float(b["weight"].sum()) for b in bs))
    assert counts == [3, 3, 3, 3]
    assert sum(weights) == N_TEST


@pytest.fixture(scope="module")
def mnist_npz(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mnist_data"))
    rng = np.random.default_rng(3)
    x_train = rng.integers(0, 255, (256, 28, 28), dtype=np.uint8)
    y_train = rng.integers(0, 10, 256).astype(np.int64)
    x_test = rng.integers(0, 255, (N_TEST, 28, 28), dtype=np.uint8)
    y_test = rng.integers(0, 10, N_TEST).astype(np.int64)
    np.savez(os.path.join(root, "mnist.npz"), x_train=x_train,
             y_train=y_train, x_test=x_test, y_test=y_test)
    return root


def test_exact_eval_matches_numpy_reference(devices, mnist_npz):
    """Trainer.evaluate over the real-file MNIST path must equal a numpy
    reference computed on the raw (unpadded, unbatched) test set."""
    cfg = load_config(base={
        "name": "exact-eval",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "mnist", "data_dir": mnist_npz,
                 "global_batch_size": 32, "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 3, "log_interval": 3},
    })
    trainer = Trainer(cfg)
    trainer.train()
    results = trainer.evaluate()
    # Full coverage: all 87 test examples, once.
    assert results["eval_examples"] == N_TEST

    # Numpy reference on the same standardized test set, no padding.
    with np.load(os.path.join(mnist_npz, "mnist.npz")) as d:
        images = d["x_test"].astype(np.float32)[..., None] / 255.0
        labels = d["y_test"].astype(np.int32)
    mean = images.mean(axis=(1, 2, 3), keepdims=True)
    std = images.std(axis=(1, 2, 3), keepdims=True) + 1e-6
    images = (images - mean) / std

    params = jax.device_get(trainer.state.params)
    logits = np.asarray(
        trainer.builder.model.apply({"params": params}, images, train=False),
        np.float32,
    )
    # log-softmax CE + top-1, f64 accumulation for a tight reference.
    shifted = logits - logits.max(axis=-1, keepdims=True)
    logp = shifted - np.log(np.exp(shifted).sum(axis=-1, keepdims=True))
    ref_loss = float(-logp[np.arange(N_TEST), labels].mean())
    ref_top1 = float((logits.argmax(-1) == labels).mean())

    assert results["eval_top1"] == pytest.approx(ref_top1, abs=1e-12)
    assert results["eval_loss"] == pytest.approx(ref_loss, rel=1e-5)


def test_native_eval_parity_with_tfdata(tmp_path):
    """The native ImageNet eval must match the tf.data eval twin on the
    same fabricated records: identical cardinality, labels, weights and
    coverage; pixels equal to decode tolerance. 64×64 JPEGs with
    image_size 56 make the resize an identity for BOTH paths (central
    crop 87.5% of 64 = 56), so the bilinear-vs-bicubic filter delta drops
    out and the comparison isolates decode + crop + standardize."""
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.core.config import DataConfig
    from distributed_tensorflow_framework_tpu.data.imagenet import make_imagenet

    root = str(tmp_path / "imgnet")
    os.makedirs(root)
    rng = np.random.default_rng(11)
    n = 17  # batch 5 → 4 batches, last padded (2 real + 3 pad)
    with tf.io.TFRecordWriter(
            os.path.join(root, "validation-00000-of-00001")) as w:
        for i in range(n):
            img = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
            w.write(tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(
                        value=[tf.io.encode_jpeg(img).numpy()])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[i + 1])),
            })).SerializeToString())

    def batches(native: bool):
        cfg = DataConfig(name="imagenet", data_dir=root, global_batch_size=5,
                         image_size=56, use_native_reader=native, seed=0,
                         num_classes=1000)  # fixture labels are 1..n ids
        ds = make_imagenet(cfg, 0, 1, train=False)
        out = list(ds)
        return ds, out

    ds_tf, tf_batches = batches(False)
    ds_nat, nat_batches = batches(True)
    assert ds_tf.cardinality == ds_nat.cardinality == 4
    assert len(tf_batches) == len(nat_batches) == 4
    for bt, bn in zip(tf_batches, nat_batches):
        np.testing.assert_array_equal(bt["label"], bn["label"])
        np.testing.assert_array_equal(bt["weight"], bn["weight"])
        a = np.asarray(bt["image"], np.float32)
        b = np.asarray(bn["image"], np.float32)
        # Standardized units (std ≈ 57 raw counts): decoder IDCT deltas of
        # a few counts → mean ~0.02, worst pixel ~3 counts on noise JPEGs;
        # identical geometry means no resize delta.
        assert np.abs(a - b).mean() < 0.05
        assert np.abs(a - b).max() < 1.0
    assert sum(float(b["weight"].sum()) for b in nat_batches) == n

    # Mid-pass resume on the native eval stream: restore after batch 1
    # replays batches 2..4 identically.
    ds2 = make_imagenet(
        DataConfig(name="imagenet", data_dir=root, global_batch_size=5,
                   image_size=56, use_native_reader=True, seed=0,
                   num_classes=1000),
        0, 1, train=False)
    first = next(ds2)
    np.testing.assert_array_equal(first["label"], nat_batches[0]["label"])
    snap = ds2.state()
    ds3 = make_imagenet(
        DataConfig(name="imagenet", data_dir=root, global_batch_size=5,
                   image_size=56, use_native_reader=True, seed=0,
                   num_classes=1000),
        0, 1, train=False)
    ds3.restore(snap)
    for want in nat_batches[1:]:
        got = next(ds3)
        np.testing.assert_array_equal(want["label"], got["label"])
        np.testing.assert_array_equal(want["weight"], got["weight"])
        np.testing.assert_array_equal(
            np.asarray(want["image"], np.float32),
            np.asarray(got["image"], np.float32))
    with pytest.raises(StopIteration):
        next(ds3)


def test_native_reader_eval_rejected_at_build(devices, tmp_path):
    """A config that would crash at the FIRST evaluate() (native MLM reader
    has no exact-eval path) must fail at build time, not after training."""
    import tensorflow as tf

    root = str(tmp_path / "mlm")
    os.makedirs(root)
    with tf.io.TFRecordWriter(os.path.join(root, "a.tfrecord")) as w:
        for r in range(8):  # a full train batch so the train-peek succeeds
            ids = np.arange(16, dtype=np.int64) + 100 + r
            w.write(tf.train.Example(features=tf.train.Features(feature={
                "input_ids": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=ids)),
            })).SerializeToString())
    cfg = load_config(base={
        "name": "native-eval-reject",
        "mesh": {"data": 8},
        "model": {"name": "bert", "vocab_size": 512, "hidden_size": 32,
                  "num_layers": 1, "num_heads": 2, "mlp_dim": 64,
                  "max_seq_len": 16, "dtype": "float32"},
        "data": {"name": "text_mlm", "data_dir": root, "seq_len": 16,
                 "vocab_size": 512,  # match the model (vocab guard)
                 "global_batch_size": 8, "use_native_reader": True},
        "train": {"total_steps": 2, "eval_steps": 2},
    })
    trainer = Trainer(cfg)
    with pytest.raises(ValueError, match="exact-eval"):
        trainer.build()


def test_eval_data_swap_invalidates_cache(devices, mnist_npz):
    """Pointing config.eval_data somewhere new after a first evaluate()
    must rebuild the cached pipeline + compiled step, not silently reuse
    the old one."""
    from distributed_tensorflow_framework_tpu.core.config import DataConfig

    cfg = load_config(base={
        "name": "eval-swap",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "mnist", "data_dir": mnist_npz,
                 "global_batch_size": 32, "image_size": 28, "channels": 1},
        "train": {"total_steps": 2, "log_interval": 2},
    })
    trainer = Trainer(cfg)
    trainer.train()
    r1 = trainer.evaluate()
    assert r1["eval_examples"] == N_TEST
    # Swap eval to the synthetic stream: different pipeline, different
    # element spec (no weight key), eval_steps fallback applies.
    trainer.config.eval_data = DataConfig(
        name="synthetic_images", global_batch_size=32, image_size=28,
        channels=1,
    )
    r2 = trainer.evaluate(num_batches=3)
    assert r2["eval_examples"] == 3 * 32
    assert r2["eval_loss"] != r1["eval_loss"]


def test_eval_hook_bounded_by_eval_steps(devices, mnist_npz):
    """Mid-training EvalHook firings evaluate eval_steps batches, not the
    full set; the final eval still covers everything."""
    cfg = load_config(base={
        "name": "eval-bounded",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "mnist", "data_dir": mnist_npz,
                 "global_batch_size": 32, "image_size": 28, "channels": 1},
        "train": {"total_steps": 4, "log_interval": 2, "eval_interval": 2,
                  "eval_steps": 1},
    })
    trainer = Trainer(cfg)
    seen = []
    orig = trainer.evaluate

    def spy(step=None, num_batches=None):
        out = orig(step=step, num_batches=num_batches)
        seen.append((num_batches, out["eval_examples"]))
        return out

    trainer.evaluate = spy
    trainer.build()
    trainer.train()  # EvalHook fires at steps 2 and 4
    final = orig()
    assert final["eval_examples"] == N_TEST  # full pass
    assert seen, "EvalHook never fired"
    for num_batches, examples in seen:
        assert num_batches == 1
        assert examples == 32  # one batch, not the full set


def test_eval_pipeline_reused_across_calls(devices, mnist_npz):
    cfg = load_config(base={
        "name": "eval-reuse",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "mnist", "data_dir": mnist_npz,
                 "global_batch_size": 32, "image_size": 28, "channels": 1},
        "train": {"total_steps": 2, "log_interval": 2},
    })
    trainer = Trainer(cfg)
    trainer.train()
    r1 = trainer.evaluate()
    ds_first = trainer._eval_ds
    r2 = trainer.evaluate()
    assert trainer._eval_ds is ds_first  # no per-call pipeline rebuild
    assert r1 == r2  # deterministic full pass both times
