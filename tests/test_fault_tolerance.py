"""Failure detection / recovery (SURVEY.md §5).

The reference's contract: MonitoredTrainingSession auto-restores from the
last checkpoint when a killed job is relaunched — no elasticity, just
kill → relaunch → resume. Same contract here: a training process is
SIGKILLed mid-run (a real kill, not a clean exit), the identical command
is relaunched, and it must restore the latest checkpoint and finish.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

DRIVER = """
import jax; jax.config.update('jax_platforms','cpu')
from distributed_tensorflow_framework_tpu.cli.train import main
main(['--set','model.name=lenet5','--set','model.dtype=float32',
      '--set','data.name=synthetic_images','--set','data.image_size=28',
      '--set','data.channels=1','--set','data.global_batch_size=64',
      '--set','mesh.data=8',
      '--set','optimizer.name=sgd_momentum','--set','optimizer.learning_rate=0.01',
      '--set','train.total_steps={steps}','--set','train.log_interval=20',
      '--set','train.eval_steps=0',
      '--set','checkpoint.directory={ckpt}',
      '--set','checkpoint.save_interval_steps=20',
      '--set','checkpoint.async_save=false'])
"""


def _launch(ckpt_dir: str, steps: int) -> subprocess.Popen:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS",)}
    env["JAX_PLATFORMS"] = ""
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", DRIVER.format(ckpt=ckpt_dir, steps=steps)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo_root,
    )


def _wait_for_checkpoint(ckpt_dir: str, timeout: float = 240.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.isdir(ckpt_dir):
            steps = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
            if steps:
                return
        time.sleep(0.5)
    raise TimeoutError(f"no checkpoint appeared in {ckpt_dir}")


@pytest.mark.slow
@pytest.mark.slowest
def test_sigkill_and_relaunch_resumes(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    steps = 4000  # far more than survive the kill window

    victim = _launch(ckpt_dir, steps)
    try:
        _wait_for_checkpoint(ckpt_dir)
    finally:
        if victim.poll() is None:
            victim.send_signal(signal.SIGKILL)
    out, _ = victim.communicate(timeout=60)
    assert victim.returncode != 0, (
        f"victim survived to completion — kill landed too late:\n{out[-2000:]}"
    )

    # Relaunch the identical command with an achievable horizon: it must
    # auto-restore (MonitoredTrainingSession contract) and run to the end.
    survivor = _launch(ckpt_dir, 60)
    out, _ = survivor.communicate(timeout=420)
    assert survivor.returncode == 0, out[-3000:]
    assert "Restored checkpoint at step" in out, out[-3000:]
    assert "final train metrics" in out, out[-3000:]
