"""Failure detection / recovery (SURVEY.md §5).

The reference's contract: MonitoredTrainingSession auto-restores from the
last checkpoint when a killed job is relaunched — no elasticity, just
kill → relaunch → resume. Same contract here: a training process is
SIGKILLed mid-run (a real kill, not a clean exit), the identical command
is relaunched, and it must restore the latest checkpoint and finish.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

DRIVER = """
import sys
import jax; jax.config.update('jax_platforms','cpu')
from distributed_tensorflow_framework_tpu.cli.train import main
sys.exit(
 main(['--set','model.name=lenet5','--set','model.dtype=float32',
      '--set','data.name=synthetic_images','--set','data.image_size=28',
      '--set','data.channels=1','--set','data.global_batch_size=64',
      '--set','mesh.data=8',
      '--set','optimizer.name=sgd_momentum','--set','optimizer.learning_rate=0.01',
      '--set','train.total_steps={steps}','--set','train.log_interval=20',
      '--set','train.eval_steps=0',
      '--set','checkpoint.directory={ckpt}',
      '--set','checkpoint.save_interval_steps=20',
      '--set','checkpoint.async_save=false']))
"""


def _child_env(env_extra: dict | None = None) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS",)}
    env["JAX_PLATFORMS"] = ""
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return env


def _launch(ckpt_dir: str, steps: int,
            env_extra: dict | None = None) -> subprocess.Popen:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.Popen(
        [sys.executable, "-c", DRIVER.format(ckpt=ckpt_dir, steps=steps)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_child_env(env_extra), cwd=repo_root,
    )


def _wait_for_checkpoint(ckpt_dir: str, timeout: float = 240.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.isdir(ckpt_dir):
            steps = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
            if steps:
                return
        time.sleep(0.5)
    raise TimeoutError(f"no checkpoint appeared in {ckpt_dir}")


@pytest.mark.slow
@pytest.mark.slowest
def test_sigkill_and_relaunch_resumes(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    steps = 4000  # far more than survive the kill window

    victim = _launch(ckpt_dir, steps)
    try:
        _wait_for_checkpoint(ckpt_dir)
    finally:
        if victim.poll() is None:
            victim.send_signal(signal.SIGKILL)
    out, _ = victim.communicate(timeout=60)
    assert victim.returncode != 0, (
        f"victim survived to completion — kill landed too late:\n{out[-2000:]}"
    )

    # Relaunch the identical command with an achievable horizon: it must
    # auto-restore (MonitoredTrainingSession contract) and run to the end.
    survivor = _launch(ckpt_dir, 60)
    out, _ = survivor.communicate(timeout=420)
    assert survivor.returncode == 0, out[-3000:]
    assert "Restored checkpoint at step" in out, out[-3000:]
    assert "final train metrics" in out, out[-3000:]


# ---------------------------------------------------------- fault drills --
# DTF_FAULTS-driven, supervised end-to-end drills (docs/RESILIENCE.md).
# The fast injection-mechanics subset lives in tests/test_faults.py; these
# run real training children and are tier-2 by their slow marks.

def _final_loss(ckpt_dir: str, step: int) -> float:
    from distributed_tensorflow_framework_tpu.core import telemetry

    losses = [
        e["metrics"]["loss"]
        for e in telemetry.read_events(
            os.path.join(ckpt_dir, "events.jsonl"),
            kind="train_step", strict=False)
        if e.get("step") == step
    ]
    assert losses, f"no train_step event at step {step} in {ckpt_dir}"
    return losses[-1]


def _run_supervised(ckpt_dir: str, steps: int, sup_args: list[str],
                    env_extra: dict, timeout: float) -> subprocess.CompletedProcess:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "scripts/train_resilient.py", *sup_args, "--",
           sys.executable, "-c", DRIVER.format(ckpt=ckpt_dir, steps=steps)]
    return subprocess.run(cmd, env=_child_env(env_extra), cwd=repo_root,
                          capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
@pytest.mark.slowest
def test_supervised_crash_in_save_drill(tmp_path):
    """The acceptance drill: SIGKILL mid-save (between checkpoint data and
    manifest commit) under the supervisor → relaunch → torn step
    quarantined → resume from the last committed step → final loss
    BIT-EXACT against an uninterrupted run of the same seed."""
    ckpt_dir = str(tmp_path / "ckpt")
    ref_dir = str(tmp_path / "ref")

    ref = _launch(ref_dir, 60)
    out, _ = ref.communicate(timeout=420)
    assert ref.returncode == 0, out[-3000:]

    r = _run_supervised(
        ckpt_dir, 60,
        ["--max-attempts", "3", "--retry-sleep", "0.2", "--jitter", "0"],
        {"DTF_FAULTS": "crash_in_save:40",
         "DTF_FAULTS_STATE": str(tmp_path / "faults_state.json")},
        timeout=560,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "firing crash_in_save:40" in r.stderr, r.stderr[-3000:]
    assert "exited rc=137" in r.stderr  # SIGKILL mid-save, relaunched
    assert "done (attempt 2)" in r.stderr
    # the torn step-40 save was quarantined as uncommitted, then re-saved
    quarantined = [d for d in os.listdir(ckpt_dir) if d.startswith("40.corrupt")]
    assert quarantined, os.listdir(ckpt_dir)
    assert os.path.isdir(os.path.join(ckpt_dir, "40"))  # the re-save
    # recovery cost at most one checkpoint interval, correctness: zero
    assert _final_loss(ckpt_dir, 60) == _final_loss(ref_dir, 60)


@pytest.mark.slow
@pytest.mark.slowest
def test_sigterm_graceful_preemption_round_trip(tmp_path):
    """SIGTERM → in-flight step finishes → checkpoint committed → exit
    rc=83 (GRACEFUL_PREEMPT_RC) → relaunch resumes from the preemption
    step."""
    import json

    from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
    from distributed_tensorflow_framework_tpu.core.supervision import (
        GRACEFUL_PREEMPT_RC,
    )

    ckpt_dir = str(tmp_path / "ckpt")
    victim = _launch(ckpt_dir, 4000)
    try:
        _wait_for_checkpoint(ckpt_dir)
    finally:
        if victim.poll() is None:
            victim.send_signal(signal.SIGTERM)
    out, _ = victim.communicate(timeout=240)
    assert victim.returncode == GRACEFUL_PREEMPT_RC, out[-3000:]
    assert "preempted gracefully" in out, out[-3000:]

    preempt_step = mf.latest_committed_step(ckpt_dir)
    assert preempt_step is not None, "no committed checkpoint after preemption"
    hb = json.load(open(os.path.join(ckpt_dir, "heartbeat.json")))
    assert hb["status"] == "preempted"
    assert hb["last_completed_step"] == preempt_step

    survivor = _launch(ckpt_dir, preempt_step + 20)
    out, _ = survivor.communicate(timeout=420)
    assert survivor.returncode == 0, out[-3000:]
    assert f"Restored checkpoint at step {preempt_step}" in out, out[-3000:]


@pytest.mark.slow
@pytest.mark.slowest
def test_crash_loop_breaker_on_deterministic_crash(tmp_path):
    """crash_at_step with NO state file re-fires on every relaunch — a
    deterministic crash. The supervisor's breaker must halt after
    --crash-loop-threshold identical no-progress failures instead of
    burning all five attempts."""
    ckpt_dir = str(tmp_path / "ckpt")
    r = _run_supervised(
        ckpt_dir, 60,
        ["--max-attempts", "5", "--retry-sleep", "0.2", "--jitter", "0",
         "--crash-loop-threshold", "2",
         # crash at step 5 < first save: no heartbeat/ckpt progress signal,
         # so every attempt has the identical (137, None, None) signature
         "--heartbeat-file", str(tmp_path / "no_heartbeat.json")],
        {"DTF_FAULTS": "crash_at_step:5"},
        timeout=560,
    )
    assert r.returncode == 137, (r.returncode, r.stderr[-3000:])
    assert "CRASH LOOP" in r.stderr
    assert "deterministic_crash_loop" in r.stderr
    assert "attempt 3/5" not in r.stderr  # halted at the threshold
