"""core/faults.py: the DTF_FAULTS injection registry (docs/RESILIENCE.md).

Fast tier-1 coverage: spec parsing, once-only semantics (in-process and
across simulated relaunches via DTF_FAULTS_STATE), the infeed stall wired
into HostDataset, batch poisoning, and checkpoint corruption. The
crash kinds SIGKILL the process, so they get a subprocess each; the full
supervised drills live in test_fault_tolerance.py / test_supervisor.py.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core import faults


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.install(faults.FaultPlan())  # empty plan; no env re-read


def test_parse_all_kinds():
    plan = faults.FaultPlan.parse(
        "crash_at_step:120, stall_infeed:30s, corrupt_ckpt:params,"
        "nan_grads:200, crash_in_save:40"
    )
    by_kind = {f.kind: f for f in plan.faults}
    assert set(by_kind) == {"crash_at_step", "stall_infeed", "corrupt_ckpt",
                            "nan_grads", "crash_in_save"}
    assert by_kind["crash_at_step"].step == 120
    assert by_kind["crash_in_save"].step == 40
    assert by_kind["nan_grads"].step == 200
    assert by_kind["stall_infeed"].seconds == 30.0
    assert by_kind["corrupt_ckpt"].arg == "params"
    assert plan.active


def test_parse_empty_and_errors():
    assert not faults.FaultPlan.parse("").active
    assert not faults.FaultPlan.parse(" , ,").active
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan.parse("explode:3")
    with pytest.raises(ValueError, match="integer step"):
        faults.FaultPlan.parse("crash_at_step:soon")
    with pytest.raises(ValueError, match=">= 1"):
        faults.FaultPlan.parse("crash_at_step:0")
    with pytest.raises(ValueError, match="duration"):
        faults.FaultPlan.parse("stall_infeed:forever")


def test_stall_zero_means_forever():
    plan = faults.FaultPlan.parse("stall_infeed:0")
    assert plan.faults[0].seconds >= 3600.0


def test_fire_matches_point_and_step():
    plan = faults.FaultPlan.parse("nan_grads:3")
    assert plan.fire("step_begin", step=2) == []
    assert plan.fire("infeed", step=3) == []  # wrong point
    fired = plan.fire("step_begin", step=3)
    assert [f.kind for f in fired] == ["nan_grads"]
    # once per process: same point+step again is a no-op
    assert plan.fire("step_begin", step=3) == []


def test_module_fire_inactive_is_noop():
    faults.install(faults.FaultPlan())
    assert faults.fire("step_begin", step=1) == []
    assert faults.fire("infeed") == []


def test_state_file_survives_relaunch(tmp_path):
    """DTF_FAULTS_STATE makes firings once-only ACROSS relaunches: a plan
    re-parsed from the same spec (the relaunched child) sees the fault as
    already fired."""
    state = str(tmp_path / "faults_state.json")
    plan1 = faults.FaultPlan.parse("nan_grads:5", state_path=state)
    assert [f.kind for f in plan1.fire("step_begin", step=5)] == ["nan_grads"]
    assert json.loads(open(state).read()) == ["nan_grads:5"]
    plan2 = faults.FaultPlan.parse("nan_grads:5", state_path=state)
    assert plan2.faults[0].fired
    assert plan2.fire("step_begin", step=5) == []


def test_infeed_stall_fires_in_host_dataset():
    from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset

    def make_iter(state):
        while True:
            yield {"x": np.zeros((2,), np.float32)}

    ds = HostDataset(make_iter, element_spec={"x": ((2,), np.float32)})
    faults.install("stall_infeed:0.2s")
    t0 = time.monotonic()
    next(ds)
    stalled = time.monotonic() - t0
    assert stalled >= 0.2
    t0 = time.monotonic()
    next(ds)  # once-only: second pull is immediate
    assert time.monotonic() - t0 < 0.2


def test_crash_at_step_sigkills_subprocess(tmp_path):
    """crash_at_step is a real SIGKILL (no cleanup) — drill it end-to-end
    in a child on the step_begin fault point. The state file must record
    the firing BEFORE the kill so a relaunch does not re-fire."""
    state = str(tmp_path / "state.json")
    prog = (
        "from distributed_tensorflow_framework_tpu.core import faults\n"
        "faults.active_plan()\n"
        "for step in (1, 2, 3):\n"
        "    faults.fire('step_begin', step=step)\n"
        "print('SURVIVED', flush=True)\n"
    )
    env = dict(os.environ, DTF_FAULTS="crash_at_step:2",
               DTF_FAULTS_STATE=state)
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == -9, (r.returncode, r.stderr)
    assert "SURVIVED" not in r.stdout
    assert json.loads(open(state).read()) == ["crash_at_step:2"]
    # relaunch with the same env: the recorded firing disarms the fault
    r2 = subprocess.run([sys.executable, "-c", prog], env=env,
                        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert "SURVIVED" in r2.stdout


def test_corrupt_checkpoint_dir_truncates_largest(tmp_path):
    d = tmp_path / "7"
    d.mkdir()
    (d / "small.bin").write_bytes(b"x" * 10)
    (d / "big.bin").write_bytes(b"y" * 1000)
    (d / "manifest.json").write_text("{}")  # never the corruption target
    hit = faults.corrupt_checkpoint_dir(str(d))
    assert hit == str(d / "big.bin")
    assert (d / "big.bin").stat().st_size == 500
    assert (d / "small.bin").stat().st_size == 10
    assert (d / "manifest.json").read_text() == "{}"


def test_corrupt_empty_dir_returns_none(tmp_path):
    d = tmp_path / "9"
    d.mkdir()
    assert faults.corrupt_checkpoint_dir(str(d)) is None


# ------------------------------------- recovery-ladder fault kinds ----
# loss_spike:N / repeat_nan:N:K / stall_infeed:S:N feed the in-process
# recovery ladder (train/anomaly.py); the supervised end-to-end drills
# live in tests/test_recovery_drills.py.


def test_parse_recovery_kinds():
    plan = faults.FaultPlan.parse(
        "loss_spike:40, repeat_nan:30:5, stall_infeed:3s:4")
    by_kind = {f.kind: f for f in plan.faults}
    assert by_kind["loss_spike"].step == 40
    assert by_kind["repeat_nan"].step == 30
    assert by_kind["repeat_nan"].count == 5
    assert by_kind["stall_infeed"].seconds == 3.0
    assert by_kind["stall_infeed"].step == 4


def test_parse_recovery_kind_errors():
    with pytest.raises(ValueError, match="start:count"):
        faults.FaultPlan.parse("repeat_nan:30")
    with pytest.raises(ValueError, match="count >= 1"):
        faults.FaultPlan.parse("repeat_nan:30:0")
    with pytest.raises(ValueError, match="ordinal must be an integer"):
        faults.FaultPlan.parse("stall_infeed:3s:soon")
    with pytest.raises(ValueError, match="ordinal must be >= 1"):
        faults.FaultPlan.parse("stall_infeed:3s:0")


def test_repeat_nan_fires_on_every_step_in_range():
    """repeat_nan:N:K poisons every step in [N, N+K) — including the
    REPLAYED steps after a rollback lands the loop back before N. That
    re-poisoning is what drives the ladder to max_rollbacks and the
    distinct-rc escalation."""
    plan = faults.FaultPlan.parse("repeat_nan:30:3")
    assert plan.fire("step_begin", step=29) == []
    for s in (30, 31):
        assert [f.kind for f in plan.fire("step_begin", step=s)] == \
            ["repeat_nan"]
    # a rollback replays step 30: still inside the window, fires again
    # (the budget is K total fires, not K distinct steps)
    assert [f.kind for f in plan.fire("step_begin", step=30)] == \
        ["repeat_nan"]
    assert plan.faults[0].fired  # 3 fires consumed the K=3 budget
    assert plan.fire("step_begin", step=31) == []


def test_stall_infeed_ordinal_targets_nth_pull():
    """The pull ordinal lets a drill stall INSIDE the step loop — pull 1
    is the Trainer's build-time sample peek, which the watchdog does not
    guard."""
    from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset

    def make_iter(state):
        while True:
            yield {"x": np.zeros((2,), np.float32)}

    ds = HostDataset(make_iter, element_spec={"x": ((2,), np.float32)})
    faults.install("stall_infeed:0.2s:3")
    for _ in range(2):  # pulls 1 and 2 are untouched
        t0 = time.monotonic()
        next(ds)
        assert time.monotonic() - t0 < 0.15
    t0 = time.monotonic()
    next(ds)  # pull 3 stalls
    assert time.monotonic() - t0 >= 0.2
