"""Fleet router (serve/fleet.py) — tier-1 unit coverage with stub
replicas.

The router is stdlib-only and fronts anything speaking the replica HTTP
contract (/predict, /healthz, /reload), so these tests drive it against
in-process stub servers: routing and balance, hedged retry on a
DIFFERENT replica, circuit-breaker eject/readmit, load shedding with
Retry-After, the rolling reload walk (including the abort-on-reject
rule), the serve-fault spec parsing, and the KIND_SERVE_ROUTE /
KIND_SERVE_EJECT / KIND_SERVE_RELOAD telemetry rollups.

The real thing — three ``cli/serve.py`` subprocesses killed, stalled and
rolled under live load — is the slow drill in test_fleet_drill.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.core.config import ServeConfig
from distributed_tensorflow_framework_tpu.serve.fleet import (
    FleetError,
    FleetRouter,
    ReplicaLaunchError,
    read_endpoint,
)

pytestmark = pytest.mark.serve


class StubReplica:
    """A scriptable replica: flip ``fail``/``down``/``slow_s`` to model a
    broken, dead, or wedged engine; ``digest`` models the weights
    actually being served (swapped by /reload unless ``reject_reload``).
    """

    def __init__(self):
        outer = self
        self.fail = False
        self.down = False
        self.slow_s = 0.0
        self.digest = "digest-v1"
        self.step = 7
        self.reject_reload = False
        self.predicts = 0
        self.reloads = 0
        self.lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if outer.down:
                    self._reply(503, {"error": "down"})
                    return
                with outer.lock:
                    digest, step = outer.digest, outer.step
                self._reply(200, {
                    "status": "ok", "task": "classify", "model": "stub",
                    "step": step, "vocab_size": 10,
                    "input_spec": {"image": {"shape": [4], "dtype": "f32"}},
                    "artifact": {"step": step, "content_digest": digest,
                                 "param_spec_digest": "spec", "reloads":
                                 outer.reloads},
                    "engine": {"state": "running", "queue_depth": 0,
                               "requests": outer.predicts},
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if self.path == "/reload":
                    payload = json.loads(body)
                    if outer.reject_reload:
                        self._reply(409, {"reloaded": False,
                                          "error": "reload rejected"})
                        return
                    with outer.lock:
                        outer.reloads += 1
                        outer.digest = "digest-" + payload["artifact_dir"]
                        outer.step += 1
                        to_digest = outer.digest
                    self._reply(200, {"reloaded": True,
                                      "to_digest": to_digest,
                                      "from_digest": "digest-v1"})
                    return
                if outer.slow_s:
                    time.sleep(outer.slow_s)
                with outer.lock:
                    outer.predicts += 1
                if outer.fail or outer.down:
                    self._reply(500, {"error": "stub failure"})
                else:
                    self._reply(200, {"outputs": [[0.0]], "rows": 1,
                                      "step": outer.step})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def _router(stubs, tmp_path=None, *, start=False, writer=None, **knobs):
    base = {"port": 0, "fleet_probe_interval_s": 0.1, "fleet_retries": 2,
            "fleet_retry_backoff_ms": 5.0, "fleet_eject_failures": 2,
            "fleet_deadline_s": 10.0, "fleet_attempt_timeout_s": 5.0,
            "fleet_healthz_stale_s": 2.0}
    base.update(knobs)
    router = FleetRouter(ServeConfig(**base), telemetry_writer=writer)
    for stub in stubs:
        router.add_replica(url=stub.url, admitted=True)
    thread = threading.Thread(target=router.serve_forever, daemon=True)
    thread.start()
    if start:
        router.start()
    return router, thread


def _post(url, payload, timeout=20.0):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


@pytest.fixture
def fleet2():
    stubs = [StubReplica(), StubReplica()]
    routers = []
    yield stubs, routers
    for router, thread in routers:
        router.shutdown("test teardown")
        thread.join(10)
    for stub in stubs:
        stub.close()


def test_routes_and_balances(fleet2):
    stubs, routers = fleet2
    router, thread = _router(stubs)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    seen = set()
    for _ in range(8):
        status, out, headers = _post(url, {"inputs": {"image": [[1.0]]}})
        assert status == 200 and out["rows"] == 1
        seen.add(headers.get("X-DTF-Replica"))
    # Equal-load ties round-robin: both replicas actually served.
    assert seen == {"r0", "r1"}
    health = router.fleet_healthz()
    assert health["fleet"]["router"]["requests"] == 8
    assert health["fleet"]["router"]["shed"] == 0
    routed = {r["replica"]: r["routed"] for r in health["fleet"]["replicas"]}
    assert routed == {"r0": 4, "r1": 4}


def test_retry_lands_on_different_replica(fleet2):
    stubs, routers = fleet2
    stubs[0].fail = True
    router, thread = _router(stubs)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    for _ in range(6):
        status, out, headers = _post(url, {"inputs": {"image": [[1.0]]}})
        assert status == 200
        assert headers.get("X-DTF-Replica") == "r1"
    health = router.fleet_healthz()
    assert health["fleet"]["router"]["retries"] >= 1
    # fleet_eject_failures=2 consecutive 500s tripped the breaker.
    states = {r["replica"]: r["state"] for r in health["fleet"]["replicas"]}
    assert states["r0"] == "ejected"


def test_eject_then_readmit_via_prober(fleet2):
    stubs, routers = fleet2
    stubs[0].down = True
    router, thread = _router(stubs, start=True)
    routers.append((router, thread))

    def state_of(index):
        health = router.fleet_healthz()
        return health["fleet"]["replicas"][index]["state"]

    deadline = time.monotonic() + 10
    while state_of(0) != "ejected" and time.monotonic() < deadline:
        time.sleep(0.05)
    assert state_of(0) == "ejected"
    stubs[0].down = False  # heals; the prober must readmit
    deadline = time.monotonic() + 10
    while state_of(0) != "admitted" and time.monotonic() < deadline:
        time.sleep(0.05)
    assert state_of(0) == "admitted"


def test_sheds_with_retry_after_when_nothing_admitted(fleet2):
    stubs, routers = fleet2
    router, thread = _router(stubs[:1], fleet_shed_retry_after_s=2.5)
    routers.append((router, thread))
    with router._lock:
        router._replicas[0].state = "ejected"
    url = f"http://{router.host}:{router.port}"
    status, out, headers = _post(url, {"inputs": {"image": [[1.0]]}})
    assert status == 503
    assert out["retryable"] is True
    assert headers.get("Retry-After") == "2.5"
    assert router.fleet_healthz()["fleet"]["router"]["shed"] == 1


def test_rolling_reload_walks_fleet_in_order(fleet2, tmp_path):
    stubs, routers = fleet2
    router, thread = _router(stubs)
    routers.append((router, thread))
    results, ok = router.rolling_reload("v2")
    assert ok is True
    assert [r["replica"] for r in results] == ["r0", "r1"]
    assert all(r["ok"] for r in results)
    assert all(r["to_digest"] == "digest-v2" for r in results)
    assert all(s.reloads == 1 for s in stubs)
    # Both replicas readmitted and self-reporting the NEW digest.
    health = router.fleet_healthz()
    for rep in health["fleet"]["replicas"]:
        assert rep["state"] == "admitted"
        assert rep["artifact"]["content_digest"] == "digest-v2"


def test_rejected_reload_aborts_roll(fleet2):
    stubs, routers = fleet2
    stubs[0].reject_reload = True
    router, thread = _router(stubs)
    routers.append((router, thread))
    results, ok = router.rolling_reload("v2")
    assert ok is False
    # The roll stopped AT the rejecting replica: r1 was never asked, so
    # a bad artifact cannot spread past the first verification failure.
    assert len(results) == 1 and results[0]["replica"] == "r0"
    assert results[0]["status"] == 409
    assert stubs[1].reloads == 0
    # The rejecting replica keeps serving its OLD weights, admitted.
    health = router.fleet_healthz()
    assert health["fleet"]["replicas"][0]["state"] == "admitted"
    url = f"http://{router.host}:{router.port}"
    status, _, _ = _post(url, {"inputs": {"image": [[1.0]]}})
    assert status == 200


def test_concurrent_rolls_are_refused(fleet2):
    stubs, routers = fleet2
    router, thread = _router(stubs)
    routers.append((router, thread))
    with router._lock:
        router._rolling = True
    with pytest.raises(FleetError, match="already in progress"):
        router.rolling_reload("v2")
    with router._lock:
        router._rolling = False


def test_4xx_passes_through_without_retry(fleet2):
    stubs, routers = fleet2
    router, thread = _router(stubs)
    routers.append((router, thread))
    url = f"http://{router.host}:{router.port}"
    # The stub 200s any predict body, so drive the router's own 400 path
    # (empty Content-Length) — a client error must not burn retries.
    body = b""
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=10)
    assert err.value.code == 400
    # No retries burned on deterministic client errors.
    assert router.fleet_healthz()["fleet"]["router"]["retries"] == 0


def test_spawn_replicas_requires_launcher(fleet2):
    stubs, routers = fleet2
    router, thread = _router(stubs)
    routers.append((router, thread))
    with pytest.raises(ReplicaLaunchError, match="no launcher"):
        router.spawn_replicas(1)


def test_read_endpoint_tolerates_absent_and_torn_files(tmp_path):
    path = tmp_path / "endpoint.json"
    assert read_endpoint(str(path)) == ""
    path.write_text("{not json")
    assert read_endpoint(str(path)) == ""
    path.write_text(json.dumps({"url": "http://127.0.0.1:9"}))
    assert read_endpoint(str(path)) == "http://127.0.0.1:9"


# ----------------------------------------------------------- serve faults


def test_serve_fault_specs_parse():
    plan = faults.FaultPlan.parse(
        "kill_replica:1:3,stall_replica:2:10s,corrupt_reload:v2")
    kill, stall, corrupt = plan.faults
    assert kill.kind == "kill_replica" and kill.replica == 1
    assert kill.step == 3 and kill.point == "fleet_chaos"
    assert stall.kind == "stall_replica" and stall.replica == 2
    assert stall.seconds == 10.0 and stall.point == "fleet_chaos"
    assert corrupt.kind == "corrupt_reload"
    assert corrupt.point == "fleet_reload" and corrupt.step is None


def test_serve_fault_defaults_and_validation():
    fault = faults.FaultPlan.parse("kill_replica:0").faults[0]
    assert fault.replica == 0 and fault.step == 1  # first tick default
    forever = faults.FaultPlan.parse("stall_replica:1:0").faults[0]
    assert forever.seconds >= 3600  # "0" = stopped forever
    with pytest.raises(ValueError, match="replica"):
        faults.FaultPlan.parse("kill_replica:-1")
    with pytest.raises(ValueError, match="replica:seconds"):
        faults.FaultPlan.parse("stall_replica:nope")


def test_serve_faults_fire_at_their_points():
    plan = faults.FaultPlan.parse("kill_replica:0:2,corrupt_reload:v2")
    assert plan.fire("fleet_chaos", step=1) == []  # tick 1: not yet
    fired = plan.fire("fleet_chaos", step=2)
    assert [f.kind for f in fired] == ["kill_replica"]
    assert plan.fire("fleet_chaos", step=2) == []  # once per process
    fired = plan.fire("fleet_reload")
    assert [f.kind for f in fired] == ["corrupt_reload"]


# ------------------------------------------------------- telemetry rollup


def test_fleet_telemetry_rollup(tmp_path):
    """KIND_SERVE_ROUTE / KIND_SERVE_EJECT / KIND_SERVE_RELOAD aggregate
    into the summary's fleet section and the human rollup."""
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    for replica, retries in (("r0", 0), ("r1", 1), ("r0", 0), ("r2", 2)):
        writer.emit(telemetry.KIND_SERVE_ROUTE,
                    metrics={"latency_ms": 5.0, "retries": retries,
                             "status": 200},
                    replica=replica, shed=False, deadline_exceeded=False)
    writer.emit(telemetry.KIND_SERVE_ROUTE,
                metrics={"latency_ms": 1.0, "retries": 0, "status": 503},
                replica=None, shed=True, deadline_exceeded=False)
    writer.emit(telemetry.KIND_SERVE_EJECT, replica="r1", action="eject",
                reason="dead (rc=-9)")
    writer.emit(telemetry.KIND_SERVE_EJECT, replica="r1", action="restart",
                reason="supervised relaunch")
    writer.emit(telemetry.KIND_SERVE_EJECT, replica="r1", action="readmit",
                reason="healthz recovered")
    writer.emit(telemetry.KIND_SERVE_RELOAD, metrics={"reload_ms": 120.0},
                replica="r0", ok=True, from_digest="aaaa1111",
                to_digest="bbbb2222")
    writer.emit(telemetry.KIND_SERVE_RELOAD, metrics={"reload_ms": 15.0},
                replica="r1", ok=False, from_digest="aaaa1111",
                to_digest=None)
    writer.close()
    summary = telemetry.summarize_events(events)
    fleet = summary["fleet"]
    assert fleet["requests"] == 5
    assert fleet["routed"] == {"r0": 2, "r1": 1, "r2": 1}
    assert fleet["retries"] == 3
    assert fleet["shed"] == 1
    assert fleet["ejects"] == [{"replica": "r1", "reason": "dead (rc=-9)"}]
    assert fleet["readmits"] == 1
    assert fleet["restarts"] == 1
    assert [r["ok"] for r in fleet["reloads"]] == [True, False]
    assert fleet["skew"] is not None
    text = telemetry.format_run_summary(summary)
    assert "fleet: 5 proxied" in text
    assert "retries 3" in text
    assert "shed 1" in text
    assert "readmits 1" in text
    assert "aaaa1111" in text and "bbbb2222" in text
    assert "REJECTED" in text


def test_runs_without_fleet_events_have_no_fleet_section(tmp_path):
    events = str(tmp_path / "train_only.jsonl")
    writer = telemetry.TelemetryWriter(events)
    writer.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    writer.close()
    summary = telemetry.summarize_events(events)
    assert summary["fleet"] is None
    assert "fleet:" not in telemetry.format_run_summary(summary)
