"""Serve-path chaos drill (tier-2): a 3-replica fleet under live load
while replicas are killed, stalled, and rolled to new weights.

The acceptance bar is the robustness headline: ZERO failed client
requests while

  * replica 0 is SIGKILLed mid-load (``kill_replica:0:10``) — the
    circuit breaker ejects it, supervision restarts it, the prober
    readmits it;
  * replica 1 is SIGSTOPed for several seconds (``stall_replica:1:6s``)
    — alive, port open, answering nothing: the hedged per-attempt
    timeout routes around it until SIGCONT;
  * the fleet is rolled to a new artifact one drained replica at a
    time, and a ``corrupt_reload`` roll is rejected by every replica
    with the old weights still serving.

The router runs IN-PROCESS (chaos timing is driven through
faults.install, deterministic relative to fleet readiness) while every
replica is a real ``cli/serve.py`` subprocess spawned by the cli/fleet
launcher — the same process tree production runs. Traffic is the real
``scripts/load_gen.py`` over HTTP; its SERVE_BENCH.json (with the /2
fleet section) is archived to ``DTF_SERVE_BENCH_DIR`` when the tier
driver sets it (scripts/run_tier1.sh).
"""

import copy
import json
import os
import pathlib
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest
from test_train_lenet import lenet_config

from distributed_tensorflow_framework_tpu.cli.fleet import (
    make_replica_launcher,
)
from distributed_tensorflow_framework_tpu.core import faults, telemetry, tracing
from distributed_tensorflow_framework_tpu.serve import (
    FleetRouter,
    export_checkpoint,
    load_artifact,
    save_artifact,
)
from distributed_tensorflow_framework_tpu.train import Trainer

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.slow, pytest.mark.serve]


def _perturbed(artifact, out_dir, bump):
    params = __import__("jax").tree.map(
        lambda x: x + np.asarray(bump, x.dtype)
        if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
        artifact.params)
    return save_artifact(
        str(out_dir), model_config=artifact.model_config,
        task=artifact.task, params=params,
        batch_stats=artifact.batch_stats, step=artifact.step + 1,
        input_spec=artifact.input_spec,
        vocab_size=artifact.meta.get("vocab_size"))


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def test_fleet_chaos_drill(devices, tmp_path):
    # 1. Train + export the serving artifact, and two rollout versions.
    cfg = lenet_config(**{
        "checkpoint.directory": str(tmp_path / "ckpt"),
        "checkpoint.async_save": False,
        "checkpoint.save_interval_steps": 10,
        "train.total_steps": 10,
    })
    trainer = Trainer(cfg)
    trainer.build()
    trainer.train()
    cfg.serve.data = 1
    cfg.serve.allow_reshard = True
    art_dir = export_checkpoint(cfg, str(tmp_path / "artifact"))
    artifact = load_artifact(art_dir)
    v2_dir = _perturbed(artifact, tmp_path / "artifact_v2", 0.1)
    v3_dir = _perturbed(artifact, tmp_path / "artifact_v3", 0.2)
    v2_digest = load_artifact(v2_dir).version_digest

    # 2. Router in-process, replicas as real cli/serve.py subprocesses
    # via the same launcher cli/fleet.py uses.
    serve_cfg = copy.deepcopy(cfg.serve)
    serve_cfg.port = 0
    serve_cfg.fleet_replicas = 3
    serve_cfg.fleet_probe_interval_s = 0.25
    serve_cfg.fleet_eject_failures = 2
    serve_cfg.fleet_healthz_stale_s = 5.0
    serve_cfg.fleet_attempt_timeout_s = 8.0
    # Below load_gen's 60s client timeout: the router must always answer
    # (even with its worst-case retry chain) before the client gives up.
    serve_cfg.fleet_deadline_s = 45.0
    serve_cfg.fleet_retries = 3
    serve_cfg.drain_timeout_s = 30.0
    log_dir = tmp_path / "fleet_logs"
    log_dir.mkdir()
    events_path = str(log_dir / "events.jsonl")
    writer = telemetry.TelemetryWriter(events_path)
    launcher = make_replica_launcher(
        art_dir, str(log_dir),
        ["serve.max_batch_size=8", "serve.max_wait_ms=5"])
    recorder = tracing.FlightRecorder(
        256, dump_dir=str(log_dir)).attach(writer)
    router = FleetRouter(serve_cfg, telemetry_writer=writer,
                         launcher=launcher, flight_recorder=recorder)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    serve_thread = threading.Thread(target=router.serve_forever,
                                    daemon=True)
    try:
        # Install the chaos plan BEFORE the prober starts: the chaos
        # clock arms once all replicas are admitted, stall fires at
        # tick 1 (right as readiness lands) and the kill at tick 10
        # (~2.5s later, while load_gen traffic is flowing).
        faults.install("kill_replica:0:10,stall_replica:1:6s")
        router.spawn_replicas()
        serve_thread.start()
        router.start()
        assert router.wait_ready(timeout=240.0), router.fleet_healthz()
        url = f"http://{router.host}:{router.port}"

        def replica(index):
            return router.fleet_healthz()["fleet"]["replicas"][index]

        # 3. Drive real client load through load_gen while the chaos
        # plan kills r0 and stalls r1 underneath it.
        bench_dir = os.environ.get("DTF_SERVE_BENCH_DIR")
        if bench_dir:
            os.makedirs(bench_dir, exist_ok=True)
            bench_path = os.path.join(bench_dir, "SERVE_BENCH_FLEET.json")
        else:
            bench_path = str(tmp_path / "SERVE_BENCH_FLEET.json")
        gen = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "load_gen.py"),
             "--endpoint", url, "--requests", "300", "--concurrency", "16",
             "--mode", "closed", "--out", bench_path],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=600)
        assert gen.returncode == 0, gen.stdout + gen.stderr

        # 4. ZERO failed client requests, with the failures the router
        # absorbed visible in the bench's fleet section.
        bench = json.loads(pathlib.Path(bench_path).read_text())
        assert bench["schema"] == "dtf-serve-bench/2"
        run = bench["runs"][0]
        assert run["ok"] == 300 and run["errors"] == 0, run
        assert run["by_replica"]  # per-replica client attribution
        assert bench["fleet"] is not None
        assert bench["fleet"]["router_delta"]["requests"] >= 300

        # 5. The killed replica was ejected, restarted by supervision,
        # and readmitted; the stalled one recovered after SIGCONT.
        _wait(lambda: replica(0)["restarts"] >= 1, 60,
              "supervised restart of the killed replica")
        _wait(lambda: all(replica(i)["state"] == "admitted"
                          for i in range(3)), 240,
              "killed + stalled replicas readmitted")

        # 6. Rolling reload to v2: drain → reload → probe → readmit, one
        # replica at a time, mixed versions visible mid-roll via the
        # content digest each replica self-reports on /healthz.
        body = json.dumps({"artifact_dir": v2_dir}).encode()
        req = urllib.request.Request(
            url + "/reload", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            roll = json.load(resp)
        assert roll["reloaded"] is True, roll
        assert [r["ok"] for r in roll["replicas"]] == [True] * 3
        assert all(r["to_digest"] == v2_digest for r in roll["replicas"])
        assert all(r["from_digest"] != v2_digest
                   for r in roll["replicas"])
        health = router.fleet_healthz()
        assert all(r["artifact"]["content_digest"] == v2_digest
                   for r in health["fleet"]["replicas"])

        # 7. corrupt_reload: the NEW artifact is torn before the roll;
        # the first replica's manifest verification rejects it (409),
        # the roll aborts, and every replica still serves v2.
        faults.install("corrupt_reload:v3")
        req = urllib.request.Request(
            url + "/reload",
            data=json.dumps({"artifact_dir": v3_dir}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                rejected = json.load(resp)
                status = resp.status
        except urllib.error.HTTPError as e:
            status, rejected = e.code, json.loads(e.read() or b"{}")
        assert status == 409 and rejected["reloaded"] is False
        assert len(rejected["replicas"]) == 1  # aborted at the first 409
        assert rejected["replicas"][0]["status"] == 409
        health = router.fleet_healthz()
        assert all(r["artifact"]["content_digest"] == v2_digest
                   for r in health["fleet"]["replicas"])
        ok, _, _ = _predict_ok(url)
        assert ok

        # 8. Telemetry explains the whole degradation story: routes with
        # retries, eject → restart → readmit, reload timeline.
        writer.close()
        events = list(telemetry.read_events(events_path))
        actions = [(ev["extra"].get("replica"), ev["extra"].get("action"))
                   for ev in events
                   if ev["kind"] == telemetry.KIND_SERVE_EJECT]
        assert ("r0", "eject") in actions
        assert ("r0", "restart") in actions
        assert ("r0", "readmit") in actions
        reloads = [ev["extra"] for ev in events
                   if ev["kind"] == telemetry.KIND_SERVE_RELOAD]
        assert sum(1 for ev in reloads if ev.get("ok")) >= 3
        summary = telemetry.summarize_events(events_path)
        assert summary["fleet"]["requests"] >= 300
        assert summary["fleet"]["restarts"] >= 1
        text = telemetry.format_run_summary(summary)
        assert "fleet:" in text and "ejections:" in text
        assert summary["spans"]["count"] > 0  # KIND_SPAN rode the stream

        # 9. One causal story per request: every load_gen request carried
        # a fresh trace context, and a request that survived the chaos by
        # retrying stitches into ONE tree — router root, the failed
        # attempt, the retried attempt, and the winning replica's
        # queue/batch/compute spans from its own events stream.
        from scripts import analyze_trace
        assert len(run["trace_ids"]) == 300  # per-request ids, /2-additive
        trace_ids = set(run["trace_ids"])
        span_paths = [p for p in (
            events_path,
            *(str(log_dir / f"r{i}" / "events.jsonl") for i in range(3)),
        ) if os.path.exists(p)]
        traces = analyze_trace.build_traces(
            analyze_trace.collect_spans(span_paths))
        retried = None
        for t in traces:
            if t["trace"] not in trace_ids:
                continue
            names = {s["name"] for s in t["spans"]}
            failed = any(s["name"] == "fleet.attempt"
                         and s["status"] != "ok" for s in t["spans"])
            won = any(s["name"] == "fleet.attempt"
                      and s["status"] == "ok" for s in t["spans"])
            if failed and won and "engine.compute" in names:
                retried = t
                break
        assert retried is not None, \
            "no retried request produced a full router→engine trace tree"
        assert [r["name"] for r in retried["roots"]] == ["router.request"]
        assert {"router.request", "fleet.attempt", "serve.request",
                "engine.queue", "engine.batch",
                "engine.compute"} <= {s["name"] for s in retried["spans"]}
        cp = analyze_trace.critical_path(retried)
        assert cp["retry"] > 0, cp  # the failed attempt cost is visible

        # Perfetto export: valid Chrome trace-event JSON, archived for
        # the tier driver when DTF_TRACE_DIR is set.
        trace_dir = os.environ.get("DTF_TRACE_DIR") or str(tmp_path)
        os.makedirs(trace_dir, exist_ok=True)
        perfetto_path = os.path.join(trace_dir, "FLEET_TRACE.json")
        assert analyze_trace.main(
            [str(log_dir),
             *(str(log_dir / f"r{i}") for i in range(3)),
             "--spans", "--perfetto", perfetto_path]) == 0
        doc = json.loads(pathlib.Path(perfetto_path).read_text())
        assert any(e.get("ph") == "X" for e in doc["traceEvents"])

        # 10. The flight recorder dumped when the prober saw r0 die; the
        # dump's ring holds the fault's causal neighborhood (the eject
        # record and the spans that led up to it).
        rec_path = str(log_dir / f"flightrec-{os.getpid()}.json")
        assert os.path.exists(rec_path), os.listdir(str(log_dir))
        rec = json.loads(pathlib.Path(rec_path).read_text())
        assert rec["schema"] == tracing.FLIGHTREC_SCHEMA
        assert "r0" in rec["reason"] and "dead" in rec["reason"]
        kinds = {e.get("kind") for e in rec["events"]}
        assert telemetry.KIND_SERVE_EJECT in kinds, sorted(kinds)
    finally:
        faults.install(None)
        clean = router.shutdown("drill teardown")
        serve_thread.join(30)
        try:
            writer.close()
        except ValueError:
            pass
        assert clean, "fleet drain left a replica running"


def _predict_ok(url):
    rng = np.random.default_rng(3)
    image = rng.normal(size=(1, 28, 28, 1)).astype(np.float32).tolist()
    body = json.dumps({"inputs": {"image": image}}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json.load(resp)
        return resp.status == 200, out, resp.headers.get("X-DTF-Replica")
