"""Goodput ledger (core/goodput.py): wall-clock accounting that sums.

Unit-level coverage of the accountant the observability drill
(tests/test_observability_drill.py) exercises end-to-end: bucket math,
the TelemetryWriter listener join (ckpt_save blocked-ms → ckpt_blocked),
periodic/final emission, cross-attempt stitching with supervisor-
classified restart gaps, and the rendered table's sums-to-100% property.
"""

import pytest

from distributed_tensorflow_framework_tpu.core import goodput, telemetry


def test_snapshot_buckets_sum_to_wall():
    led = goodput.GoodputLedger()
    led._t0 -= 1.0  # backdate the clock: 1 s of wall has "elapsed"
    led.add("startup", 0.01)
    led.absorb_phases({"dispatch": 0.02, "infeed": 0.005})
    snap = led.snapshot()
    assert set(snap["buckets"]) == {
        "startup", "step_compute", "infeed_wait", "other"}
    # other is the residual, so the buckets reconstruct the wall exactly
    # (to rounding) — the invariant the drill asserts across attempts.
    assert sum(snap["buckets"].values()) == pytest.approx(
        snap["wall_s"], abs=0.01)
    assert 0.0 <= snap["goodput_frac"] <= 1.0


def test_backdated_clock_keeps_startup_inside_wall():
    """The Trainer backdates the ledger to its __init__ entry: a startup
    charge spanning the pre-ledger build must fit inside wall_s instead
    of overflowing it (which would clamp ``other`` at 0 and break the
    buckets-sum-to-wall invariant the drill asserts)."""
    import time
    t0 = time.perf_counter() - 5.0  # "__init__ started 5 s ago"
    led = goodput.GoodputLedger(t0_perf=t0)
    led.add("startup", time.perf_counter() - t0)  # the loop-entry charge
    snap = led.snapshot()
    assert snap["wall_s"] >= snap["buckets"]["startup"]
    assert sum(snap["buckets"].values()) == pytest.approx(
        snap["wall_s"], abs=0.01)
    # t0_wall is shifted back by the same amount, so cross-attempt
    # stitching sees coverage start where the wall actually began.
    assert time.time() - led.t0_wall == pytest.approx(
        snap["wall_s"], abs=0.5)


def test_absorb_phases_maps_and_preserves_unknown():
    led = goodput.GoodputLedger()
    led.absorb_phases({"dispatch": 1.0, "backpressure": 0.5,
                       "compile": 0.25, "infeed": 0.125,
                       "metrics_fetch": 0.0625, "mystery_phase": 0.03})
    snap = led.snapshot()
    b = snap["buckets"]
    assert b["step_compute"] == pytest.approx(1.5)  # dispatch+backpressure
    assert b["recompile"] == pytest.approx(0.25)
    assert b["infeed_wait"] == pytest.approx(0.125)
    assert b["metrics_fetch"] == pytest.approx(0.0625)
    # An unrecognized StepTimer phase must never silently vanish.
    assert b["mystery_phase"] == pytest.approx(0.03)


def test_timed_and_counts():
    led = goodput.GoodputLedger()
    with led.timed("rollback"):
        pass
    led.count("rollbacks")
    led.count("batches_skipped", 3)
    snap = led.snapshot()
    assert snap["buckets"]["rollback"] >= 0.0
    assert snap["counters"] == {"rollbacks": 1, "batches_skipped": 3}


def test_listener_joins_ckpt_save_blocked_ms(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="led")
    led = goodput.GoodputLedger(w, interval_s=0.0)
    w.emit(telemetry.KIND_CKPT_SAVE, step=10,
           metrics={"ckpt_save_blocked_ms": 1500.0,
                    "ckpt_save_total_ms": 2000.0})
    w.emit(telemetry.KIND_INFEED_STALL, step=11, health={"attempt": 2})
    w.emit(telemetry.KIND_ROLLBACK, step=12,
           health={"from_step": 12, "to_step": 10})
    w.emit(telemetry.KIND_BATCH_SKIPPED, step=12, health={"batches": 2})
    w.close()
    snap = led.snapshot()
    assert snap["buckets"]["ckpt_blocked"] == pytest.approx(1.5)
    assert snap["counters"] == {"ckpt_saves": 1, "infeed_stalls": 1,
                                "rollbacks": 1, "batches_skipped": 2}


def test_finalize_emits_valid_goodput_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="led")
    led = goodput.GoodputLedger(w, interval_s=1e9)
    led._t0 -= 1.0  # backdate: the event's wall_s must be nonzero
    led.absorb_phases({"dispatch": 0.5})
    assert led.maybe_emit(step=1) is None  # interval not elapsed
    led.finalize(step=2)
    w.close()
    evs = list(telemetry.read_events(
        path, kind=telemetry.KIND_GOODPUT, strict=True))
    assert len(evs) == 1
    ev = evs[0]
    assert ev["extra"]["final"] is True
    assert ev["extra"]["buckets"]["step_compute"] == pytest.approx(0.5)
    assert ev["extra"]["t0"] == pytest.approx(led.t0_wall)
    assert ev["metrics"]["wall_s"] > 0


def _emit_attempt(path, run_id, *, t0, wall_s, buckets, counters=None,
                  final=True):
    w = telemetry.TelemetryWriter(path, run_id=run_id)
    productive = sum(buckets.get(b, 0.0)
                     for b in goodput.PRODUCTIVE_BUCKETS)
    w.emit(telemetry.KIND_GOODPUT,
           metrics={"wall_s": wall_s,
                    "goodput_frac": productive / wall_s},
           buckets=buckets, counters=counters or {}, t0=t0, final=final)
    w.close()


def test_stitch_attempts_classified_gaps(tmp_path):
    ev_path = str(tmp_path / "events.jsonl")
    # attempt 1: 10 s, 8 productive; crashes. attempt 2 starts 3 s later.
    _emit_attempt(ev_path, "run-a", t0=1000.0, wall_s=10.0,
                  buckets={"step_compute": 8.0, "startup": 1.0,
                           "other": 1.0},
                  counters={"ckpt_saves": 2})
    _emit_attempt(ev_path, "run-b", t0=1013.0, wall_s=5.0,
                  buckets={"step_compute": 4.0, "other": 1.0},
                  counters={"ckpt_saves": 1})
    sup = str(tmp_path / "supervisor_events.jsonl")
    sw = telemetry.TelemetryWriter(sup, run_id="sup")
    sw.emit(telemetry.KIND_SUPERVISOR_ATTEMPT, attempt=1, rc=137,
            classification="crashed")
    sw.close()

    g = goodput.stitch_attempts(ev_path)
    assert [a["run_id"] for a in g["attempts"]] == ["run-a", "run-b"]
    assert g["wall_s"] == pytest.approx(18.0)  # 10 + 5 + 3 gap
    assert g["buckets"]["restart_gap"] == pytest.approx(3.0)
    assert g["restart_gaps"] == [
        {"after_attempt": 1, "seconds": pytest.approx(3.0),
         "classification": "crashed"}]
    assert g["counters"] == {"ckpt_saves": 3}
    assert g["goodput_frac"] == pytest.approx(12.0 / 18.0)
    # The invariant the drill asserts: buckets cover the measured span.
    assert sum(g["buckets"].values()) == pytest.approx(g["wall_s"])


def test_stitch_prefers_final_over_periodic(tmp_path):
    ev_path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(ev_path, run_id="run-a")
    w.emit(telemetry.KIND_GOODPUT, metrics={"wall_s": 2.0,
                                            "goodput_frac": 0.5},
           buckets={"step_compute": 1.0, "other": 1.0}, counters={},
           t0=100.0, final=False)
    w.emit(telemetry.KIND_GOODPUT, metrics={"wall_s": 6.0,
                                            "goodput_frac": 0.5},
           buckets={"step_compute": 3.0, "other": 3.0}, counters={},
           t0=100.0, final=True)
    # A periodic event written AFTER the final one (out-of-order flush)
    # must not displace it.
    w.emit(telemetry.KIND_GOODPUT, metrics={"wall_s": 3.0,
                                            "goodput_frac": 0.5},
           buckets={"step_compute": 1.5, "other": 1.5}, counters={},
           t0=100.0, final=False)
    w.close()
    g = goodput.stitch_attempts(ev_path)
    assert len(g["attempts"]) == 1
    assert g["wall_s"] == pytest.approx(6.0)
    assert g["attempts"][0]["final"] is True


def test_stitch_returns_none_without_goodput_events(tmp_path):
    ev_path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(ev_path, run_id="serve")
    w.emit(telemetry.KIND_SERVE_QUEUE, metrics={"queue_depth": 1})
    w.close()
    assert goodput.stitch_attempts(ev_path) is None


def test_format_table_sums_to_100_pct(tmp_path):
    ev_path = str(tmp_path / "events.jsonl")
    _emit_attempt(ev_path, "run-a", t0=0.0, wall_s=10.0,
                  buckets={"step_compute": 7.0, "infeed_wait": 2.0,
                           "other": 1.0})
    g = goodput.stitch_attempts(ev_path)
    text = goodput.format_goodput_table(g)
    assert "goodput ledger: 1 attempt(s), 10.0 s measured wall-clock" in text
    assert "step_compute         7.00   70.0%" in text
    assert "TOTAL               10.00  100.0%" in text
    assert "goodput: 70.0% of wall-clock was productive step compute" in text


def test_listener_failure_does_not_break_emit(tmp_path):
    """A broken listener must never lose the run's telemetry."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="led")

    def bad_listener(ev):
        raise RuntimeError("boom")

    w.add_listener(bad_listener)
    w.emit(telemetry.KIND_HEALTH, health={"event": "ok"})
    w.close()
    evs = list(telemetry.read_events(path, strict=True))
    assert len(evs) == 1
