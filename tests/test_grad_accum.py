"""Gradient accumulation (train.grad_accum_steps).

The accumulation invariant: for a dropout/BN-free model in float32, one
step on batch B with grad_accum_steps=k must produce (numerically) the
same parameters as one step on B with no accumulation — mean of equal-size
microbatch gradients == full-batch gradient.
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _lenet_cfg(accum: int):
    return load_config(base={
        "name": "accum-test",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 32,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.1},
        "train": {"total_steps": 2, "grad_accum_steps": accum},
    })


def _one_step(accum: int, devices):
    cfg = _lenet_cfg(accum)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((32, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 32).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    state, metrics = step(state, batch)
    return jax.device_get(state.params), jax.device_get(metrics)


@pytest.mark.slow
def test_accum_matches_full_batch(devices):
    p1, m1 = _one_step(1, devices)
    p4, m4 = _one_step(4, devices)
    flat1 = jax.tree.leaves(p1)
    flat4 = jax.tree.leaves(p4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    assert np.isclose(m1["loss"], m4["loss"], rtol=1e-5)


def _bert_cfg(accum: int):
    # dropout off: the accum path folds a different rng per microbatch, so
    # only the deterministic model can match the accum=1 trajectory.
    return load_config(base={
        "name": "accum-mlm-test",
        "mesh": {"data": 8},
        "model": {"name": "bert", "vocab_size": 64, "hidden_size": 32,
                  "num_layers": 2, "num_heads": 2, "mlp_dim": 64,
                  "max_seq_len": 16, "dtype": "float32", "dropout_rate": 0.0},
        "data": {"name": "synthetic_mlm", "vocab_size": 64,
                 "global_batch_size": 16, "seq_len": 16},
        # sgd, not adam: adaptive per-param normalization amplifies float
        # summation-order noise in tiny grads far beyond any tolerance that
        # would still catch a real weighting bug.
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.1},
        "train": {"total_steps": 2, "grad_accum_steps": accum},
    })


def _one_mlm_step(accum: int):
    from distributed_tensorflow_framework_tpu.data import get_dataset

    cfg = _bert_cfg(accum)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    ds = get_dataset(cfg.data)
    batch = to_global(next(ds), mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    state, metrics = step(state, batch)
    return jax.device_get(state.params), jax.device_get(metrics)


@pytest.mark.slow
def test_accum_matches_full_batch_mlm(devices):
    """MLM normalizes by the per-microbatch masked-token count; the
    weighted accumulation must still reproduce the full-batch gradient."""
    p1, m1 = _one_mlm_step(1)
    p4, m4 = _one_mlm_step(4)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)
    assert np.isclose(m1["loss"], m4["loss"], rtol=1e-4)


def test_accum_indivisible_batch_rejected(devices):
    cfg = _lenet_cfg(5)  # 32 % 5 != 0
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((32, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 32).astype(np.int32),
    }
    batch = to_global(host, mesh)
    import pytest

    with pytest.raises(ValueError, match="does not divide"):
        state = builder.init_state(0, batch)
        step = builder.make_train_step(batch)
        step(state, batch)
