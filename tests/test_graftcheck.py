"""graftcheck: AST-layer passes, suppressions, report schema, CLI.

Two jobs (ISSUE 11):

  * prove every pass LIVE — each must produce findings on its ``*_bad``
    fixture (tests/graftcheck_fixtures/) and stay silent on the clean
    twin; a lint that never fires is indistinguishable from no lint;
  * hold the repo itself clean — the self-audit runs the registered
    passes over this checkout in tier-1 and asserts zero unsuppressed
    findings, making graftcheck's rules part of the PR gate.

The jaxpr-layer twins live in tests/test_graftcheck_jaxpr.py.
"""

import ast
import json
import pathlib
import subprocess
import sys

import pytest

from tools.graftcheck import ast_passes, cli, registry
from tools.graftcheck.context import RepoContext, git_changed_files
from tools.graftcheck.findings import (
    Finding,
    REPORT_SCHEMA,
    SEVERITY_INTERNAL,
    apply_suppressions,
    build_report,
    load_suppressions,
    round_trip,
    validate_report,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIX = pathlib.Path(__file__).resolve().parent / "graftcheck_fixtures"
SNIP = FIX / "snippets"


def _tree(path: pathlib.Path) -> ast.Module:
    return ast.parse(path.read_text(), filename=str(path))


def _fixture_ctx(name: str) -> RepoContext:
    return RepoContext(FIX / name, package="pkg")


# ---------------------------------------------------------------- registry --
def test_registry_has_the_advertised_pass_set():
    ids = set(registry.PASSES)
    assert {"raw-collective", "host-sync-in-step", "config-knob-coverage",
            "telemetry-kind-coverage", "slow-marker", "typed-errors",
            "thread-lifecycle", "lock-discipline",
            "jaxpr-donation", "jaxpr-f32-upcast",
            "jaxpr-collective-census",
            "hlo-reshard-census", "hlo-donation-survival",
            "hlo-memory-budget"} <= ids
    assert len(ids) >= 14
    jaxpr = registry.passes_for_layer(registry.LAYER_JAXPR)
    assert len(jaxpr) >= 2
    hlo = registry.passes_for_layer(registry.LAYER_HLO)
    assert len(hlo) == 3


def test_duplicate_pass_id_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        registry.register("raw-collective", registry.LAYER_AST, "dup")(
            lambda ctx: [])
    with pytest.raises(ValueError, match="unknown layer"):
        registry.register("brand-new", "nope", "bad layer")(lambda ctx: [])


# --------------------------------------------------- per-file pass fixtures --
def test_raw_collective_pass_fires_on_bad_fixture():
    path = SNIP / "raw_collective_bad.py"
    findings = ast_passes.scan_raw_collectives("snip.py", _tree(path))
    assert len(findings) == 3, [f.message for f in findings]
    msgs = " ".join(f.message for f in findings)
    assert "pmean" in msgs and "all_gather" in msgs and "psum" in msgs


def test_raw_collective_pass_silent_on_clean_fixture():
    path = SNIP / "raw_collective_clean.py"
    assert ast_passes.scan_raw_collectives("snip.py", _tree(path)) == []


def test_host_sync_pass_fires_on_bad_fixture():
    path = SNIP / "host_sync_bad.py"
    findings = ast_passes.scan_host_sync("snip.py", _tree(path))
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 5, [f.message for f in findings]
    for marker in (".item", "device_get", "block_until_ready", "numpy",
                   "float()"):
        assert marker in msgs, marker


def test_host_sync_pass_silent_on_clean_fixture():
    # float(4) on a literal is NOT a device sync and must not be flagged.
    path = SNIP / "host_sync_clean.py"
    assert ast_passes.scan_host_sync("snip.py", _tree(path)) == []


def test_typed_errors_pass_fires_on_bad_fixture():
    path = SNIP / "typed_errors_bad.py"
    findings = ast_passes.scan_typed_errors("snip.py", _tree(path))
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 4, [f.message for f in findings]
    assert "raise Exception" in msgs
    assert "bare" in msgs
    assert "named" in msgs  # BadFailure must be *Error
    assert "docstring" in msgs


def test_typed_errors_pass_silent_on_clean_fixture():
    path = SNIP / "typed_errors_clean.py"
    assert ast_passes.scan_typed_errors("snip.py", _tree(path)) == []


# ---------------------------------------------------- mini-repo pass fixtures --
def test_config_coverage_fires_on_dead_knob():
    findings = ast_passes.config_coverage_pass(_fixture_ctx("config_repo_bad"))
    assert len(findings) == 2, [f.message for f in findings]
    assert all("dead_knob" in f.message for f in findings)
    kinds = {("never read" in f.message, "nowhere in docs" in f.message)
             for f in findings}
    assert kinds == {(True, False), (False, True)}


def test_config_coverage_silent_on_clean_repo():
    # alpha is read as an attribute, axis_name as a string constant — both
    # count as consumption, both documented.
    findings = ast_passes.config_coverage_pass(
        _fixture_ctx("config_repo_clean"))
    assert findings == [], [f.message for f in findings]


def test_telemetry_coverage_fires_on_orphan_and_duplicate_kinds():
    findings = ast_passes.telemetry_coverage_pass(
        _fixture_ctx("telemetry_repo_bad"))
    msgs = [f.message for f in findings]
    assert len(findings) == 3, msgs
    assert any("share the string value" in m for m in msgs)
    assert any("KIND_ORPHAN" in m and "rollup" in m for m in msgs)
    assert any("KIND_ORPHAN" in m and "no test" in m for m in msgs)


def test_telemetry_coverage_silent_on_clean_repo():
    findings = ast_passes.telemetry_coverage_pass(
        _fixture_ctx("telemetry_repo_clean"))
    assert findings == [], [f.message for f in findings]


def test_slow_marker_fires_on_unmarked_drill():
    findings = ast_passes.slow_marker_pass(_fixture_ctx("marker_repo_bad"))
    assert len(findings) == 1, [f.message for f in findings]
    assert "test_crash_drill_without_mark" in findings[0].message


def test_slow_marker_silent_on_marked_drill():
    findings = ast_passes.slow_marker_pass(_fixture_ctx("marker_repo_clean"))
    assert findings == [], [f.message for f in findings]


# ------------------------------------------------------------ suppressions --
def test_suppression_file_parsing(tmp_path):
    sup = tmp_path / "sup.txt"
    sup.write_text(
        "# comment\n"
        "\n"
        "raw-collective | tests/foo.py:* | parity reference\n"
        "only | twofields\n"
        "typed-errors | pkg/x.py:3 |\n")
    sups, findings = load_suppressions(sup)
    assert len(sups) == 1
    assert sups[0].pass_id == "raw-collective"
    assert sups[0].justification == "parity reference"
    # Malformed line + missing justification both become findings.
    assert len(findings) == 2
    assert all(f.pass_id == "suppressions" for f in findings)


def load_suppressions_from_lines(*lines):
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".txt", delete=False) as fh:
        fh.write("\n".join(lines) + "\n")
        name = fh.name
    return load_suppressions(name)


def test_suppression_matching_marks_and_copies_justification():
    f1 = Finding("raw-collective", "tests/foo.py:7", "raw psum")
    f2 = Finding("raw-collective", "pkg/bar.py:9", "raw psum")
    sups, _ = load_suppressions_from_lines(
        "raw-collective | tests/foo.py:* | known parity test")
    stale = apply_suppressions([f1, f2], sups)
    assert f1.suppressed and f1.justification == "known parity test"
    assert not f2.suppressed
    assert stale == []


def test_stale_suppression_is_a_finding():
    sups, _ = load_suppressions_from_lines(
        "typed-errors | nowhere.py:* | obsolete")
    stale = apply_suppressions([], sups, suppression_file="sup.txt")
    assert len(stale) == 1
    assert "stale suppression" in stale[0].message
    assert stale[0].where == "sup.txt:1"


def test_stale_check_scoped_to_passes_run():
    # Partial runs (--layer/--pass) must not call suppressions for unrun
    # passes stale.
    sups, _ = load_suppressions_from_lines(
        "jaxpr-f32-upcast | trace:* | intentional f32 head")
    stale = apply_suppressions([], sups, stale_check_ids={"raw-collective"})
    assert stale == []
    stale = apply_suppressions([], sups,
                               stale_check_ids={"jaxpr-f32-upcast"})
    assert len(stale) == 1


def test_internal_errors_are_not_suppressible():
    f = Finding("telemetry-kind-coverage", "core/telemetry.py",
                "extraction degraded", severity=SEVERITY_INTERNAL)
    sups, _ = load_suppressions_from_lines(
        "* | * | sweep everything under the rug")
    apply_suppressions([f], sups)
    assert not f.suppressed


# ------------------------------------------------------------ report schema --
def test_report_builds_validates_and_round_trips():
    findings = [
        Finding("raw-collective", "pkg/a.py:1", "raw psum"),
        Finding("raw-collective", "tests/b.py:2", "raw psum",
                suppressed=True, justification="parity"),
        Finding("slow-marker", "tests/c.py", "vacuous",
                severity=SEVERITY_INTERNAL),
    ]
    report = build_report(findings, ["raw-collective", "slow-marker"], ROOT)
    assert report["schema"] == REPORT_SCHEMA
    assert report["counts"] == {
        "findings": 2, "suppressed": 1, "internal_errors": 1}
    assert validate_report(report) == []
    assert round_trip(report) == json.loads(json.dumps(report))
    assert Finding.from_dict(report["findings"][0]).fingerprint == \
        "raw-collective|pkg/a.py:1"


def test_report_validation_catches_shape_violations():
    assert validate_report({}) != []
    bad = build_report([Finding("p", "w", "m")], ["p"], ROOT)
    bad["schema"] = "dtf-lint-report/0"
    bad["findings"][0]["severity"] = "warning"
    del bad["counts"]["findings"]
    errs = validate_report(bad)
    assert any("schema" in e for e in errs)
    assert any("severity" in e for e in errs)
    assert any("counts.findings" in e for e in errs)


# --------------------------------------------------------------------- CLI --
def _no_sup(tmp_path):
    return str(tmp_path / "empty_suppressions.txt")


def test_cli_exit_findings_on_bad_repo(tmp_path, capsys):
    rc = cli.main(["--root", str(FIX / "marker_repo_bad"),
                   "--pass", "slow-marker",
                   "--suppressions", _no_sup(tmp_path)])
    assert rc == cli.EXIT_FINDINGS
    out = capsys.readouterr().out
    assert "slow-marker" in out and "1 finding(s)" in out


def test_cli_exit_clean_on_clean_repo(tmp_path, capsys):
    rc = cli.main(["--root", str(FIX / "marker_repo_clean"),
                   "--pass", "slow-marker",
                   "--suppressions", _no_sup(tmp_path)])
    assert rc == cli.EXIT_CLEAN
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_exit_internal_when_a_pass_cannot_run(tmp_path, capsys):
    # jaxpr passes refuse to run against a repo without the real package —
    # that's an internal error (exit 2), never a clean bill of health.
    rc = cli.main(["--root", str(FIX / "marker_repo_bad"),
                   "--pass", "jaxpr-donation",
                   "--suppressions", _no_sup(tmp_path)])
    assert rc == cli.EXIT_INTERNAL
    assert "[internal]" in capsys.readouterr().out


def test_cli_exit_usage_on_unknown_pass(tmp_path, capsys):
    rc = cli.main(["--root", str(ROOT), "--pass", "no-such-pass",
                   "--suppressions", _no_sup(tmp_path)])
    assert rc == cli.EXIT_USAGE


def test_cli_exit_usage_on_bad_flag():
    with pytest.raises(SystemExit) as exc:
        cli.main(["--no-such-flag"])
    assert exc.value.code == cli.EXIT_USAGE


def test_cli_list_passes(capsys):
    assert cli.main(["--list-passes"]) == cli.EXIT_CLEAN
    out = capsys.readouterr().out
    for pid in registry.PASSES:
        assert pid in out


def test_cli_json_report_is_valid(tmp_path, capsys):
    report_path = tmp_path / "report.json"
    rc = cli.main(["--root", str(FIX / "marker_repo_bad"),
                   "--pass", "slow-marker",
                   "--suppressions", _no_sup(tmp_path),
                   "--json", str(report_path), "--format", "json"])
    assert rc == cli.EXIT_FINDINGS
    stdout_report = json.loads(capsys.readouterr().out)
    file_report = json.loads(report_path.read_text())
    assert validate_report(file_report) == []
    assert file_report == stdout_report
    assert file_report["counts"]["findings"] == 1
    assert file_report["passes_run"] == ["slow-marker"]


# ----------------------------------------------------------- changed mode --
def test_git_changed_files_sees_modified_and_untracked(tmp_path):
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True)
    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    (tmp_path / "a.py").write_text("x = 1\n")
    git("add", "a.py")
    git("commit", "-qm", "seed")
    (tmp_path / "a.py").write_text("x = 2\n")
    (tmp_path / "b.py").write_text("y = 1\n")
    assert git_changed_files(tmp_path) == {"a.py", "b.py"}


def test_changed_mode_skips_unanchored_repo_passes():
    parser = cli.build_parser()
    args = parser.parse_args(["--changed"])
    # An unrelated file: anchored repo-wide passes (config/telemetry/
    # slow-marker) drop out, per-file passes and jaxpr stay filtered too.
    ids = {p.pass_id for p in cli.select_passes(args, {"some/other.py"})}
    assert "config-knob-coverage" not in ids
    assert "telemetry-kind-coverage" not in ids
    assert "jaxpr-donation" not in ids  # jaxpr is opt-in under --changed
    assert "raw-collective" in ids      # per-file: self-restricts
    # Touching an anchor pulls the repo-wide pass back in.
    ids = {p.pass_id for p in cli.select_passes(args, {"docs/CONFIG.md"})}
    assert "config-knob-coverage" in ids


def test_changed_mode_skips_trace_layers_unless_trace_flag():
    """--changed drops the jaxpr/hlo trace passes (seconds of compile
    time) with an explicit skip list; --trace opts them back in."""
    parser = cli.build_parser()
    step = {"distributed_tensorflow_framework_tpu/train/step.py"}
    args = parser.parse_args(["--changed"])
    ids = {p.pass_id for p in cli.select_passes(args, step)}
    assert "jaxpr-donation" not in ids
    assert "hlo-donation-survival" not in ids
    skipped = {p.pass_id for p in cli.skipped_trace_passes(args, step)}
    assert {"jaxpr-donation", "hlo-donation-survival"} <= skipped
    # Unanchored change: nothing relevant was skipped, no notice.
    assert cli.skipped_trace_passes(args, {"docs/README.md"}) == []
    args = parser.parse_args(["--changed", "--trace"])
    ids = {p.pass_id for p in cli.select_passes(args, step)}
    assert {"jaxpr-donation", "hlo-donation-survival"} <= ids
    assert cli.skipped_trace_passes(args, step) == []


def test_changed_mode_restricts_per_file_scan():
    ctx = RepoContext(ROOT, changed=set())
    assert ast_passes.raw_collective_pass(ctx) == []
    assert ast_passes.typed_errors_pass(ctx) == []


# -------------------------------------------------------------- self-audit --
def test_self_audit_repo_is_clean_ast_layer():
    """Tier-1 gate: every AST pass over this checkout, real suppression
    file applied — zero unsuppressed findings, zero internal errors, and
    the suppression file itself parses clean."""
    ctx = RepoContext(ROOT)
    findings = []
    for info in registry.passes_for_layer(registry.LAYER_AST):
        findings.extend(info.fn(ctx))
    sups, parse_findings = load_suppressions(cli.DEFAULT_SUPPRESSIONS)
    assert parse_findings == [], [f.message for f in parse_findings]
    apply_suppressions(findings, sups)
    active = [f for f in findings if not f.suppressed]
    assert active == [], [(f.pass_id, f.where, f.message) for f in active]


def test_self_audit_cli_full_run_is_clean():
    """End-to-end acceptance: the shipped entry point, all layers, exit 0.
    Subprocess so the env-pinning in scripts/graftcheck.py is exercised."""
    res = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "graftcheck.py")],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 finding(s)" in res.stdout
