"""graftcheck concurrency-contract passes (thread-lifecycle,
lock-discipline) — fixture fire/silent proofs plus regression pins for
the real fixes the passes flushed out of the threaded modules (ISSUE 12):

  * every background thread in the package now carries a ``dtf-*`` name
    (serve batcher/reporter/drain, infeed prefetch/pull);
  * the serve reporter thread funnels failures into the typed
    ``ServeReporterError`` surfaced on ``drain()``;
  * a failed SIGTERM drain surfaces as ``ServeDrainError`` from
    ``serve_forever()`` instead of hanging the process with the error
    lost to a daemon thread.

These are pinned HERE, not suppressed — the shipped suppression file
carries no thread-lifecycle/lock-discipline entries.
"""

import ast
import pathlib
import signal
import threading
import time

import pytest

from tools.graftcheck import cli
from tools.graftcheck.concurrency_passes import (
    scan_lock_discipline,
    scan_thread_lifecycle,
)
from tools.graftcheck.findings import load_suppressions

ROOT = pathlib.Path(__file__).resolve().parent.parent
SNIP = pathlib.Path(__file__).resolve().parent / "graftcheck_fixtures" / "snippets"
PKG = ROOT / "distributed_tensorflow_framework_tpu"

THREADED_MODULES = (
    "ckpt/async_saver.py",
    "serve/engine.py",
    "serve/server.py",
    "data/infeed.py",
    "core/telemetry.py",
    "core/goodput.py",
    "core/faults.py",
)


def _tree(path: pathlib.Path) -> ast.Module:
    return ast.parse(path.read_text(), filename=str(path))


# --------------------------------------------------------- thread-lifecycle --
def test_thread_lifecycle_fires_on_bad_fixture():
    findings = scan_thread_lifecycle(
        "snip.py", _tree(SNIP / "thread_lifecycle_bad.py"))
    msgs = " ".join(f.message for f in findings)
    # One finding per broken rule, several threads tripping the funnel:
    assert "without name=" in msgs
    assert "not statically resolvable" in msgs
    assert "lacks the 'dtf-' prefix" in msgs
    assert "neither daemon=True nor joined" in msgs
    assert "does not funnel" in msgs
    assert "ThreadPoolExecutor needs thread_name_prefix" in msgs
    assert len(findings) >= 6, [f.message for f in findings]


def test_thread_lifecycle_silent_on_clean_fixture():
    findings = scan_thread_lifecycle(
        "snip.py", _tree(SNIP / "thread_lifecycle_clean.py"))
    assert findings == [], [f.message for f in findings]


# ---------------------------------------------------------- lock-discipline --
def test_lock_discipline_fires_on_bad_fixture():
    findings = scan_lock_discipline(
        "snip.py", _tree(SNIP / "lock_discipline_bad.py"))
    msgs = [f.message for f in findings]
    # Racy.count: two bare write sites (bg + API); Lockless.total: one
    # class-level no-lock finding.
    assert len(findings) == 3, msgs
    assert sum("Racy.count" in m and "outside" in m for m in msgs) == 2
    assert sum("Lockless.total" in m and "owns no lock" in m
               for m in msgs) == 1


def test_lock_discipline_silent_on_clean_fixture():
    findings = scan_lock_discipline(
        "snip.py", _tree(SNIP / "lock_discipline_clean.py"))
    assert findings == [], [f.message for f in findings]


# ----------------------------------------- regression pins for the real fixes --
@pytest.mark.parametrize("rel", THREADED_MODULES)
def test_threaded_module_passes_both_contracts(rel):
    """The seven threaded modules are clean under BOTH passes with no
    suppressions — this pins the dtf-* renames and the exception funnels
    (pre-fix serve/engine.py, serve/server.py and data/infeed.py all
    produced findings)."""
    tree = _tree(PKG / rel)
    tl = scan_thread_lifecycle(rel, tree)
    ld = scan_lock_discipline(rel, tree)
    assert tl == [], [f.message for f in tl]
    assert ld == [], [f.message for f in ld]


def test_no_concurrency_suppressions_shipped():
    sups, _ = load_suppressions(cli.DEFAULT_SUPPRESSIONS)
    assert not any(s.pass_id in ("thread-lifecycle", "lock-discipline")
                   for s in sups)


def test_thread_names_are_the_documented_ones():
    """The exact dtf-* names, greppable in a thread dump."""
    src = (PKG / "serve" / "engine.py").read_text()
    assert '"dtf-serve-batcher"' in src
    assert '"dtf-serve-reporter"' in src
    assert '"dtf-serve-drain"' in (PKG / "serve" / "server.py").read_text()
    infeed = (PKG / "data" / "infeed.py").read_text()
    assert '"dtf-infeed-prefetch"' in infeed
    assert '"dtf-infeed-pull"' in infeed


class _FailingEngine:
    """Minimal engine whose drain always fails."""

    def stats(self):
        return {"queue_depth": 0}

    def drain(self, timeout):
        raise RuntimeError("seeded drain failure")


def test_failed_sigterm_drain_surfaces_instead_of_hanging():
    """Pre-fix, a drain-thread failure left serve_forever() blocked
    forever (httpd.shutdown() never ran, _done never set) with the error
    on a daemon thread's stderr. Now it must surface as ServeDrainError
    from serve_forever() within the join budget."""
    from distributed_tensorflow_framework_tpu.core.config import ServeConfig
    from distributed_tensorflow_framework_tpu.serve.server import (
        ServeDrainError,
        ServingServer,
    )

    cfg = ServeConfig(port=0, drain_timeout_s=1.0)
    server = ServingServer(_FailingEngine(), cfg)
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    raised: list[BaseException] = []

    def run():
        try:
            server.serve_forever()
        except BaseException as e:  # noqa: BLE001 — the assertion target
            raised.append(e)

    t = threading.Thread(target=run, daemon=True, name="dtf-test-serve")
    try:
        server.install_sigterm_drain()
        t.start()
        time.sleep(0.2)
        signal.raise_signal(signal.SIGTERM)  # handler runs on this thread
        t.join(timeout=15)
        assert not t.is_alive(), \
            "serve_forever still blocked after a failed drain"
        assert len(raised) == 1 and isinstance(raised[0], ServeDrainError)
        assert isinstance(raised[0].__cause__, RuntimeError)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        server.httpd.server_close()
