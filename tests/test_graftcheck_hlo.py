"""graftcheck compiled-HLO layer against the REAL compiled artifacts.

Acceptance pins from ISSUE 12 — each pass catches its seeded regression:

  * ``hlo-reshard-census`` — compiling a program whose shardings force a
    GSPMD-inserted collective (a matmul contracting over a sharded dim,
    i.e. a dropped/wrong sharding constraint) produces the finding with
    shape/bytes/sharding detail; the aligned twin is silent; the real
    probes and the serve forward are clean.
  * ``hlo-donation-survival`` — compiling the same step WITHOUT
    ``donate_argnums`` drops the executable's input_output_alias table
    and the audit fires; the real compiled step keeps one alias per
    state leaf.
  * ``hlo-memory-budget`` — the shrunken/inflated fixture budget trips
    both sides of the tolerance band against a fixed analysis dict; the
    checked-in configs/hlo_budgets.json gates the real programs clean.

Compiled artifacts are memoized per process (hlo_passes._COMPILED_CACHE
over jaxpr_passes._PROBE_CACHE), so these tests and the tier-1
self-audit share the compile work.
"""

import importlib.util
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftcheck import cli, hlo_passes as hp, jaxpr_passes as jp, registry
from tools.graftcheck.context import RepoContext
from tools.graftcheck.findings import apply_suppressions, load_suppressions

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIX = pathlib.Path(__file__).resolve().parent / "graftcheck_fixtures"


def _snippets():
    spec = importlib.util.spec_from_file_location(
        "graftcheck_hlo_snippets", FIX / "hlo_snippets.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ctx(devices):
    return RepoContext(ROOT)


def _mesh_1d(devices):
    return Mesh(np.array(devices).reshape(8), ("data",))


# ------------------------------------------------------------- HLO parsing --
def test_shape_bytes_reads_tuples_and_dtypes():
    assert hp.shape_bytes("f32[64,128]{1,0}") == 64 * 128 * 4
    assert hp.shape_bytes("(bf16[8,4], s8[16])") == 8 * 4 * 2 + 16
    assert hp.shape_bytes("token[]") == 0


def test_collect_collectives_counts_async_pairs_once():
    text = (
        "  %ag-start = f32[8]{0} all-gather-start(f32[1]{0} %p), dims={0}\n"
        "  %ag-done = f32[8]{0} all-gather-done(f32[8]{0} %ag-start)\n"
        "  ROOT %ar = f32[8]{0} all-reduce(f32[8]{0} %x), to_apply=%add\n")
    instrs = hp.collect_collectives(text)
    assert [i["op"] for i in instrs] == ["all-gather", "all-reduce"]


# ---------------------------------------------------------- reshard census --
def test_reshard_census_fires_on_seeded_sharding_mismatch(devices):
    """The seeded regression: contracting a matmul over a sharded dim —
    what dropping the step's sharding constraint does — forces GSPMD to
    insert an all-reduce the jaxpr never declared."""
    snip = _snippets()
    mesh = _mesh_1d(devices)
    xs = jax.ShapeDtypeStruct((64, 256), jnp.float32,
                              sharding=NamedSharding(mesh, P(None, "data")))
    ws = jax.ShapeDtypeStruct((256, 128), jnp.float32,
                              sharding=NamedSharding(mesh, P("data", None)))
    text = jax.jit(snip.reshard_bad).lower(xs, ws).compile().as_text()
    instrs = hp.collect_collectives(text)
    assert any(i["op"] == "all-reduce" for i in instrs), instrs
    findings = hp.audit_reshard_census("seeded", instrs, {})
    assert len(findings) == 1
    msg = findings[0].message
    assert "GSPMD inserted" in msg
    assert "f32[64,128]" in msg and "32768 bytes" in msg


def test_reshard_census_silent_on_aligned_twin(devices):
    snip = _snippets()
    mesh = _mesh_1d(devices)
    a = jax.ShapeDtypeStruct((64, 256), jnp.float32,
                             sharding=NamedSharding(mesh, P("data", None)))
    text = jax.jit(snip.reshard_clean).lower(a, a).compile().as_text()
    instrs = hp.collect_collectives(text)
    assert instrs == []
    assert hp.audit_reshard_census("clean", instrs, {}) == []


def test_reshard_census_tolerates_fused_and_decomposed_collectives():
    # XLA lowered all all_to_alls away on CPU (q8 probe) — fewer than
    # declared must NOT fire; only extras are reshards.
    assert hp.audit_reshard_census(
        "x", [], {"all-to-all": 20, "all-gather": 20}) == []


def test_reshard_census_pass_clean_on_real_probes(ctx):
    findings = hp.reshard_census_pass(ctx)
    assert findings == [], [(f.where, f.message) for f in findings]


def test_serve_forward_compiles_with_zero_collectives(ctx):
    """Replicated params over the dp serving mesh: nothing to reshard."""
    compiled = hp.get_compiled(ctx, "serve")
    assert hp.collect_collectives(compiled["text"]) == []
    assert compiled["analysis"] is not None


# -------------------------------------------------------- donation survival --
def test_donation_survives_to_the_compiled_executable(ctx):
    for name in hp.DONATION_PROBES:
        probe = jp.get_probe(ctx, name)
        entries = hp.count_alias_entries(hp.get_compiled(ctx, name)["text"])
        assert entries >= probe["n_state_leaves"] > 0, \
            (name, entries, probe["n_state_leaves"])


def test_donation_survival_catches_seeded_regression(ctx):
    """Compile (not just lower) the same step WITHOUT donate_argnums: the
    executable's input_output_alias table vanishes and the audit fires."""
    probe = jp.get_probe(ctx, "jit_f32")
    undonated = jax.jit(probe["builder"]._train_step_jit)
    text = undonated.lower(
        probe["state_shapes"], probe["batch"]).compile().as_text()
    entries = hp.count_alias_entries(text)
    assert entries < probe["n_state_leaves"]
    findings = hp.audit_donation_survival(
        entries, probe["n_state_leaves"], "hlo:seeded_no_donate")
    assert len(findings) == 1
    assert "died in lowering" in findings[0].message


def test_donation_survival_pass_clean_on_real_step(ctx):
    findings = hp.donation_survival_pass(ctx)
    assert findings == [], [(f.where, f.message) for f in findings]


def test_fused_update_keeps_at_least_the_unfused_aliases(ctx):
    """precision.fused_update moves the optax apply into the bucketed
    walk — the whole point is each param is read-modified-written once,
    which only holds if donation survives: the fused executable must
    alias at least as many input-output pairs as the unfused ZeRO step."""
    fused = hp.count_alias_entries(
        hp.get_compiled(ctx, "shard_zero_fused")["text"])
    unfused = hp.count_alias_entries(
        hp.get_compiled(ctx, "shard_zero")["text"])
    assert fused >= unfused > 0, (fused, unfused)


def test_bf16_policy_budget_rides_next_to_its_f32_twin(ctx):
    """The regenerated budgets pin the bf16-policy program alongside the
    f32 twin. The state (args/outputs) is identical — masters stay f32 —
    so any drift between the twins lives in temp bytes, where activation
    width shows up. (On this CPU gate backend float normalization stages
    bf16 math through f32 copies, so bf16 temp reads HIGHER — see the
    BUDGET_PROGRAMS note; the entry still gates the bf16 program against
    its own regressions.)"""
    budgets = hp.load_budgets(hp.budgets_path(ctx))
    f32 = budgets["programs"]["train_step:jit_f32"]
    b16 = budgets["programs"]["train_step:jit_bf16_policy"]
    assert b16["argument_bytes"] == f32["argument_bytes"]
    assert b16["output_bytes"] == f32["output_bytes"]
    assert b16["temp_bytes"] != f32["temp_bytes"]


# ----------------------------------------------------------- memory budget --
_FAKE_ANALYSIS = {
    "argument_bytes": 1000000,
    "output_bytes": 500000,
    "temp_bytes": 750000,
    "peak_bytes_est": 2000000,
}


def _fixture_entry(which: str) -> dict:
    data = json.loads((FIX / f"hlo_budgets_{which}.json").read_text())
    assert data["schema"] == hp.BUDGETS_SCHEMA
    return data["programs"]["train_step:fixture"]


def test_budget_audit_fires_on_seeded_regression_and_staleness():
    findings = hp.audit_budget_entry(
        "train_step:fixture", _FAKE_ANALYSIS, _fixture_entry("bad"),
        tolerance=0.1)
    msgs = [f.message for f in findings]
    assert len(findings) == 2, msgs
    # peak shrunk below actual → regression; output inflated → stale.
    assert any("peak_bytes_est regressed" in m for m in msgs)
    assert any("output_bytes budget is stale" in m for m in msgs)


def test_budget_audit_silent_on_clean_twin():
    findings = hp.audit_budget_entry(
        "train_step:fixture", _FAKE_ANALYSIS, _fixture_entry("clean"),
        tolerance=0.1)
    assert findings == [], [f.message for f in findings]


def test_budget_band_has_an_absolute_floor():
    # A 10-byte program must not flap on a 12-byte wobble.
    entry = {f: 10 for f in hp.BUDGET_FIELDS}
    analysis = {f: 22 for f in hp.BUDGET_FIELDS}
    assert hp.audit_budget_entry("x", analysis, entry, tolerance=0.1) == []


def test_missing_budgets_file_is_an_internal_error(tmp_path):
    ctx = RepoContext(tmp_path)
    findings = hp.memory_budget_pass(ctx)
    assert len(findings) == 1
    assert findings[0].severity == "internal-error"
    assert "--update-budgets" in findings[0].message


def test_jax_version_drift_asks_for_regeneration_not_noise(tmp_path):
    path = tmp_path / "configs" / "hlo_budgets.json"
    path.parent.mkdir()
    data = json.loads((FIX / "hlo_budgets_clean.json").read_text())
    path.write_text(json.dumps(data))
    findings = hp.memory_budget_pass(RepoContext(tmp_path))
    assert len(findings) == 1  # one notice, not one per program/field
    assert "jax fixture" in findings[0].message
    assert "--update-budgets" in findings[0].message


def test_checked_in_budgets_gate_the_real_programs_clean(ctx):
    """The committed configs/hlo_budgets.json covers every budgeted
    program with a matching probe-config digest and passes the gate."""
    budgets = hp.load_budgets(hp.budgets_path(ctx))
    assert set(budgets["programs"]) == set(hp.BUDGET_PROGRAMS)
    for program, probe_name in hp.BUDGET_PROGRAMS.items():
        assert budgets["programs"][program]["config_sha256"] == \
            hp.probe_config_digest(probe_name), program
    findings = hp.memory_budget_pass(ctx)
    assert findings == [], [(f.where, f.message) for f in findings]


def test_update_budgets_round_trips(ctx, tmp_path):
    out = tmp_path / "budgets.json"
    hp.write_budgets(ctx, out)
    written = hp.load_budgets(out)
    assert written["provenance"]["jax"] == jax.__version__
    assert set(written["programs"]) == set(hp.BUDGET_PROGRAMS)
    for program in hp.BUDGET_PROGRAMS:
        entry = written["programs"][program]
        findings = hp.audit_budget_entry(
            program, entry, entry, written["tolerance_frac"])
        assert findings == []


# -------------------------------------------------------------- self-audit --
def test_registry_advertises_the_hlo_layer():
    hlo = registry.passes_for_layer(registry.LAYER_HLO)
    assert {p.pass_id for p in hlo} == {
        "hlo-reshard-census", "hlo-donation-survival", "hlo-memory-budget"}
    assert registry.LAYER_HLO in registry.TRACE_LAYERS


def test_self_audit_hlo_layer_clean(ctx):
    findings = []
    for info in registry.passes_for_layer(registry.LAYER_HLO):
        findings.extend(info.fn(ctx))
    sups, _ = load_suppressions(cli.DEFAULT_SUPPRESSIONS)
    apply_suppressions(findings, sups)
    active = [f for f in findings if not f.suppressed]
    assert active == [], [(f.pass_id, f.where, f.message) for f in active]
