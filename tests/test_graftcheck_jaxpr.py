"""graftcheck jaxpr-layer audits against the REAL train step.

Acceptance pins from ISSUE 11:

  * the donation audit catches a seeded regression — lowering the same
    step WITHOUT ``donate_argnums`` must produce the finding;
  * the collective census exactly matches the CollectiveTally rows for a
    dp×fsdp shard_map step (and the q8/ZeRO probes), in both directions;
  * the f32-upcast audit flags exactly the deliberate f32 logits head
    (covered by the shipped suppressions) and nothing else.

Probes are memoized in tools/graftcheck/jaxpr_passes._PROBE_CACHE, so
these tests and the tier-1 self-audit trace each configuration once.
"""

import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import pytest

from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from tools.graftcheck import cli, jaxpr_passes as jp, registry
from tools.graftcheck.context import RepoContext
from tools.graftcheck.findings import apply_suppressions, load_suppressions

ROOT = pathlib.Path(__file__).resolve().parent.parent
SNIPPETS_PATH = (pathlib.Path(__file__).resolve().parent
                 / "graftcheck_fixtures" / "jaxpr_snippets.py")


def _snippets():
    spec = importlib.util.spec_from_file_location(
        "graftcheck_jaxpr_snippets", SNIPPETS_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ctx(devices):
    return RepoContext(ROOT)


# ---------------------------------------------------------------- donation --
def test_donation_pass_clean_on_real_step(ctx):
    findings = jp.donation_pass(ctx)
    assert findings == [], [f.message for f in findings]
    probe = jp.get_probe(ctx, "jit_f32")
    # The audit is counting something real: one alias per state leaf.
    assert probe["n_state_leaves"] > 0
    assert probe["alias_count"] >= probe["n_state_leaves"]


def test_donation_audit_catches_seeded_regression(ctx):
    """Re-jit the SAME underlying step function without donate_argnums:
    the aliasing markers vanish from the lowered text and the audit must
    produce the finding. This is the proof the pass would catch someone
    dropping donate_argnums=(0,) from train/step.py."""
    probe = jp.get_probe(ctx, "jit_f32")
    undonated = jax.jit(probe["builder"]._train_step_jit)
    text = undonated.lower(probe["state_shapes"], probe["batch"]).as_text()
    alias_count = jp.count_output_aliases(text)
    assert alias_count < probe["n_state_leaves"]
    findings = jp.audit_donation(alias_count, probe["n_state_leaves"],
                                 "trace:seeded_no_donate")
    assert len(findings) == 1
    assert "donor-aliased" in findings[0].message
    assert "donate_argnums" in findings[0].message


# -------------------------------------------------------------- f32 upcast --
def test_upcast_audit_fires_on_bad_snippet(devices):
    snip = _snippets()
    x = jnp.zeros((8, 16), jnp.bfloat16)
    w = jnp.zeros((16, 4), jnp.bfloat16)
    hits = jp.collect_upcasts(jax.make_jaxpr(snip.upcast_bad)(x, w))
    assert hits, "bf16→f32 widening feeding a dot must be detected"
    assert all(prim == "dot_general" for prim, _ in hits)


def test_upcast_audit_silent_on_clean_snippet(devices):
    snip = _snippets()
    x = jnp.zeros((8, 16), jnp.bfloat16)
    w = jnp.zeros((16, 4), jnp.bfloat16)
    assert jp.collect_upcasts(jax.make_jaxpr(snip.upcast_clean)(x, w)) == []


def test_upcast_pass_flags_only_the_f32_logits_head(ctx):
    """On the real bf16 step every finding is the deliberate f32 logits
    head — with op provenance naming the layer — and the shipped
    suppression file covers all of them."""
    findings = jp.f32_upcast_pass(ctx)
    assert findings, "the bf16 probe must see the known f32 logits head"
    assert all("logits" in f.where for f in findings), \
        [(f.where, f.message) for f in findings]
    sups, _ = load_suppressions(cli.DEFAULT_SUPPRESSIONS)
    apply_suppressions(findings, sups)
    assert all(f.suppressed for f in findings)


def test_policy_fixture_bad_mid_network_widening_detected(devices):
    """Violating fixture for the bf16-policy probe: a hidden (non-logits)
    matmul widened to f32 must be flagged."""
    snip = _snippets()
    x = jnp.zeros((8, 16), jnp.bfloat16)
    wh = jnp.zeros((16, 16), jnp.bfloat16)
    wl = jnp.zeros((16, 4), jnp.bfloat16)
    hits = jp.collect_upcasts(jax.make_jaxpr(snip.policy_upcast_bad)(x, wh, wl))
    assert hits, "mid-network bf16→f32 widening must be detected"
    assert all(prim == "dot_general" for prim, _ in hits)


def test_policy_fixture_clean_preferred_accum_not_flagged(devices):
    """Clean twin: bf16 operands with f32 MXU accumulation carry no
    convert op — nothing to flag."""
    snip = _snippets()
    x = jnp.zeros((8, 16), jnp.bfloat16)
    wh = jnp.zeros((16, 16), jnp.bfloat16)
    wl = jnp.zeros((16, 4), jnp.bfloat16)
    assert jp.collect_upcasts(
        jax.make_jaxpr(snip.policy_upcast_clean)(x, wh, wl)) == []


def test_bf16_policy_probe_overrides_f32_model_dtype(ctx):
    """The jit_bf16_policy probe keeps model.dtype=float32 and flips the
    compute dtype purely through precision.activation_dtype — its trace
    must show the same deliberate f32 logits-head widening the explicit
    bf16 model does (an all-f32 trace would mean the policy override was
    silently dropped)."""
    probe = jp.get_probe(ctx, "jit_bf16_policy")
    assert str(probe["config"].model.dtype) == "float32"
    assert probe["config"].precision.activation_dtype == "bf16"
    hits = jp.collect_upcasts(probe["jaxpr"])
    assert hits, "policy override dropped: no bf16 compute in the trace"
    assert all("logits" in stack for _, stack in hits), hits


# --------------------------------------------------------- collective census --
def _mesh_1d(devices):
    import numpy as np
    return jax.sharding.Mesh(np.array(devices).reshape(8), ("data",))


def _census_of(fn, devices):
    from jax.sharding import PartitionSpec as P
    # check_vma=False to match the trainer's shard_map usage — with vma
    # tracking on, jax rewrites psum to a different primitive family.
    mapped = coll.shard_map(fn, mesh=_mesh_1d(devices),
                            in_specs=(P("data"),), out_specs=P(),
                            check_vma=False)
    with coll.tally() as t:
        jx = jax.make_jaxpr(mapped)(jnp.zeros((8, 4), jnp.float32))
    return jp.collective_census(jx), jp.expected_census(dict(t.calls))


def test_census_fixture_bad_raw_psum_is_a_mismatch(devices):
    snip = _snippets()
    actual, (expected, unknown) = _census_of(snip.census_bad, devices)
    assert unknown == []
    assert actual.get("psum", 0) > expected.get("psum", 0), (actual, expected)


def test_census_fixture_clean_wrapper_matches(devices):
    snip = _snippets()
    actual, (expected, unknown) = _census_of(snip.census_clean, devices)
    assert unknown == []
    assert actual == expected and actual.get("psum") == 1


def test_census_matches_tally_for_dp_fsdp_step(ctx):
    """ISSUE 11 acceptance: exact two-way census match for the explicit
    dp=4 × fsdp=2 shard_map step, with the known composition pinned."""
    probe = jp.get_probe(ctx, "shard_dp_fsdp")
    actual = jp.collective_census(probe["jaxpr"])
    expected, unknown = jp.expected_census(probe["tally_calls"])
    assert unknown == []
    assert actual == expected, (actual, expected)
    calls = probe["tally_calls"]
    assert calls["allreduce_grads_pmean"] > 0    # grad sync-DP reduce
    assert calls["all_gather"] > 0               # fsdp param gathers
    assert actual["psum"] == (calls["allreduce_grads_pmean"]
                              + calls["pmean"])
    assert actual["all_gather"] == calls["all_gather"]


def test_census_q8_wire_honesty(ctx):
    """int8+error-feedback probe: each q8 scatter is TWO all_to_all ops
    on the wire (payload + block scales) and each q8 gather TWO
    all_gather ops — the tally's byte accounting rides exactly that."""
    probe = jp.get_probe(ctx, "shard_q8_ef")
    actual = jp.collective_census(probe["jaxpr"])
    expected, unknown = jp.expected_census(probe["tally_calls"])
    assert unknown == []
    assert actual == expected, (actual, expected)
    calls = probe["tally_calls"]
    assert calls["allreduce_grads_q8_scatter"] > 0
    assert calls["allreduce_grads_q8_gather"] > 0
    assert actual["all_to_all"] == 2 * calls["allreduce_grads_q8_scatter"]
    assert actual["all_gather"] == 2 * calls["allreduce_grads_q8_gather"]


def test_census_zero_probe_accounts_for_the_grad_norm_psum(ctx):
    """Regression pin for the untallied lax.psum the census flushed out of
    zero.shard_global_norm: the ZeRO probe's grad-norm psum must now have
    a tally row, and the whole step must census-match."""
    probe = jp.get_probe(ctx, "shard_zero")
    actual = jp.collective_census(probe["jaxpr"])
    expected, unknown = jp.expected_census(probe["tally_calls"])
    assert unknown == []
    assert actual == expected, (actual, expected)
    calls = probe["tally_calls"]
    assert calls["zero_reduce_scatter"] > 0
    assert calls["zero_all_gather"] > 0
    assert calls.get("psum", 0) >= 1  # shard_global_norm, now tallied


def test_census_fused_update_keeps_the_wire_identical(ctx):
    """precision.fused_update moves the optax apply into the bucketed
    walk — it must change WHERE the update runs, not what goes on the
    wire: identical tally kinds and counts to the unfused ZeRO probe,
    and a clean two-way census."""
    fused = jp.get_probe(ctx, "shard_zero_fused")
    unfused = jp.get_probe(ctx, "shard_zero")
    actual = jp.collective_census(fused["jaxpr"])
    expected, unknown = jp.expected_census(fused["tally_calls"])
    assert unknown == []
    assert actual == expected, (actual, expected)
    assert fused["tally_calls"] == unfused["tally_calls"], (
        fused["tally_calls"], unfused["tally_calls"])


# -------------------------------------------------------------- self-audit --
def test_self_audit_jaxpr_layer_clean(ctx):
    findings = []
    for info in registry.passes_for_layer(registry.LAYER_JAXPR):
        findings.extend(info.fn(ctx))
    sups, _ = load_suppressions(cli.DEFAULT_SUPPRESSIONS)
    apply_suppressions(findings, sups)
    active = [f for f in findings if not f.suppressed]
    assert active == [], [(f.pass_id, f.where, f.message) for f in active]
