"""ImageNet TFRecord pipeline (data/imagenet.py) against real records.

Builds a tiny TFRecord shard set of synthetic JPEGs (the reference's input
format) and drives the actual decode→augment→batch path — the synthetic
fallback covers everything else, so without this the TFRecord branch would
ship untested.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_framework_tpu.core.config import DataConfig  # noqa: E402
from distributed_tensorflow_framework_tpu.data.imagenet import make_imagenet  # noqa: E402


from conftest import write_imagenet_records  # noqa: E402


@pytest.fixture(scope="module")
def record_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("imagenet"))
    write_imagenet_records(root, split="train")
    write_imagenet_records(root, split="validation")
    return root


def _cfg(root: str) -> DataConfig:
    return DataConfig(name="imagenet", data_dir=root, global_batch_size=8,
                      image_size=32, shuffle_buffer=16, seed=7,
                      num_classes=1000)  # fixture labels are 1..n ids


def test_tfrecord_decode_augment_batch(record_dir):
    ds = make_imagenet(_cfg(record_dir), 0, 1, train=True)
    batch = next(ds)
    assert batch["image"].shape == (8, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (8,)
    # Labels shifted [1,1000] → [0,999].
    assert batch["label"].min() >= 0 and batch["label"].max() < 1000
    # Standardized pixels: roughly zero-centered, not raw [0,255].
    assert abs(float(np.asarray(batch["image"], np.float32).mean())) < 3.0


def test_tfrecord_determinism_and_resume(record_dir):
    ds1 = make_imagenet(_cfg(record_dir), 0, 1, train=True)
    a0 = next(ds1)
    a1 = next(ds1)

    # Fresh pipeline, same seed → identical stream.
    ds2 = make_imagenet(_cfg(record_dir), 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(
        np.asarray(a0["image"], np.float32), np.asarray(b0["image"], np.float32)
    )

    # Snapshot after one batch, restore into a fresh pipeline → replays the
    # SECOND batch (the skip-count resume contract).
    state = ds2.state()
    ds3 = make_imagenet(_cfg(record_dir), 0, 1, train=True)
    ds3.restore(state)
    c1 = next(ds3)
    np.testing.assert_array_equal(
        np.asarray(a1["image"], np.float32), np.asarray(c1["image"], np.float32)
    )


def test_tfrecord_bf16_output(record_dir):
    import ml_dtypes

    cfg = _cfg(record_dir)
    cfg.image_dtype = "bfloat16"
    ds = make_imagenet(cfg, 0, 1, train=True)
    batch = next(ds)
    assert batch["image"].dtype == np.dtype(ml_dtypes.bfloat16)


def test_tfrecord_eval_transform(record_dir):
    ds = make_imagenet(_cfg(record_dir), 0, 1, train=False)
    batch = next(ds)
    assert batch["image"].shape == (8, 32, 32, 3)


def test_eval_covers_every_record_once(record_dir):
    # 16 validation records, batch 5 → 4 batches, last padded to 5 with
    # weight 0 (exact-eval contract: one pass, every record once).
    cfg = _cfg(record_dir)
    cfg.global_batch_size = 5
    ds = make_imagenet(cfg, 0, 1, train=False)
    assert ds.cardinality == 4  # ceil(16/5)
    batches = list(ds)
    assert len(batches) == 4
    total = sum(float(b["weight"].sum()) for b in batches)
    assert total == 16
    # Labels covered exactly once: the writer assigns sequential labels
    # (n%1000)+1 for n=1..16, shifted to [0,999] → 1..16 after -1... i.e.
    # stored 2..17, shifted 1..16.
    labels = np.concatenate(
        [b["label"][b["weight"] > 0] for b in batches]
    )
    assert sorted(labels.tolist()) == list(range(1, 17))
    with pytest.raises(StopIteration):
        next(ds)


def test_eval_counts_host_shard_not_total(record_dir):
    # 2 validation files over 2 processes: each host streams ONE file
    # (8 records, batch 5 → 2 batches) — not ceil(16/5)=4 padded batches.
    cfg = _cfg(record_dir)
    cfg.global_batch_size = 10  # per-host b=5 with process_count=2
    ds = make_imagenet(cfg, 0, 2, train=False)
    assert ds.cardinality == 2
    batches = list(ds)
    assert len(batches) == 2
    assert sum(float(b["weight"].sum()) for b in batches) == 8
