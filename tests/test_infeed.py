"""Infeed prefetcher (data/infeed.py): background producer semantics.

The async path must be observably identical to the synchronous one —
same batch order, same snapshot pairing — and must release the dataset
immediately on early close (the consumer may restore/reuse it next).
"""

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig, MeshConfig
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import prefetch_to_device
from distributed_tensorflow_framework_tpu.data.synthetic import synthetic_images


def _ds():
    cfg = DataConfig(name="synthetic_images", global_batch_size=16,
                     image_size=8, channels=1, seed=5)
    return synthetic_images(cfg, 0, 1)


def test_background_matches_sync(devices):
    mesh = create_mesh(MeshConfig(data=8))
    sync_out, async_out = [], []
    for background, out in ((False, sync_out), (True, async_out)):
        ds = _ds()
        it = prefetch_to_device(ds, mesh, size=2, background=background)
        for _ in range(5):
            batch, snap = next(it)
            out.append((np.asarray(batch["image"]), dict(snap)))
        it.close()
    for (a_img, a_snap), (b_img, b_snap) in zip(sync_out, async_out):
        np.testing.assert_array_equal(a_img, b_img)
        assert a_snap == b_snap


def test_background_close_releases_dataset(devices):
    mesh = create_mesh(MeshConfig(data=8))
    ds = _ds()
    it = prefetch_to_device(ds, mesh, size=2, background=True)
    next(it)
    it.close()
    # After close the producer is stopped; restoring and re-pulling from
    # the dataset must be race-free and deterministic.
    ds.restore({"step": 0})
    first = next(ds)
    ds.restore({"step": 0})
    again = next(ds)
    np.testing.assert_array_equal(first["image"], again["image"])


def test_background_propagates_errors(devices):
    mesh = create_mesh(MeshConfig(data=8))

    class Boom:
        element_spec = {}

        def __iter__(self):
            return self

        def __next__(self):
            raise RuntimeError("boom in pipeline")

        def state(self):
            return {}

    it = prefetch_to_device(Boom(), mesh, size=2, background=True)
    import pytest

    with pytest.raises(RuntimeError, match="boom"):
        next(it)


# ------------------------------------------------------- watchdog ----
# deadline_s > 0 arms the infeed watchdog: a pull that exceeds the
# deadline raises InfeedStallError from next() while the pull keeps
# running underneath — retrying resumes the SAME batch (never skipped,
# never re-issued). Exercised here with a controllable stalling dataset;
# the end-to-end Trainer retry rung is drilled in test_recovery_drills.py.

import time

import pytest

from distributed_tensorflow_framework_tpu.data.infeed import InfeedStallError


class _StallingDataset:
    """Yields {"x": full(pull_ordinal)} batches; sleeps on chosen pulls."""

    element_spec = {"x": ((8,), "float32")}

    def __init__(self, stall_on=(), stall_s=0.0):
        self.n = 0
        self.stall_on = set(stall_on)
        self.stall_s = stall_s

    def __iter__(self):
        return self

    def __next__(self):
        self.n += 1
        if self.n in self.stall_on:
            time.sleep(self.stall_s)
        return {"x": np.full((8,), float(self.n), np.float32)}

    def state(self):
        return {"n": self.n}


def _value(item):
    batch, _snap = item
    return float(np.asarray(batch["x"])[0])


def test_sync_watchdog_raises_and_resumes_same_pull(devices):
    mesh = create_mesh(MeshConfig(data=8))
    ds = _StallingDataset(stall_on={1}, stall_s=0.6)
    it = prefetch_to_device(ds, mesh, size=1, deadline_s=0.1)
    t0 = time.monotonic()
    with pytest.raises(InfeedStallError) as ei:
        next(it)
    assert time.monotonic() - t0 < 0.5      # report, not a full wait
    assert ei.value.deadline_s == 0.1
    # The stalled pull is still in flight; once it completes, retries
    # deliver batches 1, 2, 3 in order — nothing skipped or re-pulled.
    time.sleep(0.7)
    assert [_value(next(it)) for _ in range(3)] == [1.0, 2.0, 3.0]
    assert ds.n == 3  # exactly the delivered pulls — none re-issued
    it.close()


def test_sync_watchdog_buffer_covers_stall(devices):
    """A stall with batches still buffered is absorbed, not raised: the
    lookahead exists precisely to ride out short pipeline hiccups."""
    mesh = create_mesh(MeshConfig(data=8))
    ds = _StallingDataset(stall_on={3}, stall_s=0.6)
    it = prefetch_to_device(ds, mesh, size=2, deadline_s=0.1)
    assert _value(next(it)) == 1.0          # fills pulls 1+2, pops 1
    assert _value(next(it)) == 2.0          # pull 3 stalls — swallowed
    with pytest.raises(InfeedStallError):
        next(it)                            # buffer empty: now it surfaces
    time.sleep(0.7)
    assert _value(next(it)) == 3.0          # same pull, resumed
    it.close()


def test_background_watchdog_raises_then_recovers(devices):
    mesh = create_mesh(MeshConfig(data=8))
    ds = _StallingDataset(stall_on={2}, stall_s=0.6)
    it = prefetch_to_device(ds, mesh, size=1, background=True,
                            deadline_s=0.1)
    assert _value(next(it)) == 1.0
    stalls = 0
    deadline = time.monotonic() + 5.0
    while True:
        try:
            got = _value(next(it))
            break
        except InfeedStallError:
            stalls += 1
            assert time.monotonic() < deadline, "stall never cleared"
    assert got == 2.0 and stalls >= 1
    assert _value(next(it)) == 3.0
    it.close()


def test_zero_deadline_disables_watchdog(devices):
    mesh = create_mesh(MeshConfig(data=8))
    ds = _StallingDataset(stall_on={1}, stall_s=0.3)
    it = prefetch_to_device(ds, mesh, size=1, deadline_s=0.0)
    t0 = time.monotonic()
    assert _value(next(it)) == 1.0          # blocks through the stall
    assert time.monotonic() - t0 >= 0.3
    it.close()
