"""Infeed prefetcher (data/infeed.py): background producer semantics.

The async path must be observably identical to the synchronous one —
same batch order, same snapshot pairing — and must release the dataset
immediately on early close (the consumer may restore/reuse it next).
"""

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig, MeshConfig
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import prefetch_to_device
from distributed_tensorflow_framework_tpu.data.synthetic import synthetic_images


def _ds():
    cfg = DataConfig(name="synthetic_images", global_batch_size=16,
                     image_size=8, channels=1, seed=5)
    return synthetic_images(cfg, 0, 1)


def test_background_matches_sync(devices):
    mesh = create_mesh(MeshConfig(data=8))
    sync_out, async_out = [], []
    for background, out in ((False, sync_out), (True, async_out)):
        ds = _ds()
        it = prefetch_to_device(ds, mesh, size=2, background=background)
        for _ in range(5):
            batch, snap = next(it)
            out.append((np.asarray(batch["image"]), dict(snap)))
        it.close()
    for (a_img, a_snap), (b_img, b_snap) in zip(sync_out, async_out):
        np.testing.assert_array_equal(a_img, b_img)
        assert a_snap == b_snap


def test_background_close_releases_dataset(devices):
    mesh = create_mesh(MeshConfig(data=8))
    ds = _ds()
    it = prefetch_to_device(ds, mesh, size=2, background=True)
    next(it)
    it.close()
    # After close the producer is stopped; restoring and re-pulling from
    # the dataset must be race-free and deterministic.
    ds.restore({"step": 0})
    first = next(ds)
    ds.restore({"step": 0})
    again = next(ds)
    np.testing.assert_array_equal(first["image"], again["image"])


def test_background_propagates_errors(devices):
    mesh = create_mesh(MeshConfig(data=8))

    class Boom:
        element_spec = {}

        def __iter__(self):
            return self

        def __next__(self):
            raise RuntimeError("boom in pipeline")

        def state(self):
            return {}

    it = prefetch_to_device(Boom(), mesh, size=2, background=True)
    import pytest

    with pytest.raises(RuntimeError, match="boom"):
        next(it)
