"""scripts/launch_local_cluster.py — the localhost fake-cluster tool.

Drives the real script end-to-end: two jax.distributed processes train
the synthetic-LeNet config through the DCN code path and must both exit
0; a bad config must fail fast (nonzero exit, no hang) even though the
healthy peer is blocked in a collective.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "launch_local_cluster.py")


def _run(workdir, *train_args, timeout=300):
    return subprocess.run(
        [sys.executable, SCRIPT, "--procs", "2", "--workdir", str(workdir),
         "--", "--config", "configs/lenet_mnist.yaml", *train_args],
        capture_output=True, text=True, timeout=timeout)


def test_two_process_train(tmp_path):
    r = _run(tmp_path,
             "--set", "train.total_steps=4",
             "--set", "train.log_interval=2",
             "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
             "--set", "checkpoint.directory=",
             "--set", "mesh.data=-1")
    assert r.returncode == 0, r.stderr
    for i in (0, 1):
        log = (tmp_path / f"worker-{i}.log").read_text()
        assert "step 4" in log, log[-2000:]


def test_worker_failure_surfaces_fast(tmp_path):
    # Unknown config key: every worker dies at startup; the launcher must
    # exit nonzero (not hang waiting on worker 0) and name a failed worker.
    r = _run(tmp_path, "--set", "train.totl_steps=5", timeout=120)
    assert r.returncode != 0
    assert "exited" in r.stderr
