"""scripts/launch_local_cluster.py — the localhost fake-cluster tool.

Fast tier: argument parsing, log-tail forensics, bind-race detection and
the port-retry relaunch loop, driven in-process with a stubbed
``spawn_gang`` (no gang, no JAX).

Slow tier drives the real script end-to-end: two jax.distributed
processes train the synthetic-LeNet config through the DCN code path and
must both exit 0; a bad config must fail fast (nonzero exit, no hang)
even though the healthy peer is blocked in a collective.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts import launch_local_cluster as llc  # noqa: E402

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "launch_local_cluster.py")


# ---------------------------------------------------------------------------
# Fast tier: parsing + port-retry machinery, no subprocess gang
# ---------------------------------------------------------------------------


class TestParseArgs:
    def test_separator_stripped(self):
        args = llc.parse_args(["--procs", "3", "--", "--config", "c.yaml"])
        assert args.procs == 3
        assert args.train_args == ["--config", "c.yaml"]
        assert args.port_retries == llc.PORT_RETRIES

    def test_missing_train_args_errors(self):
        with pytest.raises(SystemExit):
            llc.parse_args(["--procs", "2"])

    def test_bad_proc_count_errors(self):
        with pytest.raises(SystemExit):
            llc.parse_args(["--procs", "0", "--", "--config", "c.yaml"])

    def test_port_retries_flag(self):
        args = llc.parse_args(["--port-retries", "5", "--", "x"])
        assert args.port_retries == 5


class TestLogForensics:
    def test_log_tail_reads_last_bytes(self, tmp_path):
        p = tmp_path / "w.log"
        p.write_text("a" * 100 + "THE-END")
        assert llc.log_tail(str(p), max_bytes=10).endswith("THE-END")

    def test_log_tail_unreadable_is_empty(self, tmp_path):
        assert llc.log_tail(str(tmp_path / "missing.log")) == ""

    def test_bind_failure_signatures(self):
        assert llc.is_bind_failure("RuntimeError: Address already in use")
        assert llc.is_bind_failure("coordinator FAILED TO BIND to port")
        assert llc.is_bind_failure("[Errno 98] bind failed")
        assert not llc.is_bind_failure("ValueError: bad mesh")
        assert not llc.is_bind_failure("")


class _FakeProc:
    """Just enough Popen for _wait_gang/_reap: exits immediately."""

    def __init__(self, rc):
        self.returncode = None
        self._rc = rc
        self.pid = 0

    def poll(self):
        self.returncode = self._rc
        return self._rc

    def wait(self, timeout=None):
        return self.poll()

    def terminate(self):
        pass

    def kill(self):
        pass


def _stub_spawn(tmp_path, rcs_by_attempt, log_text_by_attempt, record):
    """A spawn_gang stub: writes the scripted worker-0 log and returns
    FakeProcs with the scripted exit codes."""
    def spawn(train_args, *, procs, devices_per_proc, workdir, port,
              base_env=None):
        i = min(len(record["ports"]), len(rcs_by_attempt) - 1)
        record["ports"].append(port)
        os.makedirs(workdir, exist_ok=True)
        with open(llc.log_path(workdir, 0), "w") as fh:
            fh.write(log_text_by_attempt[i])
        return [_FakeProc(rc) for rc in rcs_by_attempt[i]], []
    return spawn


class TestPortRetry:
    def test_bind_race_relaunches_on_fresh_port(self, tmp_path, monkeypatch):
        record = {"ports": []}
        monkeypatch.setattr(llc, "spawn_gang", _stub_spawn(
            tmp_path,
            [[1, 0], [0, 0]],
            ["Address already in use", "ok"], record))
        rc = llc.main(["--procs", "2", "--workdir", str(tmp_path),
                       "--", "--config", "c.yaml"])
        assert rc == 0
        assert len(record["ports"]) == 2
        assert len(set(record["ports"])) == 2  # a FRESH port per attempt

    def test_retries_exhausted_reports_failure(self, tmp_path, monkeypatch,
                                               capsys):
        record = {"ports": []}
        monkeypatch.setattr(llc, "spawn_gang", _stub_spawn(
            tmp_path, [[1, 0]], ["failed to bind"], record))
        rc = llc.main(["--procs", "2", "--workdir", str(tmp_path),
                       "--port-retries", "2", "--", "--config", "c.yaml"])
        assert rc == 1
        assert len(record["ports"]) == 2
        err = capsys.readouterr().err
        assert "worker 0 exited 1" in err  # log tail surfaced
        assert "failed to bind" in err

    def test_real_failure_is_not_retried(self, tmp_path, monkeypatch,
                                         capsys):
        record = {"ports": []}
        monkeypatch.setattr(llc, "spawn_gang", _stub_spawn(
            tmp_path, [[1, 0]], ["ValueError: bad mesh"], record))
        rc = llc.main(["--procs", "2", "--workdir", str(tmp_path),
                       "--", "--config", "c.yaml"])
        assert rc == 1
        assert len(record["ports"]) == 1
        assert "ValueError: bad mesh" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Slow tier: the real 2-process gang end-to-end
# ---------------------------------------------------------------------------


def _run(workdir, *train_args, procs=2, devices_per_proc=2, timeout=300):
    return subprocess.run(
        [sys.executable, SCRIPT, "--procs", str(procs),
         "--devices-per-proc", str(devices_per_proc),
         "--workdir", str(workdir),
         "--", "--config", "configs/lenet_mnist.yaml", *train_args],
        capture_output=True, text=True, timeout=timeout)


@pytest.mark.slowest
@pytest.mark.slow
def test_two_process_train(tmp_path, gang_capability):
    r = _run(tmp_path,
             "--set", "train.total_steps=4",
             "--set", "train.log_interval=2",
             "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
             "--set", "checkpoint.directory=",
             "--set", "mesh.data=-1")
    assert r.returncode == 0, r.stderr
    # Step-metric lines are chief-only; every worker reaches the end.
    chief = (tmp_path / "worker-0.log").read_text()
    assert "step 4" in chief, chief[-2000:]
    assert "2 local / 4 global devices" in chief, chief[-2000:]
    for i in (0, 1):
        log = (tmp_path / f"worker-{i}.log").read_text()
        assert "final train metrics" in log, log[-2000:]


@pytest.mark.slow
def test_worker_failure_surfaces_fast(tmp_path):
    # Unknown config key: every worker dies at startup; the launcher must
    # exit nonzero (not hang waiting on worker 0) and name a failed worker.
    r = _run(tmp_path, "--set", "train.totl_steps=5", timeout=120)
    assert r.returncode != 0
    assert "exited" in r.stderr


def _step_metrics(log: str, step: int) -> str:
    """The deterministic metric fields of a worker's step-N log line
    (loss/top1/grad_norm — drops wall-clock-dependent throughput/timing)."""
    m = re.search(
        rf"step {step}: (grad_norm=\S+) (learning_rate=\S+) (loss=\S+) "
        rf"(top1=\S+) (top5=\S+)", log)
    assert m, f"no step-{step} metrics line:\n{log[-2000:]}"
    return " ".join(m.groups())


@pytest.mark.slowest
@pytest.mark.slow
def test_two_process_native_input_ckpt_resume(tmp_path, gang_capability):
    """The north-star deployment shape across PROCESS boundaries (VERDICT
    r3 missing #4): per-process TFRecord file sharding + native C++
    decode + producer-thread async infeed, checkpointed mid-run and
    relaunched — the resumed run must reproduce the unbroken control's
    step-8 metrics exactly. 8 steps over a 4-batch/host epoch also rolls
    the native reader across an epoch boundary."""
    from conftest import write_imagenet_records

    tree = tmp_path / "records"
    # 4 shards so each of the 2 processes gets its own file subset
    # (data/imagenet.py shards files per process).
    write_imagenet_records(tree, counts=(16,) * 4, size=(48, 40),
                           label_fn=lambda n: (n % 100) + 1)
    data_args = (
        "--set", "data.name=imagenet",
        "--set", f"data.data_dir={tree}",
        "--set", "data.use_native_reader=true",
        "--set", "data.async_infeed=true",
        "--set", "data.global_batch_size=16",
        "--set", "data.image_size=32",
        "--set", "data.shuffle_buffer=16",
        "--set", "model.name=resnet18_cifar",
        "--set", "model.space_to_depth_stem=false",
        "--set", "model.dtype=float32",
        # Labels span [0, 64) — the head must cover them (an
        # out-of-range integer-label CE gather fills NaN into the loss
        # metric while grads stay finite: NaN-guard fires, run dies).
        "--set", "model.num_classes=100",
        "--set", "data.num_classes=100",
        "--set", "optimizer.learning_rate=0.001",
        "--set", "optimizer.grad_clip_norm=1.0",
        "--set", "train.log_interval=4",
        "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
        "--set", "mesh.data=-1",
    )
    # Control: 8 unbroken steps.
    ctrl_dir = tmp_path / "ctrl"
    r = _run(tmp_path / "w-ctrl", *data_args,
             "--set", "train.total_steps=8",
             "--set", f"checkpoint.directory={ctrl_dir}", timeout=600)
    assert r.returncode == 0, r.stderr
    want = _step_metrics(
        (tmp_path / "w-ctrl" / "worker-0.log").read_text(), 8)

    # Broken run: checkpoint at step 4 (final force-save), relaunch to 8.
    ck_dir = tmp_path / "ck"
    r = _run(tmp_path / "w-leg1", *data_args,
             "--set", "train.total_steps=4",
             "--set", f"checkpoint.directory={ck_dir}", timeout=600)
    assert r.returncode == 0, r.stderr
    r = _run(tmp_path / "w-leg2", *data_args,
             "--set", "train.total_steps=8",
             "--set", f"checkpoint.directory={ck_dir}", timeout=600)
    assert r.returncode == 0, r.stderr
    for i in (0, 1):
        log = (tmp_path / "w-leg2" / f"worker-{i}.log").read_text()
        assert "Restored checkpoint at step 4" in log, log[-2000:]
    got = _step_metrics(
        (tmp_path / "w-leg2" / "worker-0.log").read_text(), 8)
    # Bit-exact resume: the native readers on both processes re-shard the
    # same files, fast-skip to the snapshot position and replay the
    # identical shuffled/augmented record stream.
    assert got == want


@pytest.mark.slowest
@pytest.mark.slow
def test_four_process_zero1_ckpt_resume(tmp_path, gang_capability):
    """DCN-path evidence at 4 process boundaries (VERDICT r2 item 6): a
    2×2 data×fsdp mesh with ZeRO-1 opt-state sharding spans all four
    processes; a run checkpointed at step 4 and relaunched to step 8
    must reproduce the unbroken 8-step run's metrics exactly — sharded
    optimizer state, collectives and the iterator all resume across the
    process boundaries."""
    mesh_args = (
        "--set", "mesh.data=2", "--set", "mesh.fsdp=2",
        "--set", "optimizer.name=adam", "--set", "optimizer.learning_rate=0.01",
        "--set", "optimizer.shard_opt_state=true",
        "--set", "data.global_batch_size=64",
        "--set", "train.log_interval=4",
        "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
    )
    ctrl_dir = tmp_path / "ctrl"
    # Control: 8 unbroken steps.
    r = _run(tmp_path / "w-ctrl", *mesh_args,
             "--set", "train.total_steps=8",
             "--set", f"checkpoint.directory={ctrl_dir}",
             procs=4, devices_per_proc=1)
    assert r.returncode == 0, r.stderr
    ctrl_log = (tmp_path / "w-ctrl" / "worker-0.log").read_text()
    want = _step_metrics(ctrl_log, 8)

    # Broken run: 4 steps (final force-save), then relaunch to 8.
    ck_dir = tmp_path / "ck"
    r = _run(tmp_path / "w-leg1", *mesh_args,
             "--set", "train.total_steps=4",
             "--set", f"checkpoint.directory={ck_dir}",
             procs=4, devices_per_proc=1)
    assert r.returncode == 0, r.stderr
    r = _run(tmp_path / "w-leg2", *mesh_args,
             "--set", "train.total_steps=8",
             "--set", f"checkpoint.directory={ck_dir}",
             procs=4, devices_per_proc=1)
    assert r.returncode == 0, r.stderr
    for i in range(4):
        log = (tmp_path / "w-leg2" / f"worker-{i}.log").read_text()
        assert "Restored checkpoint at step 4" in log, log[-2000:]
    got = _step_metrics(
        (tmp_path / "w-leg2" / "worker-0.log").read_text(), 8)
    assert got == want  # bit-exact resume across 4 process boundaries


@pytest.mark.slowest
@pytest.mark.slow
def test_two_process_ring_attention(tmp_path, gang_capability):
    """Long-context over the PROCESS boundary: 2 processes x 1 device
    with mesh.seq=2 puts the two sequence shards in different processes,
    so every ring ppermute (K/V and mask rotation) and the final merge
    cross the jax.distributed transport — the DCN shape of the
    long-context story, which the single-process 8-device ring tests
    cannot exercise. Both workers must finish 4 steps with finite loss."""
    r = _run(tmp_path,
             "--set", "model.name=bert",
             "--set", "model.vocab_size=256",
             "--set", "model.hidden_size=32", "--set", "model.num_layers=2",
             "--set", "model.num_heads=2", "--set", "model.mlp_dim=64",
             "--set", "model.max_seq_len=256", "--set", "model.dtype=float32",
             "--set", "model.attention_impl=ring",
             "--set", "data.name=synthetic_mlm",
             "--set", "data.vocab_size=256", "--set", "data.seq_len=256",
             "--set", "data.global_batch_size=4",
             "--set", "train.total_steps=4",
             "--set", "train.log_interval=2",
             "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
             "--set", "checkpoint.directory=",
             "--set", "mesh.data=1", "--set", "mesh.seq=2",
             procs=2, devices_per_proc=1, timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]
    chief = (tmp_path / "worker-0.log").read_text()
    assert "1 local / 2 global devices" in chief, chief[-2000:]
    m = re.search(r"step 4: .*loss=(\S+)", chief)
    assert m, chief[-2000:]
    import math

    assert math.isfinite(float(m.group(1))), f"loss={m.group(1)}"
    for i in (0, 1):
        log = (tmp_path / f"worker-{i}.log").read_text()
        assert "final train metrics" in log, log[-2000:]
