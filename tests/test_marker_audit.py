"""Structural audits: pytest markers and telemetry-kind coverage.

Marker audit — subprocess training drills must be tier-2. Tier-1
(``-m "not slow"``) is the under-15-minute gate every PR runs; a
subprocess drill that launches real training children (the DRIVER
template of tests/test_fault_tolerance.py) costs minutes each and belongs
behind the ``slow`` marker. This audit makes that a checked property
instead of a review convention, so new drills (e.g. the async crash
drills) can't silently land in tier-1.

Telemetry audit — every ``KIND_*`` constant in core/telemetry.py must be
rolled up by ``summarize_events``/``format_run_summary`` and referenced
by at least one test: an event kind nothing summarizes is invisible in
exactly the post-mortems it was added for, and one no test references
can silently rot (ISSUE 6 satellite).

Pure ast — no test collection, no imports of the audited modules.
"""

import ast
import pathlib

TESTS_DIR = pathlib.Path(__file__).resolve().parent
TELEMETRY_PY = (TESTS_DIR.parent / "distributed_tensorflow_framework_tpu"
                / "core" / "telemetry.py")

# Module-level names that mark a file as a subprocess-training-drill
# module: the DRIVER template itself, importing it from the fault
# tolerance suite, or any specialized sibling template named *_DRIVER
# (e.g. the recovery drills' RECOVERY_DRIVER).
_DRIVER_NAME = "DRIVER"


def _is_driver_name(name: str) -> bool:
    return name == _DRIVER_NAME or name.endswith("_" + _DRIVER_NAME)


def _decorator_marks(fn: ast.FunctionDef) -> set[str]:
    """Names of pytest.mark.* decorators on a test function."""
    marks: set[str] = set()
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        # pytest.mark.<name> is Attribute(Attribute(Name('pytest'),'mark'),name)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"):
            marks.add(node.attr)
    return marks


def _defines_or_imports_driver(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and _is_driver_name(t.id):
                    return True
        if isinstance(node, ast.ImportFrom):
            if any(_is_driver_name(a.name) for a in node.names):
                return True
    return False


def _uses_driver(fn: ast.FunctionDef) -> bool:
    """Whether the function references DRIVER (directly or via a local
    ``from ... import DRIVER``) — the signature of launching a real
    training child."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and _is_driver_name(node.id):
            return True
        if isinstance(node, ast.ImportFrom) and \
                any(_is_driver_name(a.name) for a in node.names):
            return True
    return False


def test_subprocess_drills_carry_slow_marker():
    offenders = []
    for path in sorted(TESTS_DIR.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        module_wide = _defines_or_imports_driver(tree)
        for node in tree.body:
            if not (isinstance(node, ast.FunctionDef)
                    and node.name.startswith("test_")):
                continue
            if not (module_wide or _uses_driver(node)):
                continue
            if "slow" not in _decorator_marks(node):
                offenders.append(f"{path.name}::{node.name}")
    assert not offenders, (
        "subprocess training drills missing @pytest.mark.slow (they launch "
        f"real training children and must stay out of tier-1): {offenders}"
    )


def _telemetry_kind_names() -> list[str]:
    tree = ast.parse(TELEMETRY_PY.read_text())
    names = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith("KIND_"):
                    names.append(t.id)
    return names


def _function_source(tree: ast.Module, source: str, name: str) -> str:
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return ast.get_source_segment(source, node) or ""
    raise AssertionError(f"{name} not found in {TELEMETRY_PY}")


def test_every_telemetry_kind_is_summarized():
    """Each KIND_* must appear (by constant name) in the combined source
    of summarize_events + format_run_summary — the rollup surface
    scripts/analyze_trace.py prints."""
    source = TELEMETRY_PY.read_text()
    tree = ast.parse(source)
    rollup_src = (_function_source(tree, source, "summarize_events")
                  + _function_source(tree, source, "format_run_summary"))
    kinds = _telemetry_kind_names()
    assert len(kinds) >= 20, kinds  # self-check: extraction saw them
    missing = [k for k in kinds if k not in rollup_src]
    assert not missing, (
        "telemetry kinds with no summarize_events/format_run_summary "
        f"rollup: {missing}"
    )


def test_every_telemetry_kind_is_referenced_by_a_test():
    corpus = "".join(
        p.read_text() for p in sorted(TESTS_DIR.glob("test_*.py")))
    missing = [k for k in _telemetry_kind_names() if k not in corpus]
    assert not missing, f"telemetry kinds no test references: {missing}"


def test_audit_sees_the_known_drills():
    """Self-check: the audit must actually recognize the existing drill
    modules — an audit that matches nothing passes vacuously."""
    ft = ast.parse((TESTS_DIR / "test_fault_tolerance.py").read_text())
    assert _defines_or_imports_driver(ft)
    ac = ast.parse((TESTS_DIR / "test_async_ckpt.py").read_text())
    drill = next(n for n in ac.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "test_supervised_crash_in_save_drill_async")
    assert _uses_driver(drill)
    assert {"slow", "slowest"} <= _decorator_marks(drill)
    # Specialized *_DRIVER templates count too (recovery-ladder drills).
    rd = ast.parse((TESTS_DIR / "test_recovery_drills.py").read_text())
    assert _defines_or_imports_driver(rd)


def test_serve_kinds_are_audited():
    """Self-check that the kind audit actually covers the serving SLO
    events: all five KIND_SERVE_* constants must be extracted (a rename
    that drops the prefix would silently fall out of the serving
    rollup's audit trail)."""
    serve_kinds = {k for k in _telemetry_kind_names()
                   if k.startswith("KIND_SERVE_")}
    assert serve_kinds >= {
        "KIND_SERVE_REQUEST", "KIND_SERVE_BATCH", "KIND_SERVE_QUEUE",
        "KIND_SERVE_LATENCY", "KIND_SERVE_RECOMPILE",
    }, serve_kinds
    assert len(serve_kinds) >= 5


def test_observability_kinds_are_audited():
    """Self-check for the goodput/memory layer (ISSUE 10): both kinds
    must be extracted by the audit, so the summarized-and-test-referenced
    requirements above actually bind them — a rename that drops them
    from telemetry.py would otherwise fall out silently."""
    kinds = set(_telemetry_kind_names())
    assert {"KIND_GOODPUT", "KIND_MEMORY"} <= kinds, kinds


COLLECTIVES_PY = (TESTS_DIR.parent / "distributed_tensorflow_framework_tpu"
                  / "parallel" / "collectives.py")


def _tally_total_fields() -> list[str]:
    """The TALLY_TOTAL_FIELDS tuple from parallel/collectives.py, by ast
    (same no-import discipline as the KIND_* audit)."""
    tree = ast.parse(COLLECTIVES_PY.read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "TALLY_TOTAL_FIELDS":
                    return [ast.literal_eval(e) for e in node.value.elts]
    raise AssertionError(f"TALLY_TOTAL_FIELDS not found in {COLLECTIVES_PY}")


def test_every_tally_total_field_is_rolled_up():
    """Each grand-total field the CollectiveTally emits must surface in
    the telemetry rollup (summarize_events/format_run_summary source) —
    a total the post-mortem summary never prints silently rots, exactly
    like an unsummarized KIND_*."""
    fields = _tally_total_fields()
    assert "total_bytes" in fields and "total_logical_bytes" in fields
    source = TELEMETRY_PY.read_text()
    tree = ast.parse(source)
    rollup_src = (_function_source(tree, source, "summarize_events")
                  + _function_source(tree, source, "format_run_summary"))
    missing = [f for f in fields if f not in rollup_src]
    assert not missing, (
        f"CollectiveTally total fields with no telemetry rollup: {missing}")


def test_every_tally_total_field_is_referenced_by_a_test():
    corpus = "".join(
        p.read_text() for p in sorted(TESTS_DIR.glob("test_*.py")))
    missing = [f for f in _tally_total_fields() if f not in corpus]
    assert not missing, f"tally total fields no test references: {missing}"
