"""Structural audits: pytest markers and telemetry-kind coverage.

Thin shim (ISSUE 11): the ast logic that used to live here was promoted
into the graftcheck suite — the ``slow-marker`` and
``telemetry-kind-coverage`` passes in tools/graftcheck/ast_passes.py —
where it also runs via ``python scripts/graftcheck.py`` and the tier-1
self-audit in tests/test_graftcheck.py. These tests keep the original
one-property-per-test entry points (so a regression names the property,
not just "graftcheck failed") by delegating to the shared pass
implementations instead of duplicating them.
"""

import ast
import pathlib

from tools.graftcheck import ast_passes
from tools.graftcheck.context import RepoContext
from tools.graftcheck.findings import SEVERITY_INTERNAL

TESTS_DIR = pathlib.Path(__file__).resolve().parent
ROOT = TESTS_DIR.parent
TELEMETRY_PY = (ROOT / "distributed_tensorflow_framework_tpu"
                / "core" / "telemetry.py")


def _slow_marker_findings():
    return ast_passes.slow_marker_pass(RepoContext(ROOT))


def _telemetry_findings():
    return ast_passes.telemetry_coverage_pass(RepoContext(ROOT))


def _kind_names() -> set[str]:
    tree = ast.parse(TELEMETRY_PY.read_text())
    return set(ast_passes._module_const_assigns(tree, "KIND_"))


def test_subprocess_drills_carry_slow_marker():
    findings = _slow_marker_findings()
    assert not findings, [f.message for f in findings]


def test_every_telemetry_kind_is_summarized():
    """Each KIND_* must appear (by constant name) in the combined source
    of summarize_events + format_run_summary — the rollup surface
    scripts/analyze_trace.py prints."""
    bad = [f for f in _telemetry_findings()
           if "rollup" in f.message and "KIND_" in f.message]
    assert not bad, [f.message for f in bad]


def test_every_telemetry_kind_is_referenced_by_a_test():
    bad = [f for f in _telemetry_findings()
           if "referenced by no test" in f.message and "KIND_" in f.message]
    assert not bad, [f.message for f in bad]


def test_telemetry_audit_is_not_vacuous():
    """The pass carries its own vacuity guards (>= 20 kinds extracted,
    rollup functions found) as internal-error findings — none may fire."""
    internal = [f for f in _telemetry_findings()
                if f.severity == SEVERITY_INTERNAL]
    assert not internal, [f.message for f in internal]
    assert len(_kind_names()) >= 20


def test_audit_sees_the_known_drills():
    """Self-check: the audit must actually recognize the existing drill
    modules — an audit that matches nothing passes vacuously. (The pass
    itself re-checks test_fault_tolerance.py recognition as an
    internal-error finding; this pins the full known-drill set.)"""
    ft = ast.parse((TESTS_DIR / "test_fault_tolerance.py").read_text())
    assert ast_passes.module_defines_driver(ft)
    ac = ast.parse((TESTS_DIR / "test_async_ckpt.py").read_text())
    drill = next(n for n in ac.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "test_supervised_crash_in_save_drill_async")
    assert ast_passes.function_uses_driver(drill)
    assert {"slow", "slowest"} <= ast_passes._decorator_marks(drill)
    # Specialized *_DRIVER templates count too (recovery-ladder drills).
    rd = ast.parse((TESTS_DIR / "test_recovery_drills.py").read_text())
    assert ast_passes.module_defines_driver(rd)


def test_serve_kinds_are_audited():
    """Self-check that the kind audit actually covers the serving SLO
    events: all five KIND_SERVE_* constants must be extracted (a rename
    that drops the prefix would silently fall out of the serving
    rollup's audit trail)."""
    serve_kinds = {k for k in _kind_names() if k.startswith("KIND_SERVE_")}
    assert serve_kinds >= {
        "KIND_SERVE_REQUEST", "KIND_SERVE_BATCH", "KIND_SERVE_QUEUE",
        "KIND_SERVE_LATENCY", "KIND_SERVE_RECOMPILE",
    }, serve_kinds
    assert len(serve_kinds) >= 5


def test_observability_kinds_are_audited():
    """Self-check for the goodput/memory layer (ISSUE 10): both kinds
    must be extracted by the audit, so the summarized-and-test-referenced
    requirements above actually bind them — a rename that drops them
    from telemetry.py would otherwise fall out silently."""
    assert {"KIND_GOODPUT", "KIND_MEMORY"} <= _kind_names()


def test_every_tally_total_field_is_rolled_up():
    """Each grand-total field the CollectiveTally emits must surface in
    the telemetry rollup — a total the post-mortem summary never prints
    silently rots, exactly like an unsummarized KIND_*. The pass also
    pins total_bytes/total_logical_bytes staying in TALLY_TOTAL_FIELDS
    (internal-error finding on loss)."""
    bad = [f for f in _telemetry_findings()
           if "CollectiveTally total field" in f.message
           and "rollup" in f.message]
    assert not bad, [f.message for f in bad]


def test_every_tally_total_field_is_referenced_by_a_test():
    bad = [f for f in _telemetry_findings()
           if "CollectiveTally total field" in f.message
           and "referenced by no test" in f.message]
    assert not bad, [f.message for f in bad]
