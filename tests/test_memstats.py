"""HBM memory telemetry (core/memstats.py) + bench headroom annotation.

On the CPU backend ``device.memory_stats()`` returns nothing, so the
snapshot must fall back to host RSS (tagged ``source_kind=host_rss``)
while ``compiled.memory_analysis()`` still yields the static program
budget — the pair of rulers the bench's ``hbm_peak_bytes_per_chip`` /
``hbm_headroom_frac`` annotation (bench.py) is built on. Real chips flip
``source_kind`` to ``device_memory_stats`` with no code change.
"""

import jax
import jax.numpy as jnp
import pytest

import bench
from distributed_tensorflow_framework_tpu.core import memstats, telemetry


def test_host_rss_bytes_sane():
    current, peak = memstats.host_rss_bytes()
    assert current > 0 and peak > 0
    assert peak >= 1024 * 1024  # a python process is at least a MiB


def test_device_snapshot_cpu_falls_back_to_rss(devices):
    snap = memstats.device_memory_snapshot(devices)
    assert snap["device_count"] == 8
    assert snap["bytes_in_use"] > 0
    assert snap["peak_bytes_in_use"] >= snap["bytes_in_use"] or \
        snap["peak_bytes_in_use"] > 0
    # CPU backend: no allocator stats → the host-RSS ruler, explicitly
    # labeled so readers never mistake RSS for HBM.
    assert snap["source_kind"] in ("host_rss", "device_memory_stats")
    if snap["source_kind"] == "host_rss":
        assert snap["devices"] == []


def test_compiled_memory_analysis_on_cpu():
    @jax.jit
    def f(x):
        return (x @ x.T).sum()

    x = jnp.ones((64, 64), jnp.float32)
    compiled = f.lower(x).compile()
    ana = memstats.compiled_memory_analysis(compiled)
    assert ana is not None
    assert ana["argument_bytes"] >= 64 * 64 * 4
    assert ana["peak_bytes_est"] > 0
    assert ana["peak_bytes_est"] == (
        ana.get("argument_bytes", 0) + ana.get("output_bytes", 0)
        + ana.get("temp_bytes", 0) + ana.get("generated_code_bytes", 0))


def test_monitor_sample_emits_valid_memory_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="mem")
    mon = memstats.MemoryMonitor(w, interval_s=1e9, source="train")
    assert mon.maybe_sample(step=1) is None  # interval not elapsed
    mon.sample(step=2, final=True)
    w.close()
    evs = list(telemetry.read_events(
        path, kind=telemetry.KIND_MEMORY, strict=True))
    assert len(evs) == 1
    ev = evs[0]
    assert ev["metrics"]["bytes_in_use"] > 0
    assert ev["extra"]["source"] == "train"
    assert ev["extra"]["final"] is True


def test_monitor_capture_compiled_emits_analysis(tmp_path):
    @jax.jit
    def f(x):
        return x * 2.0

    compiled = f.lower(jnp.ones((8, 8))).compile()
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="mem")
    mon = memstats.MemoryMonitor(w, source="train")
    ana = mon.capture_compiled(compiled, label="train_step")
    w.close()
    assert ana is not None
    (ev,) = telemetry.read_events(
        path, kind=telemetry.KIND_MEMORY, strict=True)
    assert ev["extra"]["source_kind"] == "memory_analysis"
    assert ev["extra"]["program"] == "train_step"
    assert ev["extra"]["analysis"]["peak_bytes_est"] > 0
    assert ev["metrics"]["peak_bytes_est"] == ana["peak_bytes_est"]


def test_snapshot_no_emit():
    mon = memstats.MemoryMonitor(None)
    snap = mon.snapshot()  # the /healthz path: sample without a writer
    assert snap["bytes_in_use"] > 0


# ------------------------------------------------- bench annotation ----


def test_chip_hbm_capacity_known_and_fallback():
    assert bench.chip_hbm_capacity("TPU v5e") == 16 * bench.GIB
    assert bench.chip_hbm_capacity("TPU v5p") == 95 * bench.GIB
    cap = bench.chip_hbm_capacity("cpu")  # unknown chip → host RAM
    assert cap is None or cap > 0


def test_chip_peaks_carry_capacity():
    for chip, peak in bench.CHIP_PEAKS.items():
        assert len(peak) == 3, chip
        assert peak[2] >= 8 * bench.GIB, chip


def test_annotate_memory_prefers_device_stats():
    out = {}
    result = {"memory": {"peak_bytes_in_use": 4 * bench.GIB,
                         "source_kind": "device_memory_stats",
                         "analysis": {"peak_bytes_est": 999}}}
    bench._annotate_memory(out, result, "TPU v5e", 8)
    assert out["hbm_peak_bytes_per_chip"] == 4 * bench.GIB
    assert out["hbm_peak_source"] == "device_memory_stats"
    assert out["hbm_capacity_bytes_per_chip"] == 16 * bench.GIB
    assert out["hbm_headroom_frac"] == pytest.approx(0.75)


def test_annotate_memory_cpu_uses_analysis_per_chip():
    out = {}
    result = {"memory": {"peak_bytes_in_use": 123456,
                         "source_kind": "host_rss",
                         "analysis": {"peak_bytes_est": 8 * 1024}}}
    bench._annotate_memory(out, result, "cpu", 8)
    # Static whole-program estimate attributed evenly per chip.
    assert out["hbm_peak_bytes_per_chip"] == 1024
    assert out["hbm_peak_source"] == "memory_analysis"
    if "hbm_headroom_frac" in out:
        assert out["hbm_headroom_frac"] <= 1.0


def test_annotate_memory_rss_fallback_without_analysis():
    out = {}
    result = {"memory": {"peak_bytes_in_use": 2 * bench.GIB,
                         "source_kind": "host_rss"}}
    bench._annotate_memory(out, result, "cpu", 1)
    assert out["hbm_peak_bytes_per_chip"] == 2 * bench.GIB
    assert out["hbm_peak_source"] == "host_rss"


def test_annotate_memory_noop_without_data():
    out = {}
    bench._annotate_memory(out, {}, "TPU v5e", 8)
    assert out == {}


def test_annotate_roofline_still_unpacks_3_tuple():
    """The roofline annotation must keep working now that CHIP_PEAKS
    rows carry a third (capacity) element."""
    out = {}
    result = {"sec_per_step": 0.1, "flops_per_step": 1e12,
              "bytes_per_step": 1e10}
    bench._annotate_roofline(out, result, "TPU v5e", 1)
    assert out["tflops_per_sec"] == pytest.approx(10.0)
    assert "mfu" in out and "hbm_bw_util" in out
