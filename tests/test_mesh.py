"""Mesh construction tests (SURVEY.md §2 rows 1–2 replacement)."""

import pytest

from distributed_tensorflow_framework_tpu.core.config import MeshConfig
from distributed_tensorflow_framework_tpu.core.mesh import (
    batch_sharding,
    create_mesh,
    initialize_runtime,
)


def test_default_mesh_uses_all_devices(devices):
    mesh = create_mesh()
    assert mesh.devices.size == 8
    assert dict(mesh.shape) == {"data": 8, "fsdp": 1, "expert": 1, "pipe": 1,
                                "seq": 1, "model": 1}


def test_explicit_axes(devices):
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))
    assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "expert": 1, "pipe": 1,
                                "seq": 1, "model": 2}


def test_free_axis_inference(devices):
    mesh = create_mesh(MeshConfig(data=-1, model=2))
    assert mesh.shape["data"] == 4


def test_bad_shape_raises(devices):
    with pytest.raises(ValueError):
        create_mesh(MeshConfig(data=3, model=2))  # 6 != 8


def test_hybrid_shapes():
    from distributed_tensorflow_framework_tpu.core.mesh import hybrid_mesh_shapes

    sizes = {"data": 8, "fsdp": 2, "expert": 1, "pipe": 1, "seq": 1,
             "model": 4}
    ici, dcn = hybrid_mesh_shapes(sizes, 4)
    assert ici == {"data": 2, "fsdp": 2, "expert": 1, "pipe": 1, "seq": 1,
                   "model": 4}
    assert dcn == {"data": 4, "fsdp": 1, "expert": 1, "pipe": 1, "seq": 1,
                   "model": 1}
    # FSDP-dominant layout: slices spill onto fsdp when data can't cover.
    ici2, dcn2 = hybrid_mesh_shapes(
        {"data": 2, "fsdp": 8, "expert": 1, "pipe": 1, "seq": 1, "model": 1},
        4,
    )
    assert dcn2 == {"data": 2, "fsdp": 2, "expert": 1, "pipe": 1, "seq": 1,
                    "model": 1}
    assert ici2 == {"data": 1, "fsdp": 4, "expert": 1, "pipe": 1, "seq": 1,
                    "model": 1}
    with pytest.raises(ValueError, match="does not factor"):
        hybrid_mesh_shapes({"data": 3, "fsdp": 1, "expert": 1, "pipe": 1,
                            "seq": 1, "model": 1}, 4)


def test_runtime(devices):
    rt = initialize_runtime(MeshConfig(data=8))
    assert rt.is_chief
    assert rt.global_device_count == 8
    assert rt.data_parallel_size == 8
    sh = batch_sharding(rt.mesh)
    assert sh.spec == sh.spec  # constructible
